"""Typed, rate-limited clients — the L2 clientset.

Mirrors the reference's generated client stack (SURVEY.md C10/C11):
``Clientset.NewForConfig`` installs a token-bucket rate limiter and hands
out per-group typed clients (images/tf4.PNG); each typed client is built
from config defaults — group/version, API path, codec, user agent — then a
REST client (``setConfigDefaults`` → ``rest.RESTClientFor``, images/tf5.PNG
/ tf6.PNG). Here the transport is the in-memory :class:`ClusterStore`
(process-local today; the seam where a real apiserver transport would slot
in), but the client surface — create/get/list/update/update_status/delete/
watch per kind, every call metered — is the same contract the reference
says must be implemented per resource (k8s-operator.md:228).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from tfk8s_tpu import API_VERSION, GROUP, VERSION
from tfk8s_tpu.api.frozen import thaw
from tfk8s_tpu.client.ratelimit import TokenBucketRateLimiter
from tfk8s_tpu.client.store import AlreadyExists, ClusterStore, Watch


@dataclasses.dataclass
class RESTConfig:
    """Client configuration (the rest.Config analogue, images/tf6.PNG)."""

    qps: float = 50.0
    burst: int = 100
    user_agent: str = "tfk8s-tpu-operator"
    api_path: str = "/apis"
    group_version: str = API_VERSION


class TypedClient:
    """CRUD + watch for one kind in one namespace (the
    ``typed/tensorflow/v1alpha1/tfjob.go`` analogue, SURVEY.md C11)."""

    def __init__(
        self,
        store: ClusterStore,
        kind: str,
        namespace: Optional[str],
        limiter: TokenBucketRateLimiter,
    ):
        self._store = store
        self.kind = kind
        self.namespace = namespace
        self._limiter = limiter

    def _ns(self, obj: Any = None) -> str:
        if self.namespace is not None:
            return self.namespace
        if obj is not None:
            return obj.metadata.namespace
        return "default"

    def create(self, obj: Any) -> Any:
        self._limiter.accept()
        return self._do_create(obj)

    def _do_create(self, obj: Any) -> Any:
        """The unmetered create body — ``create``/``create_many`` meter
        around it; the recording fake overrides it to keep per-object
        action records and reactors working under batching."""
        if self.namespace is not None:
            obj.metadata.namespace = self.namespace
        return self._store.create(obj)

    def create_many(self, objs: List[Any]) -> List[Any]:
        """Create a batch under ONE rate-limiter acquire (a single
        reservation of ``len(objs)`` tokens — one sleep instead of one
        per object; the gang-pod creation path). AlreadyExists is
        skipped per object (idempotent, level-triggered create — the
        caller recomputes desired state next sync anyway). Returns the
        objects actually created."""
        if not objs:
            return []
        self._limiter.accept(len(objs))
        created: List[Any] = []
        for obj in objs:
            try:
                created.append(self._do_create(obj))
            except AlreadyExists:
                continue
        return created

    def get(self, name: str) -> Any:
        """Read one object. Returns a PRIVATE MUTABLE copy (copy-on-read
        at the client boundary): the store's frozen shared instance is
        thawed here, because typed-client readers are exactly the
        mutating clients — the kubelet's read-modify-write status loop,
        the event recorder's aggregation. Zero-copy shared reads are the
        lister/informer path."""
        self._limiter.accept()
        return thaw(self._store.get(self.kind, self._ns(), name))

    def list(self, label_selector: Optional[Dict[str, str]] = None) -> Tuple[List[Any], int]:
        """List (items, rv). Items from a local store are the SHARED
        frozen instances — read-only; thaw() any you need to edit."""
        self._limiter.accept()
        return self._store.list(self.kind, self.namespace, label_selector)

    def update(self, obj: Any) -> Any:
        self._limiter.accept()
        return self._store.update(obj)

    def update_status(self, obj: Any) -> Any:
        """Status-subresource write: only ``obj.status`` is applied, under
        the same optimistic-concurrency rules as update (over the wire
        this is the ``PUT .../{name}/status`` route)."""
        self._limiter.accept()
        return self._store.update_status(obj)

    def patch(self, name: str, patch: Dict[str, Any]) -> Any:
        """JSON merge-patch (wire-form keys): write only the fields you
        own; no resourceVersion needed, so concurrent writers touching
        disjoint fields never conflict (over the wire: ``PATCH
        .../{name}`` with application/merge-patch+json)."""
        self._limiter.accept()
        return self._store.patch(self.kind, self._ns(), name, patch)

    def patch_status(self, name: str, patch: Dict[str, Any]) -> Any:
        """Merge-patch confined to ``status`` (``PATCH .../{name}/status``).
        ``patch`` may be the full wire object or just ``{"status": ...}``;
        only its status applies."""
        self._limiter.accept()
        return self._store.patch(
            self.kind, self._ns(), name, patch, subresource="status"
        )

    def delete(self, name: str) -> Any:
        self._limiter.accept()
        return self._store.delete(self.kind, self._ns(), name)

    def watch(self, since_rv: Optional[int] = None) -> Watch:
        # Watches are long-lived streams, not discrete requests: one token
        # to open, none per event.
        self._limiter.accept()
        return self._store.watch(self.kind, since_rv)


class Clientset:
    """Per-kind typed clients sharing one rate limiter, built from a config
    — ``NewForConfig`` parity (images/tf4.PNG)."""

    def __init__(self, store: ClusterStore, config: Optional[RESTConfig] = None):
        self.config = config or RESTConfig()
        self._store = store
        self._limiter = TokenBucketRateLimiter(self.config.qps, self.config.burst)

    @classmethod
    def new_for_config(cls, store: ClusterStore, config: Optional[RESTConfig] = None) -> "Clientset":
        return cls(store, config)

    def tpujobs(self, namespace: Optional[str] = "default") -> TypedClient:
        return TypedClient(self._store, "TPUJob", namespace, self._limiter)

    def tpuserves(self, namespace: Optional[str] = "default") -> TypedClient:
        return TypedClient(self._store, "TPUServe", namespace, self._limiter)

    def pods(self, namespace: Optional[str] = "default") -> TypedClient:
        return TypedClient(self._store, "Pod", namespace, self._limiter)

    def services(self, namespace: Optional[str] = "default") -> TypedClient:
        return TypedClient(self._store, "Service", namespace, self._limiter)

    def generic(self, kind: str, namespace: Optional[str] = "default") -> TypedClient:
        return TypedClient(self._store, kind, namespace, self._limiter)

    def discovery(self) -> Dict[str, Any]:
        """Served group/version info (DiscoveryClient parity, images/tf4.PNG)."""
        return {
            "group": GROUP,
            "version": VERSION,
            "api_path": self.config.api_path,
            "kinds": ["TPUJob", "TPUServe", "Pod", "Service"],
        }
