"""Client-side rate limiting.

The reference's clientset installs ``flowcontrol.NewTokenBucketRateLimiter
(QPS, Burst)`` on every REST client (images/tf4.PNG at k8s-operator.md:235;
SURVEY.md C10/C16). Same construction here: a token bucket gating every
client call, plus the per-item backoff limiters the workqueue composes.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Hashable


class TokenBucketRateLimiter:
    """Classic token bucket: ``qps`` refill rate, ``burst`` capacity.
    ``accept(n)`` blocks until ``n`` tokens are available; ``try_accept()``
    doesn't block.

    ``accept`` is reservation-style (flowcontrol's ``WaitN``): the tokens
    are debited immediately — the balance may go negative — and the
    caller sleeps once for exactly the debt. One ``accept(n)`` is
    therefore a single batched wait, which is how a gang's n pod creates
    pay the rate limiter once instead of sleeping n times on the
    reconcile hot path; later callers queue behind the debt, preserving
    the overall rate."""

    def __init__(self, qps: float, burst: int, clock=time.monotonic, sleep=time.sleep):
        if qps <= 0:
            raise ValueError("qps must be > 0")
        self.qps = float(qps)
        self.burst = max(int(burst), 1)
        self._tokens = float(self.burst)
        self._last = clock()
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()

    def _refill(self) -> None:
        now = self._clock()
        self._tokens = min(self.burst, self._tokens + (now - self._last) * self.qps)
        self._last = now

    def try_accept(self) -> bool:
        with self._lock:
            self._refill()
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def try_accept_or_delay(self) -> float:
        """Admission-control shape: debit and return 0.0 when a token is
        available, else return (WITHOUT debiting or blocking) the seconds
        until one accrues — the Retry-After a shedding gateway puts on
        the 429 so clients back off for exactly the bucket's debt."""
        with self._lock:
            self._refill()
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return 0.0
            return (1.0 - self._tokens) / self.qps

    def accept(self, n: int = 1) -> None:
        if n <= 0:
            return
        with self._lock:
            self._refill()
            self._tokens -= float(n)
            wait = -self._tokens / self.qps if self._tokens < 0 else 0.0
        if wait > 0:
            self._sleep(wait)


class ItemExponentialFailureRateLimiter:
    """Per-item exponential backoff: ``base * 2^failures`` capped at ``cap``
    — the DefaultControllerRateLimiter's first half (k8s-operator.md:87)."""

    def __init__(self, base: float = 0.005, cap: float = 120.0):
        self.base = base
        self.cap = cap
        self._failures: Dict[Hashable, int] = {}
        self._lock = threading.Lock()

    def when(self, item: Hashable) -> float:
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
            return min(self.base * (2**n), self.cap)

    def forget(self, item: Hashable) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def retries(self, item: Hashable) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class BucketRateLimiter:
    """Overall-rate half of the default controller rate limiter: items are
    admitted at token-bucket pace regardless of per-item history."""

    def __init__(self, qps: float = 10.0, burst: int = 100, clock=time.monotonic):
        self.qps = qps
        self.burst = burst
        self._tokens = float(burst)
        self._last = clock()
        self._clock = clock
        self._lock = threading.Lock()

    def when(self, item: Hashable) -> float:
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst, self._tokens + (now - self._last) * self.qps)
            self._last = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return 0.0
            need = 1.0 - self._tokens
            self._tokens -= 1.0
            return need / self.qps

    def forget(self, item: Hashable) -> None:
        pass

    def retries(self, item: Hashable) -> int:
        return 0


class MaxOfRateLimiter:
    """Compose limiters, taking the worst (max) delay — the
    ``DefaultControllerRateLimiter()`` shape (k8s-operator.md:87)."""

    def __init__(self, *limiters):
        self.limiters = limiters

    def when(self, item: Hashable) -> float:
        return max(l.when(item) for l in self.limiters)

    def forget(self, item: Hashable) -> None:
        for l in self.limiters:
            l.forget(item)

    def retries(self, item: Hashable) -> int:
        return max(l.retries(item) for l in self.limiters)


def default_controller_rate_limiter() -> MaxOfRateLimiter:
    return MaxOfRateLimiter(
        ItemExponentialFailureRateLimiter(base=0.005, cap=16.0),
        BucketRateLimiter(qps=50.0, burst=500),
    )
