"""HTTP apiserver: serves a ClusterStore over REST — the out-of-process
L0 substrate.

The reference operator's whole point is talking to a *live* apiserver over
the network: kubeconfig → ``NewForConfig`` → rate-limited REST
(`/root/reference/k8s-operator.md:92-102`), resources exposed at
``/apis/<group>/<version>/namespaces/*/<plural>/...``
(`k8s-operator.md:33-34`), watches as long-lived streams feeding the
Reflector (images/informer1.png). This module is that process boundary:
the hermetic :class:`~tfk8s_tpu.client.store.ClusterStore` behind real
HTTP, so an operator in one process and a kubelet in another reconcile
the same cluster state over the wire (client/remote.py is the client
half).

Route table (JSON bodies; ``gv`` = ``tfk8s.dev/v1alpha1``):

====== ============================================== =====================
verb   path                                           store call
====== ============================================== =====================
GET    /apis/{gv}/namespaces/{ns}/{plural}            list(kind, ns)
GET    /apis/{gv}/{plural}                            list(kind, all-ns)
GET    /apis/{gv}/{plural}?watch=1&resourceVersion=N  watch(kind, N) stream
POST   /apis/{gv}/namespaces/{ns}/{plural}            create(obj)
GET    /apis/{gv}/namespaces/{ns}/{plural}/{name}     get(kind, ns, name)
PUT    /apis/{gv}/namespaces/{ns}/{plural}/{name}     update(obj)
PUT    .../{name}/status                              update(obj) (status)
DELETE /apis/{gv}/namespaces/{ns}/{plural}/{name}     delete(kind, ns, name)
GET    /apis                                          discovery doc
====== ============================================== =====================

Error mapping follows the real protocol: 404 NotFound, 409 AlreadyExists /
Conflict (distinguished by ``reason``), 410 Gone (watch window expired →
client must relist). Watch responses are newline-delimited JSON events
``{"type": "ADDED|MODIFIED|DELETED", "object": {...}}`` streamed until the
client disconnects, with periodic ``{"type": "HEARTBEAT"}`` lines so a dead
peer is detected and the server-side watch reclaimed.

Security (the part of the reference's client stack whose whole point is a
*secured* apiserver — ``rest.Config`` carries TLS + credentials,
`k8s-operator.md:93-97`, images/tf5-tf6): pass ``tls=TLSServerConfig(...)``
to serve HTTPS (optionally verifying client certs against a CA), and
``auth=AuthConfig(...)`` to require credentials. Authentication accepts a
``Authorization: Bearer <token>`` header (static-token-file model) or a
CA-verified client certificate (identity = cert CN). With auth enabled:
no/unknown credentials → **401 Unauthorized**; a read-only identity
attempting a write → **403 Forbidden**; ``/healthz`` stays open for
liveness probes. Without ``auth``, requests run as ``system:anonymous``
(the hermetic default).
"""

from __future__ import annotations

import json
import socketserver
import ssl
import threading
import time
import urllib.parse
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

from tfk8s_tpu import API_VERSION
from tfk8s_tpu.api import serde
from tfk8s_tpu.client.store import (
    AlreadyExists,
    ClusterStore,
    Conflict,
    Gone,
    Invalid,
    NotFound,
)
from tfk8s_tpu.utils.logging import get_logger

log = get_logger("apiserver")

# plural REST segment <-> scheme kind (the CRD `names` mapping,
# k8s-operator.md:20-27)
PLURALS: Dict[str, str] = {
    "tpujobs": "TPUJob",
    "tpuserves": "TPUServe",
    "pods": "Pod",
    "services": "Service",
    "leases": "Lease",
    "events": "Event",
}
KIND_TO_PLURAL = {v: k for k, v in PLURALS.items()}

_HEARTBEAT_S = 2.0


@dataclass
class TLSServerConfig:
    """Serving certs. ``client_ca_file`` set → request client certificates
    during the handshake and accept CA-verified ones as an identity (mTLS);
    bearer tokens still work alongside."""

    cert_file: str
    key_file: str
    client_ca_file: Optional[str] = None


@dataclass
class User:
    """An authenticated caller. ``readonly`` callers get GET/watch only —
    the minimal authorization split that makes 403 (authorized ≠
    authenticated) real rather than theoretical."""

    name: str
    readonly: bool = False


@dataclass
class AuthConfig:
    """Static-token authentication (the k8s ``--token-auth-file`` model):
    bearer token → user. ``allow_client_certs`` additionally admits
    mTLS-verified peers (requires ``TLSServerConfig.client_ca_file``)."""

    tokens: Dict[str, User] = field(default_factory=dict)
    allow_client_certs: bool = True

    @staticmethod
    def from_token_file(path: str) -> "AuthConfig":
        """Parse ``token,user[,readonly]`` lines (CSV like the k8s static
        token file; blank lines and ``#`` comments skipped)."""
        tokens: Dict[str, User] = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = [p.strip() for p in line.split(",")]
                if len(parts) < 2:
                    raise ValueError(f"token file line needs token,user: {line!r}")
                tokens[parts[0]] = User(
                    name=parts[1], readonly="readonly" in parts[2:]
                )
        return AuthConfig(tokens=tokens)


class _AdmissionRejected(Exception):
    """Invalid TPUJob write — mapped to 422 Invalid by the error sender."""


def _err_body(status: int, reason: str, message: str) -> bytes:
    # the k8s metav1.Status failure envelope
    return json.dumps(
        {
            "kind": "Status",
            "apiVersion": "v1",
            "status": "Failure",
            "code": status,
            "reason": reason,
            "message": message,
        }
    ).encode()


class _Handler(BaseHTTPRequestHandler):
    # HTTP/1.1 keep-alive for discrete requests; watch responses opt out
    # (Connection: close + no Content-Length → stream until disconnect).
    protocol_version = "HTTP/1.1"
    server: "APIServer"

    def log_message(self, *a):  # route through our logger, debug level
        log.debug("http: " + a[0], *a[1:])

    def setup(self) -> None:
        # Per-connection TLS: get_request hands us a not-yet-handshaken
        # SSLSocket (wrapping there, handshaking here, keeps a slow or
        # malicious peer from stalling the accept loop). Handshake errors
        # propagate to handle_error, which logs them at debug.
        if isinstance(self.request, ssl.SSLSocket):
            self.request.do_handshake()
        super().setup()

    # -- authn/authz --------------------------------------------------------

    def _send_status_error(
        self, status: int, reason: str, message: str,
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> None:
        body = _err_body(status, reason, message)
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _authenticate(self) -> Optional[User]:
        """Resolve the caller's identity, or None (no valid credentials)."""
        auth = self.server.auth
        if auth is None:
            return User("system:anonymous")
        hdr = self.headers.get("Authorization", "")
        if hdr.startswith("Bearer "):
            return auth.tokens.get(hdr[len("Bearer "):].strip())
        if auth.allow_client_certs and isinstance(self.connection, ssl.SSLSocket):
            der = self.connection.getpeercert(binary_form=True)
            if der:  # CA-verified during the handshake (CERT_OPTIONAL)
                from tfk8s_tpu.client.tlsutil import cert_common_name

                cn = cert_common_name(der)
                if cn:
                    return User(cn)
        return None

    def _gate(self, write: bool) -> Optional[User]:
        """The 401/403 boundary: returns the caller, or None after having
        sent the error. Anonymous/unknown credentials → 401 Unauthorized
        (with WWW-Authenticate, per RFC 6750); an authenticated read-only
        caller attempting a write → 403 Forbidden."""
        user = self._authenticate()
        if user is None or (write and user.readonly):
            # The gate fires BEFORE the request body is read; on HTTP/1.1
            # keep-alive the unread body bytes would be parsed as the next
            # request line — close the connection instead of desyncing it.
            self.close_connection = True
            if user is None:
                self._send_status_error(
                    401, "Unauthorized", "authentication required",
                    extra_headers={
                        "WWW-Authenticate": "Bearer", "Connection": "close",
                    },
                )
            else:
                self._send_status_error(
                    403, "Forbidden",
                    f'user "{user.name}" cannot write (read-only credential)',
                    extra_headers={"Connection": "close"},
                )
            return None
        return user

    # -- plumbing -----------------------------------------------------------

    def _send_json(self, status: int, payload: Any) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_store_error(self, exc: Exception) -> None:
        if isinstance(exc, NotFound):
            status, reason = 404, "NotFound"
        elif isinstance(exc, AlreadyExists):
            status, reason = 409, "AlreadyExists"
        elif isinstance(exc, Conflict):
            status, reason = 409, "Conflict"
        elif isinstance(exc, Gone):
            status, reason = 410, "Gone"
        elif isinstance(exc, (Invalid, _AdmissionRejected)):
            status, reason = 422, "Invalid"
        else:
            status, reason = 500, "InternalError"
            log.warning("apiserver 500: %s", exc)
        self._send_status_error(status, reason, str(exc))

    def _read_body(self) -> Dict[str, Any]:
        length = int(self.headers.get("Content-Length", "0"))
        return json.loads(self.rfile.read(length) or b"{}")

    def _route(self) -> Optional[Tuple[str, Optional[str], Optional[str], bool, Dict[str, str]]]:
        """Parse path → (kind, namespace, name, is_status, query) or None."""
        parsed = urllib.parse.urlparse(self.path)
        query = {k: v[0] for k, v in urllib.parse.parse_qs(parsed.query).items()}
        parts = [p for p in parsed.path.split("/") if p]
        # /apis/{group}/{version}/...
        if len(parts) < 3 or parts[0] != "apis":
            return None
        gv = f"{parts[1]}/{parts[2]}"
        if gv != API_VERSION and gv != "core/v1":
            return None
        rest = parts[3:]
        is_status = False
        if rest and rest[-1] == "status":
            is_status = True
            rest = rest[:-1]
        if len(rest) >= 2 and rest[0] == "namespaces":
            ns: Optional[str] = rest[1]
            rest = rest[2:]
        else:
            ns = None
        if not rest or rest[0] not in PLURALS:
            return None
        kind = PLURALS[rest[0]]
        name = rest[1] if len(rest) > 1 else None
        return kind, ns, name, is_status, query

    # -- verbs --------------------------------------------------------------
    #
    # Each verb runs through _timed: with a metrics registry on the server,
    # discrete requests record a per-verb latency histogram + counter
    # (apiserver_request_seconds{verb=...}). Watch streams are excluded
    # from the histogram — a stream lives for minutes and would bury the
    # request latencies — and counted separately at stream open.

    def _timed(self, verb: str, handler) -> None:
        m = self.server.metrics
        if m is None:
            handler()
            return
        self._streaming = False
        t0 = time.perf_counter()
        try:
            handler()
        finally:
            if not self._streaming:
                labels = {"verb": verb}
                m.observe(
                    "apiserver.request_seconds",
                    time.perf_counter() - t0, labels,
                )
                m.inc("apiserver.requests_total", 1.0, labels)

    def do_GET(self) -> None:
        self._timed("GET", self._handle_get)

    def do_POST(self) -> None:
        self._timed("POST", self._handle_post)

    def do_PUT(self) -> None:
        self._timed("PUT", self._handle_put)

    def do_PATCH(self) -> None:
        self._timed("PATCH", self._handle_patch)

    def do_DELETE(self) -> None:
        self._timed("DELETE", self._handle_delete)

    def _handle_get(self) -> None:
        if self.path == "/healthz":
            # liveness probes stay credential-free (kubelet-probe parity)
            self._send_json(200, {"status": "ok"})
            return
        if self.path == "/metrics" and self.server.metrics is not None:
            # same open stance as /healthz: operator-internal plane
            body = self.server.metrics.prometheus_text().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if self.path.split("?", 1)[0] in ("/debug/requests", "/debug/decode"):
            # zpages ride the same open stance as /metrics — the shared
            # gateway.server helpers render both bodies
            from urllib.parse import parse_qs, urlsplit

            from tfk8s_tpu.gateway.server import debug_decode, debug_requests
            from tfk8s_tpu.obs.trace import get_tracer

            sp = urlsplit(self.path)
            if sp.path == "/debug/requests":
                q = {k: v[0] for k, v in parse_qs(sp.query).items()}
                self._send_json(200, debug_requests(
                    get_tracer(), trace_id=q.get("trace_id"),
                    limit=int(q.get("limit", "32")),
                ))
            else:
                self._send_json(200, debug_decode())
            return
        if self._gate(write=False) is None:
            return
        if self.path == "/apis" or self.path == "/apis/":
            self._send_json(200, self.server.discovery_doc())
            return
        if self.path.rstrip("/") == f"/apis/{API_VERSION}":
            self._send_json(200, self.server.resource_list())
            return
        route = self._route()
        if route is None:
            self._send_json(404, {"reason": "NotFound", "message": self.path})
            return
        kind, ns, name, _st, query = route
        try:
            if name is not None:
                obj = self.server.store.get(kind, ns or "default", name)
                self._send_json(200, serde.to_wire(obj))
                return
            if query.get("watch") in ("1", "true"):
                self._serve_watch(kind, query)
                return
            selector = _parse_selector(query.get("labelSelector", ""))
            items, rv = self.server.store.list(kind, ns, selector or None)
            # the k8s *List envelope: ListMeta.resourceVersion is the
            # store's version at list time (the reflector's watch cursor)
            self._send_json(
                200,
                {
                    "apiVersion": serde.api_version_of(kind),
                    "kind": f"{kind}List",
                    "metadata": {"resourceVersion": str(rv)},
                    "items": [serde.to_wire(o) for o in items],
                },
            )
        except Exception as e:  # noqa: BLE001 — mapped to protocol errors
            self._send_store_error(e)

    def _admit(self, obj) -> None:
        """Admission for CRD writes (the validating webhook's job, done by
        the API machinery here): apply defaults, then validate — invalid
        specs are rejected at the boundary with 422 Invalid instead of
        being persisted and later failed by the controller. Raises
        :class:`_AdmissionRejected` on invalid specs."""
        if not self.server.admission:
            return
        if obj.kind == "TPUJob":
            from tfk8s_tpu.api import set_defaults, validate

            set_defaults(obj)
            errs = validate(obj)
        elif obj.kind == "TPUServe":
            from tfk8s_tpu.api import set_serve_defaults, validate_serve

            set_serve_defaults(obj)
            errs = validate_serve(obj)
        else:
            return
        if errs:
            raise _AdmissionRejected("; ".join(errs))

    def _handle_post(self) -> None:
        if self._gate(write=True) is None:
            return
        route = self._route()
        if route is None:
            self._send_json(404, {"reason": "NotFound", "message": self.path})
            return
        kind, ns, _name, _st, _q = route
        try:
            obj = serde.decode_object(self._read_body())
            if ns:
                obj.metadata.namespace = ns
            self._admit(obj)
            created = self.server.store.create(obj)
            self._send_json(201, serde.to_wire(created))
        except Exception as e:  # noqa: BLE001
            self._send_store_error(e)

    def _handle_put(self) -> None:
        if self._gate(write=True) is None:
            return
        route = self._route()
        if route is None or route[2] is None:
            self._send_json(404, {"reason": "NotFound", "message": self.path})
            return
        kind, ns, name, is_status, _q = route
        try:
            obj = serde.decode_object(self._read_body())
            # the URL is authoritative; a body naming a different object
            # is a client bug, not a redirect
            if (
                obj.kind != kind
                or obj.metadata.name != name
                or (ns is not None and obj.metadata.namespace != ns)
            ):
                body = _err_body(
                    400, "BadRequest",
                    f"body names {obj.kind} "
                    f"{obj.metadata.namespace}/{obj.metadata.name}, "
                    f"URL names {kind} {ns}/{name}",
                )
                self.send_response(400)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if is_status:
                updated = self.server.store.update_status(obj)
            else:
                self._admit(obj)
                updated = self.server.store.update(obj)
            self._send_json(200, serde.to_wire(updated))
        except Exception as e:  # noqa: BLE001
            self._send_store_error(e)

    def _handle_patch(self) -> None:
        """JSON merge-patch (RFC 7386) on objects and /status — the verb
        `kubectl apply/scale` and controller status writes ride so
        concurrent writers touch disjoint fields instead of fighting over
        whole-object PUTs (k8s-operator.md:33-34)."""
        if self._gate(write=True) is None:
            return
        ctype = (self.headers.get("Content-Type") or "").split(";")[0].strip()
        if ctype not in ("application/merge-patch+json", "application/json"):
            self._send_json(
                415,
                {
                    "reason": "UnsupportedMediaType",
                    "message": f"PATCH requires application/merge-patch+json, "
                               f"got {ctype!r}",
                },
            )
            return
        route = self._route()
        if route is None or route[2] is None:
            self._send_json(404, {"reason": "NotFound", "message": self.path})
            return
        kind, ns, name, is_status, _q = route
        try:
            patch = self._read_body()
        except ValueError as exc:
            self._send_json(
                400,
                {"reason": "BadRequest", "message": f"body is not JSON: {exc}"},
            )
            return
        if not isinstance(patch, dict):
            # RFC 7386: a merge patch document is a JSON OBJECT; an
            # array/string/null body would otherwise reach store.patch
            # and surface as a 500 AttributeError (ADVICE r5)
            self._send_json(
                400,
                {
                    "reason": "BadRequest",
                    "message": "merge patch must be a JSON object, got "
                               f"{type(patch).__name__}",
                },
            )
            return
        try:
            # admission runs on the MERGED object inside the store's
            # critical section — a patch cannot sneak an invalid spec
            # past validation, and a rejected patch commits nothing
            patched = self.server.store.patch(
                kind, ns or "default", name, patch,
                subresource="status" if is_status else None,
                admit=self._admit,
            )
            self._send_json(200, serde.to_wire(patched))
        except Exception as e:  # noqa: BLE001
            self._send_store_error(e)

    def _handle_delete(self) -> None:
        if self._gate(write=True) is None:
            return
        route = self._route()
        if route is None or route[2] is None:
            self._send_json(404, {"reason": "NotFound", "message": self.path})
            return
        kind, ns, name, _st, _q = route
        try:
            deleted = self.server.store.delete(kind, ns or "default", name)
            self._send_json(200, serde.to_wire(deleted))
        except Exception as e:  # noqa: BLE001
            self._send_store_error(e)

    # -- watch streaming ----------------------------------------------------

    def _serve_watch(self, kind: str, query: Dict[str, str]) -> None:
        self._streaming = True  # exclude the stream from request latency
        if self.server.metrics is not None:
            self.server.metrics.inc(
                "apiserver.requests_total", 1.0, {"verb": "WATCH"}
            )
        since_rv: Optional[int] = None
        if "resourceVersion" in query:
            since_rv = int(query["resourceVersion"])
        w = self.server.store.watch(kind, since_rv)  # Gone propagates to 410
        self.send_response(200)
        self.send_header("Content-Type", "application/json; stream=watch")
        self.send_header("Connection", "close")
        self.end_headers()
        self.close_connection = True
        try:
            while not self.server.stopping.is_set():
                ev = w.next(timeout=_HEARTBEAT_S)
                if ev is None:
                    line = b'{"type": "HEARTBEAT"}\n'
                else:
                    line = (
                        json.dumps(
                            {"type": ev.type.value, "object": serde.to_wire(ev.object)}
                        ).encode()
                        + b"\n"
                    )
                self.wfile.write(line)
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client went away — normal watch teardown
        finally:
            self.server.store.stop_watch(w)


def parse_selector(raw: str) -> Dict[str, str]:
    """``a=b,c=d`` → dict (the labelSelector query format). The ONE
    parser — the CLI's ``-l`` flag uses it too, so client and server
    selector semantics cannot drift."""
    out: Dict[str, str] = {}
    for part in (raw or "").split(","):
        if "=" in part:
            k, v = part.split("=", 1)
            out[k.strip()] = v.strip()
    return out


_parse_selector = parse_selector  # internal alias (pre-rename call sites)


class APIServer(ThreadingHTTPServer):
    """Threaded HTTP(S) apiserver over one ClusterStore. ``port=0`` binds an
    ephemeral port (tests); ``serve_background()`` runs on a daemon thread
    and returns the bound port. ``tls``/``auth`` secure the wire (module
    docstring)."""

    daemon_threads = True
    # watches hold sockets open; allow plenty of concurrent streams
    request_queue_size = 64

    def __init__(
        self,
        store: ClusterStore,
        host: str = "127.0.0.1",
        port: int = 0,
        admission: bool = True,
        tls: Optional[TLSServerConfig] = None,
        auth: Optional[AuthConfig] = None,
        metrics=None,
    ):
        self.store = store
        self.admission = admission
        self.auth = auth
        # optional utils.logging.Metrics: per-verb request latency
        # histograms + /metrics exposition on this listener
        self.metrics = metrics
        if metrics is not None:
            metrics.describe(
                "apiserver.request_seconds",
                "Wall time per discrete apiserver request, by verb.",
            )
            metrics.describe(
                "apiserver.requests_total",
                "Requests served, by verb (WATCH counts stream opens).",
            )
        self.stopping = threading.Event()
        self._ssl_ctx: Optional[ssl.SSLContext] = None
        if tls is not None:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls.cert_file, tls.key_file)
            if tls.client_ca_file:
                ctx.load_verify_locations(tls.client_ca_file)
                # OPTIONAL, not REQUIRED: bearer-token clients carry no
                # cert; a presented cert must still verify against the CA
                ctx.verify_mode = ssl.CERT_OPTIONAL
            self._ssl_ctx = ctx
        super().__init__((host, port), _Handler)

    def get_request(self):
        sock, addr = self.socket.accept()
        if self._ssl_ctx is not None:
            # wrap here, handshake in the handler thread (_Handler.setup)
            sock = self._ssl_ctx.wrap_socket(
                sock, server_side=True, do_handshake_on_connect=False
            )
        return sock, addr

    def handle_error(self, request, client_address) -> None:  # type: ignore[override]
        # TLS handshake failures from probes/misconfigured clients are
        # operationally normal; keep them off stderr.
        import sys

        exc = sys.exc_info()[1]
        if isinstance(exc, (ssl.SSLError, ConnectionError, TimeoutError, OSError)):
            log.debug("connection from %s failed: %s", client_address, exc)
            return
        super().handle_error(request, client_address)

    @property
    def port(self) -> int:
        return self.server_address[1]

    @property
    def url(self) -> str:
        scheme = "https" if self._ssl_ctx is not None else "http"
        return f"{scheme}://{self.server_address[0]}:{self.port}"

    def discovery_doc(self) -> Dict[str, Any]:
        # metav1.APIGroupList, what `kubectl api-versions` reads at /apis
        group, version = API_VERSION.split("/")
        gv = {"groupVersion": API_VERSION, "version": version}
        return {
            "kind": "APIGroupList",
            "apiVersion": "v1",
            "groups": [
                {"name": group, "versions": [gv], "preferredVersion": gv}
            ],
        }

    def resource_list(self) -> Dict[str, Any]:
        # metav1.APIResourceList for the group-version (kubectl api-resources)
        verbs = ["create", "delete", "get", "list", "patch", "update", "watch"]
        return {
            "kind": "APIResourceList",
            "apiVersion": "v1",
            "groupVersion": API_VERSION,
            "resources": [
                {
                    "name": plural,
                    "kind": kind,
                    "namespaced": True,
                    "verbs": verbs,
                }
                for plural, kind in sorted(PLURALS.items())
            ]
            + [
                {
                    "name": f"{plural}/status",
                    "kind": kind,
                    "namespaced": True,
                    "verbs": ["patch", "update"],
                }
                for plural, kind in sorted(PLURALS.items())
            ],
        }

    def serve_background(self) -> int:
        t = threading.Thread(target=self.serve_forever, daemon=True, name="apiserver")
        t.start()
        return self.port

    def shutdown(self) -> None:  # type: ignore[override]
        self.stopping.set()
        super().shutdown()
