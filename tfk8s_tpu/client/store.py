"""In-memory cluster state store with List/Watch — the L0 substrate.

This is the apiserver-shaped object store the whole control plane runs
against hermetically, the way the reference's operator family tests
"multi-node" against a fake clientset serving CRUD + watch from an
in-memory tracker (SURVEY.md §4). It implements the semantics the reference
documents for the real apiserver:

- **Optimistic concurrency**: every write bumps a store-wide monotonic
  ``resource_version``; updates carrying a stale version fail with
  :class:`Conflict` (the requeue-on-conflict path, SURVEY.md §7 hard part 2).
- **Watch streams**: ``watch(kind, since_rv)`` replays buffered events after
  ``since_rv`` then streams live — the List/Watch contract the Reflector
  consumes (images/informer1.png at k8s-operator.md:60). A ``since_rv``
  older than the history window raises :class:`Gone` (HTTP 410), forcing
  the reflector to relist — exactly the real protocol.
- **Finalizer-gated deletion**: deleting an object with finalizers only sets
  ``metadata.deletion_timestamp``; the object is removed when a controller
  strips the last finalizer (k8s-operator.md:36-43).
- **Durability** (``journal_dir``): every mutation appends one JSONL record
  to a write-ahead log before it is acknowledged; a snapshot compacts the
  log periodically. A restarted store replays snapshot+WAL and resumes the
  SAME resource_version sequence — the etcd-backed persistence the
  reference's REST contract presupposes (k8s-operator.md:33-43: deletion
  timestamps and finalizers only make sense on objects that survive a
  control-plane restart). Watchers reconnecting from a pre-restart rv that
  the replayed WAL no longer covers get :class:`Gone` and relist — the
  same recovery path as a compacted etcd.
"""

from __future__ import annotations

import copy
import enum
import itertools
import json
import logging
import os
import queue
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Tuple

log = logging.getLogger(__name__)


class StoreError(Exception):
    pass


class NotFound(StoreError):
    pass


class AlreadyExists(StoreError):
    pass


class Conflict(StoreError):
    """Stale resource_version on update (optimistic-concurrency failure)."""


class Gone(StoreError):
    """Watch requested from a resource_version older than the event buffer —
    the client must relist (HTTP 410 semantics)."""


class Invalid(StoreError):
    """A syntactically well-formed request whose CONTENT cannot be
    processed (HTTP 422 semantics): e.g. a merge-patch carrying a
    non-numeric ``metadata.resourceVersion`` precondition. Distinct from
    admission rejection (which validates the merged OBJECT); this
    rejects the request itself."""


class Unavailable(StoreError):
    """The apiserver cannot be reached (connection refused/reset, 5xx) —
    transient by nature; callers with durable obligations (the kubelet's
    terminal phase writes) retry these, and ONLY these."""


class JournalCorrupt(StoreError):
    """A complete (newline-terminated) WAL record failed to decode —
    mid-file corruption or a schema break. Refusing to start is the only
    safe response: truncating would destroy acked records written after
    the bad one."""


class Unauthorized(StoreError):
    """No/invalid credentials against a secured apiserver (HTTP 401)."""


class Forbidden(StoreError):
    """Authenticated but not permitted — e.g. a read-only credential
    attempting a write (HTTP 403)."""


class EventType(str, enum.Enum):
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"


@dataclass
class WatchEvent:
    type: EventType
    object: Any  # a deep copy; safe to mutate

    @property
    def kind(self) -> str:
        return self.object.kind


_SENTINEL = object()


class Watch:
    """One consumer's event stream. Iterate to receive events; ``stop()``
    ends the iteration (the stopCh analogue, k8s-operator.md:200-203)."""

    def __init__(self) -> None:
        self._q: "queue.Queue[Any]" = queue.Queue()
        self._stopped = False

    def _push(self, ev: WatchEvent) -> None:
        if not self._stopped:
            self._q.put(ev)

    def stop(self) -> None:
        self._stopped = True
        self._q.put(_SENTINEL)

    def __iter__(self) -> Iterator[WatchEvent]:
        while True:
            item = self._q.get()
            if item is _SENTINEL or self._stopped:
                return
            yield item

    def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        """Blocking pop with timeout; None on timeout or stop."""
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if item is _SENTINEL:
            return None
        return item


def _key(namespace: str, name: str) -> str:
    return f"{namespace}/{name}"


def match_labels(selector: Dict[str, str], labels: Dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


def merge_patch(target: Any, patch: Any) -> Any:
    """RFC 7386 JSON merge-patch: dicts merge recursively, ``null`` deletes
    a key, everything else (including lists) replaces wholesale."""
    if not isinstance(patch, dict):
        return copy.deepcopy(patch)
    result = dict(target) if isinstance(target, dict) else {}
    for k, v in patch.items():
        if v is None:
            result.pop(k, None)
        else:
            result[k] = merge_patch(result.get(k), v)
    return result


_MISSING = object()


def replace_patch(current: Any, desired: Any) -> Dict[str, Any]:
    """The inverse of :func:`merge_patch`: the smallest merge-patch that
    transforms ``current`` into exactly ``desired`` — keys present in
    current but absent from desired become explicit ``null`` deletions.
    This is how `apply` gets REPLACE semantics (a field removed from the
    manifest really goes away) over the merge-patch wire verb. Returns
    ``{}`` when nothing differs."""
    p = _replace_patch(current, desired)
    return {} if p is _MISSING else p


def _replace_patch(current: Any, desired: Any) -> Any:
    if isinstance(desired, dict) and isinstance(current, dict):
        patch = {}
        for k, v in desired.items():
            cv = current.get(k, _MISSING)
            if cv is _MISSING:
                patch[k] = copy.deepcopy(v)
            else:
                sub = _replace_patch(cv, v)
                if sub is not _MISSING:
                    patch[k] = sub
        for k in current:
            if k not in desired:
                patch[k] = None
        return patch if patch else _MISSING
    if current == desired:
        return _MISSING
    return copy.deepcopy(desired)


class ClusterStore:
    """Thread-safe object store keyed by (kind, namespace/name).

    With ``journal_dir`` set, the store is durable: ``snapshot.json`` holds
    a compacted full state, ``wal.jsonl`` the event log since; construction
    replays both and resumes the rv sequence. ``fsync=False`` trades
    power-loss durability for write latency (kill -9 survival only needs
    the page cache, so tests and the control-plane bench may disable it).
    """

    def __init__(
        self,
        history_limit: int = 4096,
        journal_dir: Optional[str] = None,
        compact_every: int = 4096,
        fsync: bool = True,
    ) -> None:
        self._lock = threading.RLock()
        self._objects: Dict[str, Dict[str, Any]] = {}
        self._rv = itertools.count(1)
        self._last_rv = 0
        # ring buffer of (rv, WatchEvent) for replay
        self._history: "deque[Tuple[int, WatchEvent]]" = deque(maxlen=history_limit)
        self._watchers: List[Tuple[str, Watch]] = []
        self._journal_dir = journal_dir
        self._compact_every = compact_every
        self._fsync = fsync
        self._wal = None  # append handle on wal.jsonl
        self._wal_records = 0
        self._poisoned = False
        if journal_dir is not None:
            self._open_journal()

    # -- journal ------------------------------------------------------------

    @property
    def _snapshot_path(self) -> str:
        return os.path.join(self._journal_dir, "snapshot.json")

    @property
    def _wal_path(self) -> str:
        return os.path.join(self._journal_dir, "wal.jsonl")

    def _open_journal(self) -> None:
        """Replay snapshot + WAL, then open the WAL for append. A torn final
        line (kill -9 mid-write) is truncated away — everything before it
        was acknowledged with a complete line, so nothing acked is lost."""
        from tfk8s_tpu.api import serde  # api layer; no import cycle

        os.makedirs(self._journal_dir, exist_ok=True)
        if os.path.exists(self._snapshot_path):
            with open(self._snapshot_path) as f:
                snap = json.load(f)
            self._last_rv = snap["rv"]
            for data in snap["objects"]:
                obj = serde.decode_object(data)
                self._bucket(obj.kind)[obj.metadata.key] = obj
        good_end = 0
        if os.path.exists(self._wal_path):
            with open(self._wal_path, "rb") as f:
                for line in f:
                    if not line.endswith(b"\n"):
                        # A torn tail is the expected kill -9 artifact: the
                        # record was never acked (ack follows the full-line
                        # write), so truncating exactly it loses nothing.
                        log.warning(
                            "journal: truncating torn WAL tail (%d bytes)", len(line)
                        )
                        break
                    try:
                        rec = json.loads(line)
                        obj = serde.decode_object(rec["obj"])
                        etype = EventType(rec["type"])
                    except (ValueError, KeyError) as e:
                        # A COMPLETE line that fails to decode is mid-file
                        # corruption (or a schema break). Acked records may
                        # follow it — truncating here would destroy them, so
                        # refuse to start instead (etcd does the same).
                        raise JournalCorrupt(
                            f"{self._wal_path} byte {good_end}: "
                            f"undecodable complete record: {e}"
                        ) from e
                    bucket = self._bucket(obj.kind)
                    if etype == EventType.DELETED:
                        bucket.pop(obj.metadata.key, None)
                    else:
                        bucket[obj.metadata.key] = obj
                    self._last_rv = max(self._last_rv, rec["rv"])
                    self._history.append((rec["rv"], WatchEvent(etype, obj)))
                    self._wal_records += 1
                    good_end += len(line)
        self._rv = itertools.count(self._last_rv + 1)
        self._wal = open(self._wal_path, "ab")
        if good_end != self._wal.tell():
            self._wal.truncate(good_end)
            self._wal.seek(good_end)

    def _journal(self, etype: EventType, obj: Any) -> None:
        """Append one event record; called under the lock, BEFORE watchers
        see the event, so nothing observable ever precedes the WAL.

        A failed append must leave the WAL byte-identical to its last good
        state: a BufferedWriter that kept (or half-wrote) the failed
        record's bytes would prepend them to the NEXT successful append —
        either resurrecting a never-acked object after restart or fusing
        two lines into one undecodable record (JournalCorrupt on the next
        start). If even the rollback fails, the journal is poisoned and
        every further mutation is refused — availability is the right
        thing to sacrifice for a store whose point is durability."""
        from tfk8s_tpu.api import serde

        if self._poisoned:
            raise StoreError(
                "journal poisoned by an earlier unrecoverable write error; "
                "refusing mutations (restart the apiserver to re-replay)"
            )
        rec = {
            "rv": obj.metadata.resource_version,
            "type": etype.value,
            "obj": serde.to_dict(obj),
        }
        start = self._wal.tell()
        try:
            self._wal.write((json.dumps(rec) + "\n").encode())
            self._wal.flush()
            if self._fsync:
                os.fsync(self._wal.fileno())
        except OSError:
            try:
                self._wal.close()  # may raise re-flushing; superseded below
            except OSError:
                pass
            try:
                with open(self._wal_path, "ab") as fix:
                    fix.truncate(start)
                self._wal = open(self._wal_path, "ab")
            except OSError:
                self._poisoned = True
                log.error(
                    "journal: could not roll back failed append; poisoning "
                    "the store (WAL intact through rv %d)", self._last_rv,
                )
            raise
        self._wal_records += 1

    def _compact(self) -> None:
        """Atomic snapshot of full state, then truncate the WAL. Watchers
        holding pre-snapshot rvs will relist via Gone after a restart —
        exactly etcd compaction semantics.

        Ordering matters: the snapshot (and, under fsync, its directory
        entry) must be durable BEFORE the WAL is truncated, or a power cut
        between the two could leave the old snapshot + an empty WAL —
        losing everything since the previous compaction.

        Runs synchronously under the store lock — a deliberate tradeoff:
        at this store's scale (thousands of objects) the pause is
        single-digit ms every ``compact_every`` writes; a background
        compactor would need WAL segment rotation for no measured win
        (the control-plane bench rides this path).
        """
        from tfk8s_tpu.api import serde

        snap = {
            "rv": self._last_rv,
            "objects": [
                serde.to_dict(obj)
                for bucket in self._objects.values()
                for obj in bucket.values()
            ],
        }
        tmp = self._snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f)
            f.flush()
            if self._fsync:
                os.fsync(f.fileno())
        os.replace(tmp, self._snapshot_path)
        if self._fsync:
            # persist the rename itself before dropping the WAL
            dir_fd = os.open(self._journal_dir, os.O_RDONLY)
            try:
                os.fsync(dir_fd)
            finally:
                os.close(dir_fd)
        # truncate through the live handle — no close/reopen window in
        # which a failure could leave the store without a WAL handle
        self._wal.truncate(0)
        self._wal.seek(0)
        self._wal_records = 0

    def close(self) -> None:
        with self._lock:
            if self._wal is not None:
                self._wal.close()
                self._wal = None

    # -- internals ----------------------------------------------------------

    def _bump(self) -> int:
        self._last_rv = next(self._rv)
        return self._last_rv

    def _emit(self, etype: EventType, obj: Any, apply=None) -> None:
        """Journal, then commit, then notify — in that order. ``apply``
        performs the actual bucket mutation; deferring it until after the
        WAL append succeeds keeps the log write-AHEAD: a failed append
        (ENOSPC, dead disk) raises to the client with NO state change, so
        readers can never observe an object that a restart would forget."""
        ev = WatchEvent(etype, copy.deepcopy(obj))
        if self._wal is not None:
            self._journal(etype, ev.object)
        if apply is not None:
            apply()
        # compact only AFTER the mutation is applied — a snapshot taken
        # between journal and apply would miss the in-flight object and the
        # WAL truncation would then destroy its only record. A compaction
        # failure must NOT fail the (already committed and journaled)
        # mutation: log it and retry at the next write, when
        # _wal_records will still be over threshold.
        if self._wal is not None and self._wal_records >= self._compact_every:
            try:
                self._compact()
            except OSError as e:
                log.warning("journal: compaction failed (will retry): %s", e)
        self._history.append((obj.metadata.resource_version, ev))
        for kind, w in list(self._watchers):
            if kind == obj.kind:
                # per-watcher copy so consumers can't race each other
                w._push(WatchEvent(etype, copy.deepcopy(ev.object)))

    def _bucket(self, kind: str) -> Dict[str, Any]:
        return self._objects.setdefault(kind, {})

    # -- CRUD ---------------------------------------------------------------

    def create(self, obj: Any) -> Any:
        with self._lock:
            bucket = self._bucket(obj.kind)
            k = obj.metadata.key
            if k in bucket:
                raise AlreadyExists(f"{obj.kind} {k} already exists")
            stored = copy.deepcopy(obj)
            stored.metadata.uid = stored.metadata.uid or uuid.uuid4().hex
            stored.metadata.creation_timestamp = (
                stored.metadata.creation_timestamp or time.time()
            )
            stored.metadata.resource_version = self._bump()
            self._emit(
                EventType.ADDED, stored, apply=lambda: bucket.__setitem__(k, stored)
            )
            return copy.deepcopy(stored)

    def get(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            try:
                return copy.deepcopy(self._bucket(kind)[_key(namespace, name)])
            except KeyError:
                raise NotFound(f"{kind} {namespace}/{name} not found") from None

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> Tuple[List[Any], int]:
        """Returns (items, resource_version) — the rv is the point to start
        watching from (List-then-Watch, images/informer1.png)."""
        with self._lock:
            items = []
            for obj in self._bucket(kind).values():
                if namespace is not None and obj.metadata.namespace != namespace:
                    continue
                if label_selector and not match_labels(label_selector, obj.metadata.labels):
                    continue
                items.append(copy.deepcopy(obj))
            return items, self._last_rv

    def update(self, obj: Any) -> Any:
        """Write with optimistic-concurrency check. Clearing the last
        finalizer on a deletion-marked object completes the delete."""
        with self._lock:
            bucket = self._bucket(obj.kind)
            k = obj.metadata.key
            if k not in bucket:
                raise NotFound(f"{obj.kind} {k} not found")
            current = bucket[k]
            if obj.metadata.resource_version != current.metadata.resource_version:
                raise Conflict(
                    f"{obj.kind} {k}: resource_version "
                    f"{obj.metadata.resource_version} != {current.metadata.resource_version}"
                )
            stored = copy.deepcopy(obj)
            stored.metadata.uid = current.metadata.uid
            stored.metadata.creation_timestamp = current.metadata.creation_timestamp
            # deletion_timestamp is set by delete(), never by clients
            stored.metadata.deletion_timestamp = current.metadata.deletion_timestamp
            if (
                stored.metadata.deletion_timestamp is not None
                and not stored.metadata.finalizers
            ):
                stored.metadata.resource_version = self._bump()
                self._emit(
                    EventType.DELETED, stored, apply=lambda: bucket.pop(k)
                )
                return copy.deepcopy(stored)
            stored.metadata.resource_version = self._bump()
            self._emit(
                EventType.MODIFIED, stored, apply=lambda: bucket.__setitem__(k, stored)
            )
            return copy.deepcopy(stored)

    def update_status(self, obj: Any) -> Any:
        """Status-subresource write: applies ONLY ``obj.status`` (same
        optimistic-concurrency rules as update). Spec and metadata edits
        riding along are discarded — the real apiserver's subresource
        isolation, so a status writer can never clobber a concurrent spec
        change it hasn't seen."""
        with self._lock:
            bucket = self._bucket(obj.kind)
            k = obj.metadata.key
            if k not in bucket:
                raise NotFound(f"{obj.kind} {k} not found")
            current = bucket[k]
            if obj.metadata.resource_version != current.metadata.resource_version:
                raise Conflict(
                    f"{obj.kind} {k}: resource_version "
                    f"{obj.metadata.resource_version} != {current.metadata.resource_version}"
                )
            if not hasattr(current, "status"):
                raise StoreError(f"{obj.kind} has no status subresource")
            stored = copy.deepcopy(current)
            stored.status = copy.deepcopy(obj.status)
            stored.metadata.resource_version = self._bump()
            self._emit(
                EventType.MODIFIED, stored, apply=lambda: bucket.__setitem__(k, stored)
            )
            return copy.deepcopy(stored)

    def patch(
        self,
        kind: str,
        namespace: str,
        name: str,
        patch: Dict[str, Any],
        subresource: Optional[str] = None,
        admit=None,
    ) -> Any:
        """JSON merge-patch (RFC 7386) against the stored object — the
        PATCH verb the reference's typed client is built on
        (k8s-operator.md:33-34): writers touch only the fields they own,
        so an operator's status write and a CLI spec write never fight
        over resourceVersion the way whole-object PUTs do.

        ``patch`` is in the Kubernetes WIRE form (camelCase keys, as
        ``serde.to_wire`` produces). Unlike update(), no resourceVersion
        is required — last-writer-wins on the touched fields; a patch
        that DOES carry ``metadata.resourceVersion`` turns it into an
        optimistic precondition (k8s semantics). Server-owned metadata
        (uid, creationTimestamp, deletionTimestamp) cannot be patched.
        ``subresource='status'`` confines the patch to ``status`` exactly
        as update_status confines PUT. ``admit`` (server-side) runs on the
        MERGED object before anything commits — a rejected patch leaves no
        trace, the same boundary a validating webhook gives PUT."""
        from tfk8s_tpu.api import serde

        with self._lock:
            bucket = self._bucket(kind)
            k = _key(namespace, name)
            if k not in bucket:
                raise NotFound(f"{kind} {k} not found")
            current = bucket[k]
            patch = copy.deepcopy(patch)
            md = patch.get("metadata")
            if md is not None and not isinstance(md, dict):
                # the apiserver rejects non-object ROOTS with 400; a
                # non-object metadata SUBTREE would otherwise crash the
                # .pop below as a 500 — same request-content class: 422
                raise Invalid(
                    f"{kind} {k}: patch metadata must be an object, got "
                    f"{type(md).__name__}"
                )
            pre_rv = (md or {}).pop("resourceVersion", None)
            if pre_rv is not None:
                try:
                    pre_rv = int(pre_rv)
                except (TypeError, ValueError):
                    # malformed precondition is a 422 on the request, not
                    # a 500 out of int() (ADVICE r5)
                    raise Invalid(
                        f"{kind} {k}: metadata.resourceVersion precondition "
                        f"must be numeric, got {pre_rv!r}"
                    ) from None
                if pre_rv != current.metadata.resource_version:
                    raise Conflict(
                        f"{kind} {k}: resourceVersion precondition {pre_rv} "
                        f"!= {current.metadata.resource_version}"
                    )
            if subresource == "status":
                # fast path: merge ONLY the status subtree — the
                # controller's per-reconcile write rides this, and a
                # full-object encode→merge→decode measured ~3x slower
                # than the subtree (control_plane bench, status_patches
                # vs creates). Identity/metadata/spec are untouched by
                # construction, so none of the protections below apply.
                if not hasattr(current, "status"):
                    raise StoreError(f"{kind} has no status subresource")
                merged_status = merge_patch(
                    serde.to_wire(current.status), patch.get("status", {})
                )
                stored = copy.deepcopy(current)
                # an explicit {"status": null} resets to the DEFAULT
                # status (key deletion semantics), never to None — a
                # None status would crash every later status reader
                stored.status = serde.from_dict(
                    type(current.status), merged_status or {}
                )
                stored.metadata.resource_version = self._bump()
                self._emit(
                    EventType.MODIFIED, stored,
                    apply=lambda: bucket.__setitem__(k, stored),
                )
                return copy.deepcopy(stored)
            if subresource is not None:
                raise StoreError(f"unknown subresource {subresource!r}")
            # main-resource writes never touch status (subresource
            # isolation, mirroring update())
            patch.pop("status", None)
            cur_wire = serde.to_wire(current)
            merged = merge_patch(cur_wire, patch)
            # identity is immutable under PATCH (the real apiserver rejects
            # name changes): restore kind/apiVersion/name/namespace BEFORE
            # decoding — a patched kind would otherwise re-type the object
            # into the wrong dataclass inside the old kind's bucket
            merged["kind"] = current.kind
            merged["apiVersion"] = cur_wire["apiVersion"]
            merged.setdefault("metadata", {})
            merged["metadata"]["name"] = current.metadata.name
            merged["metadata"]["namespace"] = current.metadata.namespace
            stored = serde.decode_object(merged)
            stored.metadata.uid = current.metadata.uid
            stored.metadata.creation_timestamp = current.metadata.creation_timestamp
            stored.metadata.deletion_timestamp = current.metadata.deletion_timestamp
            if admit is not None and subresource is None:
                admit(stored)  # raises -> nothing committed
            if (
                stored.metadata.deletion_timestamp is not None
                and not stored.metadata.finalizers
            ):
                # stripping the last finalizer via PATCH completes the
                # delete, exactly like update()
                stored.metadata.resource_version = self._bump()
                self._emit(EventType.DELETED, stored, apply=lambda: bucket.pop(k))
                return copy.deepcopy(stored)
            stored.metadata.resource_version = self._bump()
            self._emit(
                EventType.MODIFIED, stored, apply=lambda: bucket.__setitem__(k, stored)
            )
            return copy.deepcopy(stored)

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        """Finalizer-aware delete (k8s-operator.md:36-43): with finalizers
        present only ``deletion_timestamp`` is set; otherwise remove."""
        with self._lock:
            bucket = self._bucket(kind)
            k = _key(namespace, name)
            if k not in bucket:
                raise NotFound(f"{kind} {k} not found")
            current = bucket[k]
            if current.metadata.finalizers:
                if current.metadata.deletion_timestamp is None:
                    marked = copy.deepcopy(current)
                    marked.metadata.deletion_timestamp = time.time()
                    marked.metadata.resource_version = self._bump()
                    self._emit(
                        EventType.MODIFIED, marked,
                        apply=lambda: bucket.__setitem__(k, marked),
                    )
                    return copy.deepcopy(marked)
                return copy.deepcopy(current)
            removed = copy.deepcopy(current)
            removed.metadata.resource_version = self._bump()
            self._emit(EventType.DELETED, removed, apply=lambda: bucket.pop(k))
            return copy.deepcopy(removed)

    # -- watch --------------------------------------------------------------

    def watch(self, kind: str, since_rv: Optional[int] = None) -> Watch:
        """Open an event stream for ``kind``. With ``since_rv``, replay
        buffered events with rv > since_rv first; raise :class:`Gone` if the
        buffer no longer reaches back that far."""
        with self._lock:
            w = Watch()
            if since_rv is not None and since_rv < self._last_rv:
                oldest_buffered = self._history[0][0] if self._history else None
                # oldest_buffered None with last_rv > 0 means the store was
                # restored from a compacted journal — the gap to since_rv is
                # unreplayable, so the client must relist (410), the same
                # contract as a compacted etcd.
                if oldest_buffered is None or since_rv < oldest_buffered - 1:
                    raise Gone(
                        f"resource_version {since_rv} is too old "
                        f"(oldest buffered: {oldest_buffered})"
                    )
                for rv, ev in self._history:
                    if rv > since_rv and ev.object.kind == kind:
                        w._push(WatchEvent(ev.type, copy.deepcopy(ev.object)))
            self._watchers.append((kind, w))
            return w

    def stop_watch(self, w: Watch) -> None:
        with self._lock:
            self._watchers = [(k, x) for k, x in self._watchers if x is not w]
        w.stop()

    @property
    def resource_version(self) -> int:
        with self._lock:
            return self._last_rv
