"""In-memory cluster state store with List/Watch — the L0 substrate.

This is the apiserver-shaped object store the whole control plane runs
against hermetically, the way the reference's operator family tests
"multi-node" against a fake clientset serving CRUD + watch from an
in-memory tracker (SURVEY.md §4). It implements the semantics the reference
documents for the real apiserver:

- **Optimistic concurrency**: every write bumps a store-wide monotonic
  ``resource_version``; updates carrying a stale version fail with
  :class:`Conflict` (the requeue-on-conflict path, SURVEY.md §7 hard part 2).
- **Watch streams**: ``watch(kind, since_rv)`` replays buffered events after
  ``since_rv`` then streams live — the List/Watch contract the Reflector
  consumes (images/informer1.png at k8s-operator.md:60). A ``since_rv``
  older than the history window raises :class:`Gone` (HTTP 410), forcing
  the reflector to relist — exactly the real protocol.
- **Finalizer-gated deletion**: deleting an object with finalizers only sets
  ``metadata.deletion_timestamp``; the object is removed when a controller
  strips the last finalizer (k8s-operator.md:36-43).
"""

from __future__ import annotations

import copy
import enum
import itertools
import queue
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Tuple


class StoreError(Exception):
    pass


class NotFound(StoreError):
    pass


class AlreadyExists(StoreError):
    pass


class Conflict(StoreError):
    """Stale resource_version on update (optimistic-concurrency failure)."""


class Gone(StoreError):
    """Watch requested from a resource_version older than the event buffer —
    the client must relist (HTTP 410 semantics)."""


class Unauthorized(StoreError):
    """No/invalid credentials against a secured apiserver (HTTP 401)."""


class Forbidden(StoreError):
    """Authenticated but not permitted — e.g. a read-only credential
    attempting a write (HTTP 403)."""


class EventType(str, enum.Enum):
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"


@dataclass
class WatchEvent:
    type: EventType
    object: Any  # a deep copy; safe to mutate

    @property
    def kind(self) -> str:
        return self.object.kind


_SENTINEL = object()


class Watch:
    """One consumer's event stream. Iterate to receive events; ``stop()``
    ends the iteration (the stopCh analogue, k8s-operator.md:200-203)."""

    def __init__(self) -> None:
        self._q: "queue.Queue[Any]" = queue.Queue()
        self._stopped = False

    def _push(self, ev: WatchEvent) -> None:
        if not self._stopped:
            self._q.put(ev)

    def stop(self) -> None:
        self._stopped = True
        self._q.put(_SENTINEL)

    def __iter__(self) -> Iterator[WatchEvent]:
        while True:
            item = self._q.get()
            if item is _SENTINEL or self._stopped:
                return
            yield item

    def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        """Blocking pop with timeout; None on timeout or stop."""
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if item is _SENTINEL:
            return None
        return item


def _key(namespace: str, name: str) -> str:
    return f"{namespace}/{name}"


def match_labels(selector: Dict[str, str], labels: Dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


class ClusterStore:
    """Thread-safe object store keyed by (kind, namespace/name)."""

    def __init__(self, history_limit: int = 4096) -> None:
        self._lock = threading.RLock()
        self._objects: Dict[str, Dict[str, Any]] = {}
        self._rv = itertools.count(1)
        self._last_rv = 0
        # ring buffer of (rv, WatchEvent) for replay
        self._history: "deque[Tuple[int, WatchEvent]]" = deque(maxlen=history_limit)
        self._watchers: List[Tuple[str, Watch]] = []

    # -- internals ----------------------------------------------------------

    def _bump(self) -> int:
        self._last_rv = next(self._rv)
        return self._last_rv

    def _emit(self, etype: EventType, obj: Any) -> None:
        ev = WatchEvent(etype, copy.deepcopy(obj))
        self._history.append((obj.metadata.resource_version, ev))
        for kind, w in list(self._watchers):
            if kind == obj.kind:
                # per-watcher copy so consumers can't race each other
                w._push(WatchEvent(etype, copy.deepcopy(ev.object)))

    def _bucket(self, kind: str) -> Dict[str, Any]:
        return self._objects.setdefault(kind, {})

    # -- CRUD ---------------------------------------------------------------

    def create(self, obj: Any) -> Any:
        with self._lock:
            bucket = self._bucket(obj.kind)
            k = obj.metadata.key
            if k in bucket:
                raise AlreadyExists(f"{obj.kind} {k} already exists")
            stored = copy.deepcopy(obj)
            stored.metadata.uid = stored.metadata.uid or uuid.uuid4().hex
            stored.metadata.creation_timestamp = (
                stored.metadata.creation_timestamp or time.time()
            )
            stored.metadata.resource_version = self._bump()
            bucket[k] = stored
            self._emit(EventType.ADDED, stored)
            return copy.deepcopy(stored)

    def get(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            try:
                return copy.deepcopy(self._bucket(kind)[_key(namespace, name)])
            except KeyError:
                raise NotFound(f"{kind} {namespace}/{name} not found") from None

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> Tuple[List[Any], int]:
        """Returns (items, resource_version) — the rv is the point to start
        watching from (List-then-Watch, images/informer1.png)."""
        with self._lock:
            items = []
            for obj in self._bucket(kind).values():
                if namespace is not None and obj.metadata.namespace != namespace:
                    continue
                if label_selector and not match_labels(label_selector, obj.metadata.labels):
                    continue
                items.append(copy.deepcopy(obj))
            return items, self._last_rv

    def update(self, obj: Any) -> Any:
        """Write with optimistic-concurrency check. Clearing the last
        finalizer on a deletion-marked object completes the delete."""
        with self._lock:
            bucket = self._bucket(obj.kind)
            k = obj.metadata.key
            if k not in bucket:
                raise NotFound(f"{obj.kind} {k} not found")
            current = bucket[k]
            if obj.metadata.resource_version != current.metadata.resource_version:
                raise Conflict(
                    f"{obj.kind} {k}: resource_version "
                    f"{obj.metadata.resource_version} != {current.metadata.resource_version}"
                )
            stored = copy.deepcopy(obj)
            stored.metadata.uid = current.metadata.uid
            stored.metadata.creation_timestamp = current.metadata.creation_timestamp
            # deletion_timestamp is set by delete(), never by clients
            stored.metadata.deletion_timestamp = current.metadata.deletion_timestamp
            if (
                stored.metadata.deletion_timestamp is not None
                and not stored.metadata.finalizers
            ):
                del bucket[k]
                stored.metadata.resource_version = self._bump()
                self._emit(EventType.DELETED, stored)
                return copy.deepcopy(stored)
            stored.metadata.resource_version = self._bump()
            bucket[k] = stored
            self._emit(EventType.MODIFIED, stored)
            return copy.deepcopy(stored)

    def update_status(self, obj: Any) -> Any:
        """Status-subresource write: applies ONLY ``obj.status`` (same
        optimistic-concurrency rules as update). Spec and metadata edits
        riding along are discarded — the real apiserver's subresource
        isolation, so a status writer can never clobber a concurrent spec
        change it hasn't seen."""
        with self._lock:
            bucket = self._bucket(obj.kind)
            k = obj.metadata.key
            if k not in bucket:
                raise NotFound(f"{obj.kind} {k} not found")
            current = bucket[k]
            if obj.metadata.resource_version != current.metadata.resource_version:
                raise Conflict(
                    f"{obj.kind} {k}: resource_version "
                    f"{obj.metadata.resource_version} != {current.metadata.resource_version}"
                )
            if not hasattr(current, "status"):
                raise StoreError(f"{obj.kind} has no status subresource")
            stored = copy.deepcopy(current)
            stored.status = copy.deepcopy(obj.status)
            stored.metadata.resource_version = self._bump()
            bucket[k] = stored
            self._emit(EventType.MODIFIED, stored)
            return copy.deepcopy(stored)

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        """Finalizer-aware delete (k8s-operator.md:36-43): with finalizers
        present only ``deletion_timestamp`` is set; otherwise remove."""
        with self._lock:
            bucket = self._bucket(kind)
            k = _key(namespace, name)
            if k not in bucket:
                raise NotFound(f"{kind} {k} not found")
            current = bucket[k]
            if current.metadata.finalizers:
                if current.metadata.deletion_timestamp is None:
                    current.metadata.deletion_timestamp = time.time()
                    current.metadata.resource_version = self._bump()
                    self._emit(EventType.MODIFIED, current)
                return copy.deepcopy(current)
            del bucket[k]
            current.metadata.resource_version = self._bump()
            self._emit(EventType.DELETED, current)
            return copy.deepcopy(current)

    # -- watch --------------------------------------------------------------

    def watch(self, kind: str, since_rv: Optional[int] = None) -> Watch:
        """Open an event stream for ``kind``. With ``since_rv``, replay
        buffered events with rv > since_rv first; raise :class:`Gone` if the
        buffer no longer reaches back that far."""
        with self._lock:
            w = Watch()
            if since_rv is not None and since_rv < self._last_rv:
                oldest_buffered = self._history[0][0] if self._history else None
                if oldest_buffered is not None and since_rv < oldest_buffered - 1:
                    raise Gone(
                        f"resource_version {since_rv} is too old "
                        f"(oldest buffered: {oldest_buffered})"
                    )
                for rv, ev in self._history:
                    if rv > since_rv and ev.object.kind == kind:
                        w._push(WatchEvent(ev.type, copy.deepcopy(ev.object)))
            self._watchers.append((kind, w))
            return w

    def stop_watch(self, w: Watch) -> None:
        with self._lock:
            self._watchers = [(k, x) for k, x in self._watchers if x is not w]
        w.stop()

    @property
    def resource_version(self) -> int:
        with self._lock:
            return self._last_rv
