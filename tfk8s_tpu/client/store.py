"""In-memory cluster state store with List/Watch — the L0 substrate.

This is the apiserver-shaped object store the whole control plane runs
against hermetically, the way the reference's operator family tests
"multi-node" against a fake clientset serving CRUD + watch from an
in-memory tracker (SURVEY.md §4). It implements the semantics the reference
documents for the real apiserver:

- **Optimistic concurrency**: every write bumps a store-wide monotonic
  ``resource_version``; updates carrying a stale version fail with
  :class:`Conflict` (the requeue-on-conflict path, SURVEY.md §7 hard part 2).
- **Watch streams**: ``watch(kind, since_rv)`` replays buffered events after
  ``since_rv`` then streams live — the List/Watch contract the Reflector
  consumes (images/informer1.png at k8s-operator.md:60). A ``since_rv``
  older than the history window raises :class:`Gone` (HTTP 410), forcing
  the reflector to relist — exactly the real protocol.
- **Finalizer-gated deletion**: deleting an object with finalizers only sets
  ``metadata.deletion_timestamp``; the object is removed when a controller
  strips the last finalizer (k8s-operator.md:36-43).
- **Durability** (``journal_dir``): every mutation appends one JSONL record
  to a write-ahead log before it is acknowledged; a snapshot compacts the
  log periodically. The WAL is **segmented per kind**
  (``wal-<Kind>.jsonl``): each record carries its resource_version, and
  replay merges every segment (plus a legacy single-stream ``wal.jsonl``
  if present) in rv order — so concurrent writers of DIFFERENT kinds
  serialize+append in parallel under their own kind locks instead of all
  funnelling one append stream through the store-wide commit lock (the
  durable-store counterpart of the per-kind-lock read/write split). A
  restarted store replays snapshot+segments and resumes the SAME
  resource_version sequence — the etcd-backed persistence the reference's
  REST contract presupposes (k8s-operator.md:33-43: deletion timestamps
  and finalizers only make sense on objects that survive a control-plane
  restart). Watchers reconnecting from a pre-restart rv that the replayed
  WAL no longer covers get :class:`Gone` and relist — the same recovery
  path as a compacted etcd.

**Copy-on-write** (client-go's shared-informer discipline, enforced via
``api/frozen.py``): every stored object is FROZEN once at the write
barrier; ``get``/``list``/watch events then share that frozen instance
by reference — zero copies on the read path, label-selector filtering
runs on the stored objects before anything is materialized, and a
consumer mutation raises :class:`~tfk8s_tpu.api.frozen.FrozenObjectError`
instead of silently corrupting shared state. Write verbs still RETURN a
private mutable copy (the pre-existing contract: callers edit the return
and send it back as the next update). Mutating clients go through
``thaw()`` (the typed client's ``get()`` does this for them).

**Locking** is two-level: one lock per kind serializes that kind's
bucket (so TPUJob status patches stop contending with Pod creates — the
expensive merge/encode/decode work runs under the kind lock only), and a
short store-wide commit lock orders rv assignment, the WAL append,
history, and watch fanout.
"""

from __future__ import annotations

import copy
import enum
import itertools
import json
import logging
import os
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass
from typing import Any, Deque, Dict, Iterator, List, Optional, Tuple

from tfk8s_tpu.api.frozen import FrozenObjectError, freeze, thaw  # noqa: F401

log = logging.getLogger(__name__)


class StoreError(Exception):
    pass


class NotFound(StoreError):
    pass


class AlreadyExists(StoreError):
    pass


class Conflict(StoreError):
    """Stale resource_version on update (optimistic-concurrency failure)."""


class Gone(StoreError):
    """Watch requested from a resource_version older than the event buffer —
    the client must relist (HTTP 410 semantics)."""


class Invalid(StoreError):
    """A syntactically well-formed request whose CONTENT cannot be
    processed (HTTP 422 semantics): e.g. a merge-patch carrying a
    non-numeric ``metadata.resourceVersion`` precondition. Distinct from
    admission rejection (which validates the merged OBJECT); this
    rejects the request itself."""


class Unavailable(StoreError):
    """The apiserver cannot be reached (connection refused/reset, 5xx) —
    transient by nature; callers with durable obligations (the kubelet's
    terminal phase writes) retry these, and ONLY these."""


class JournalCorrupt(StoreError):
    """A complete (newline-terminated) WAL record failed to decode —
    mid-file corruption or a schema break. Refusing to start is the only
    safe response: truncating would destroy acked records written after
    the bad one."""


class Unauthorized(StoreError):
    """No/invalid credentials against a secured apiserver (HTTP 401)."""


class Forbidden(StoreError):
    """Authenticated but not permitted — e.g. a read-only credential
    attempting a write (HTTP 403)."""


class EventType(str, enum.Enum):
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"


@dataclass
class WatchEvent:
    type: EventType
    # The SHARED frozen stored instance — do not mutate; thaw() for a
    # private mutable copy. (The event wrapper itself is per-watcher.)
    object: Any

    @property
    def kind(self) -> str:
        return self.object.kind


# Per-watcher pending-event bound: past it, same-key events coalesce
# (the slow-watcher policy below) so one stalled consumer's backlog is
# bounded by the number of DISTINCT live objects, not by event rate.
DEFAULT_WATCH_QUEUE = 1024

# Compaction normally runs opportunistically when a commit applies with no
# other commit in its journal window (_inflight == 0). Under sustained
# overlapping writes that moment may never come; once the WAL reaches this
# multiple of compact_every, new commits stall until the in-flight set
# drains and the compaction runs, bounding WAL growth.
FORCE_COMPACT_FACTOR = 2


def _coalesce_type(pending: EventType, new: EventType) -> EventType:
    """Merge two pending event types for one object so the consumer still
    converges to the right level-triggered state: anything followed by
    DELETED is a delete; an unseen ADDED absorbing updates stays ADDED.
    A pending DELETED is never merged INTO (the push path treats it as a
    barrier): collapsing delete+recreate would hide the deletion — and
    the identity (uid) change — from consumers whose delete path does
    real work (the kubelet stops the old pod's runner on delete)."""
    if new == EventType.DELETED:
        return EventType.DELETED
    if pending == EventType.ADDED:
        return EventType.ADDED
    return EventType.MODIFIED


class Watch:
    """One consumer's event stream. Iterate to receive events; ``stop()``
    ends the iteration (the stopCh analogue, k8s-operator.md:200-203).

    The queue holds per-watcher event WRAPPERS around shared frozen
    objects (no per-watcher deep copies). When a slow consumer's backlog
    reaches ``queue_limit``, further events for an object that already
    has one pending COALESCE into it (latest state wins — the informer
    contract is level-triggered, so intermediate states are droppable);
    events for new objects still append, bounding the backlog by the
    live-object count. ``coalesced_total`` counts the merges."""

    def __init__(self, queue_limit: int = DEFAULT_WATCH_QUEUE) -> None:
        self._cond = threading.Condition()
        self._items: Deque[WatchEvent] = deque()
        # object key -> its (single) pending event, for O(1) coalescing
        self._pending: Dict[str, WatchEvent] = {}
        self._queue_limit = queue_limit
        self._stopped = False
        self.coalesced_total = 0

    @staticmethod
    def _event_key(ev: WatchEvent) -> Optional[str]:
        try:
            return f"{ev.object.kind}/{ev.object.metadata.key}"
        except AttributeError:
            return None

    def _push(self, ev: WatchEvent) -> bool:
        """Enqueue one event (the wrapper becomes watcher-owned). Returns
        True when it coalesced into an already-pending event."""
        with self._cond:
            if self._stopped:
                return False
            key = self._event_key(ev)
            if (
                self._queue_limit
                and len(self._items) >= self._queue_limit
                and key is not None
            ):
                pending = self._pending.get(key)
                # a pending DELETED is a barrier: a re-ADD after it must
                # be delivered separately or the consumer never sees the
                # deletion (and the uid change) at all
                if pending is not None and pending.type != EventType.DELETED:
                    pending.type = _coalesce_type(pending.type, ev.type)
                    pending.object = ev.object
                    self.coalesced_total += 1
                    return True
            self._items.append(ev)
            if key is not None:
                self._pending[key] = ev
            self._cond.notify()
            return False

    def _pop_locked(self) -> WatchEvent:
        ev = self._items.popleft()
        key = self._event_key(ev)
        if key is not None and self._pending.get(key) is ev:
            del self._pending[key]
        return ev

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()

    def __iter__(self) -> Iterator[WatchEvent]:
        while True:
            with self._cond:
                while not self._items and not self._stopped:
                    self._cond.wait()
                if self._stopped:
                    return
                ev = self._pop_locked()
            yield ev

    def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        """Blocking pop with timeout; None on timeout or stop (already-
        queued events are still drained after stop)."""
        with self._cond:
            deadline = None if timeout is None else time.monotonic() + timeout
            while not self._items and not self._stopped:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(remaining)
            if self._items:
                return self._pop_locked()
            return None

    def next_batch(
        self, max_items: int = 256, timeout: Optional[float] = None
    ) -> List[WatchEvent]:
        """Blocking pop of up to ``max_items`` already-queued events — one
        wakeup drains a burst, which is what lets the Reflector apply N
        rapid updates as one batch. Empty list on timeout or stop."""
        with self._cond:
            deadline = None if timeout is None else time.monotonic() + timeout
            while not self._items and not self._stopped:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return []
                self._cond.wait(remaining)
            out: List[WatchEvent] = []
            while self._items and len(out) < max_items:
                out.append(self._pop_locked())
            return out


class _Segment:
    """One kind's WAL segment file. Appends are serialized by a private
    IO mutex (NOT the kind lock — compaction must be able to truncate a
    segment it couldn't take the kind lock for without deadlocking the
    kind→commit lock order). A failed append rolls the file back to its
    last good byte (the write-AHEAD contract: nothing half-written may
    survive to fuse with the next record)."""

    def __init__(self, path: str, fsync: bool):
        self.path = path
        self._fsync = fsync
        self._lock = threading.Lock()
        self._f = open(path, "ab")

    def append(self, line: bytes) -> None:
        """Append one complete record line, or raise leaving the file
        byte-identical to its pre-call state. OSError on unrecoverable
        rollback failure carries ``.rollback_failed = True``."""
        with self._lock:
            start = self._f.tell()
            try:
                self._f.write(line)
                self._f.flush()
                if self._fsync:
                    os.fsync(self._f.fileno())
            # ValueError = closed handle (an append racing close() past
            # the _closed check): same rollback treatment — the on-disk
            # bytes are already consistent, the reopen restores a handle
            except (OSError, ValueError) as e:
                try:
                    self._f.close()  # may raise re-flushing; superseded below
                except OSError:
                    pass
                try:
                    with open(self.path, "ab") as fix:
                        fix.truncate(start)
                    self._f = open(self.path, "ab")
                except OSError:
                    e.rollback_failed = True
                raise

    def truncate(self) -> None:
        with self._lock:
            self._f.truncate(0)
            self._f.seek(0)

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass


def _key(namespace: str, name: str) -> str:
    return f"{namespace}/{name}"


def match_labels(selector: Dict[str, str], labels: Dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


def merge_patch(target: Any, patch: Any) -> Any:
    """RFC 7386 JSON merge-patch: dicts merge recursively, ``null`` deletes
    a key, everything else (including lists) replaces wholesale."""
    if not isinstance(patch, dict):
        return copy.deepcopy(patch)
    result = dict(target) if isinstance(target, dict) else {}
    for k, v in patch.items():
        if v is None:
            result.pop(k, None)
        else:
            result[k] = merge_patch(result.get(k), v)
    return result


_MISSING = object()


def replace_patch(current: Any, desired: Any) -> Dict[str, Any]:
    """The inverse of :func:`merge_patch`: the smallest merge-patch that
    transforms ``current`` into exactly ``desired`` — keys present in
    current but absent from desired become explicit ``null`` deletions.
    This is how `apply` gets REPLACE semantics (a field removed from the
    manifest really goes away) over the merge-patch wire verb. Returns
    ``{}`` when nothing differs."""
    p = _replace_patch(current, desired)
    return {} if p is _MISSING else p


def _replace_patch(current: Any, desired: Any) -> Any:
    if isinstance(desired, dict) and isinstance(current, dict):
        patch = {}
        for k, v in desired.items():
            cv = current.get(k, _MISSING)
            if cv is _MISSING:
                patch[k] = copy.deepcopy(v)
            else:
                sub = _replace_patch(cv, v)
                if sub is not _MISSING:
                    patch[k] = sub
        for k in current:
            if k not in desired:
                patch[k] = None
        return patch if patch else _MISSING
    if current == desired:
        return _MISSING
    return copy.deepcopy(desired)


class ClusterStore:
    """Thread-safe object store keyed by (kind, namespace/name).

    With ``journal_dir`` set, the store is durable: ``snapshot.json`` holds
    a compacted full state, per-kind ``wal-<Kind>.jsonl`` segments the
    event log since; construction replays snapshot + segments (merged by
    rv; a legacy single-stream ``wal.jsonl`` is honored and retired at
    the next compaction) and resumes the rv sequence. ``fsync=False``
    trades power-loss durability for write latency (kill -9 survival only
    needs the page cache, so tests and the control-plane bench may
    disable it).

    Read contract (copy-on-write, module docstring): ``get``/``list``
    return the SHARED frozen stored instance; mutating it raises
    ``FrozenObjectError``. Write verbs return a private mutable copy.

    ``metrics`` (optional registry) exports
    ``tfk8s_watch_coalesced_total{kind}`` — events merged into a slow
    watcher's pending backlog instead of delivered individually.
    """

    def __init__(
        self,
        history_limit: int = 4096,
        journal_dir: Optional[str] = None,
        compact_every: int = 4096,
        fsync: bool = True,
        metrics=None,
        watch_queue_limit: int = DEFAULT_WATCH_QUEUE,
    ) -> None:
        # Store-wide commit lock: rv sequence, WAL, history ring, watcher
        # registry, fanout. Held only for the (cheap) commit step; the
        # expensive per-object work runs under the kind lock.
        self._lock = threading.RLock()
        # One lock per kind serializes that kind's bucket: a TPUJob
        # status patch (encode+merge+decode under its kind lock) no
        # longer blocks a concurrent Pod create. Lock order is ALWAYS
        # kind lock -> commit lock, never the reverse.
        self._kind_locks: Dict[str, threading.RLock] = {}
        self._objects: Dict[str, Dict[str, Any]] = {}
        self._rv = itertools.count(1)
        self._last_rv = 0
        # ring buffer of (rv, WatchEvent) for replay
        self._history: "deque[Tuple[int, WatchEvent]]" = deque(maxlen=history_limit)
        self._watchers: List[Tuple[str, Watch]] = []
        self._journal_dir = journal_dir
        self._compact_every = compact_every
        self._fsync = fsync
        self._metrics = metrics
        self._watch_queue_limit = watch_queue_limit
        # per-kind WAL segments (wal-<Kind>.jsonl), opened lazily on the
        # kind's first journaled write; replay merges them all by rv
        self._segments: Dict[str, _Segment] = {}
        self._wal_records = 0  # total records across all segments
        # commits between rv-assign and bucket-apply: compaction must not
        # run (and truncate a journaled-but-unapplied record) while any
        # are in flight
        self._inflight = 0
        # Set when the WAL outgrows FORCE_COMPACT_FACTOR x compact_every
        # while commits kept overlapping (the opportunistic
        # ``_inflight == 0`` check alone can starve forever under
        # sustained concurrent multi-kind writes). New commits then stall
        # at rv-assign until the last in-flight commit compacts, so WAL
        # growth is bounded at ~FORCE_COMPACT_FACTOR x the threshold.
        self._compact_pending = False
        self._compact_cv = threading.Condition(self._lock)
        # events at/below this rv are unreplayable (compacted away before
        # this process started); watchers older than it must relist
        self._base_rv = 0
        self._poisoned = False
        # close() flips this (under the commit lock): later writes skip
        # journaling instead of lazily re-opening a segment past close —
        # the pre-segment `_wal = None` semantics
        self._closed = False
        if metrics is not None:
            metrics.describe(
                "tfk8s_watch_coalesced_total",
                "Watch events merged into a slow watcher's pending "
                "backlog (latest state wins) instead of delivered "
                "individually.",
            )
        if journal_dir is not None:
            self._open_journal()

    def _kind_lock(self, kind: str) -> threading.RLock:
        lock = self._kind_locks.get(kind)
        if lock is None:
            with self._lock:
                lock = self._kind_locks.setdefault(kind, threading.RLock())
        return lock

    # -- journal ------------------------------------------------------------

    @property
    def _snapshot_path(self) -> str:
        return os.path.join(self._journal_dir, "snapshot.json")

    # single-stream WAL from pre-segment builds: replayed (merged by rv)
    # and removed at the next compaction
    @property
    def _legacy_wal_path(self) -> str:
        return os.path.join(self._journal_dir, "wal.jsonl")

    def _segment_path(self, kind: str) -> str:
        return os.path.join(self._journal_dir, f"wal-{kind}.jsonl")

    def _segment_paths_on_disk(self) -> List[str]:
        out = []
        for n in sorted(os.listdir(self._journal_dir)):
            if n == "wal.jsonl" or (n.startswith("wal-") and n.endswith(".jsonl")):
                out.append(os.path.join(self._journal_dir, n))
        return out

    def _read_segment(self, path: str) -> List[Tuple[int, EventType, Any]]:
        """Parse one WAL file into (rv, type, frozen obj) records. A torn
        FINAL line (kill -9 mid-write) is truncated away — everything
        before it was acknowledged with a complete line, so nothing acked
        is lost. A COMPLETE line that fails to decode is mid-file
        corruption (or a schema break); acked records may follow it, so
        refuse to start instead of truncating them away (etcd does the
        same)."""
        from tfk8s_tpu.api import serde  # api layer; no import cycle

        records: List[Tuple[int, EventType, Any]] = []
        good_end = 0
        with open(path, "rb") as f:
            for line in f:
                if not line.endswith(b"\n"):
                    log.warning(
                        "journal: truncating torn WAL tail of %s (%d bytes)",
                        os.path.basename(path), len(line),
                    )
                    break
                try:
                    rec = json.loads(line)
                    obj = freeze(serde.decode_object(rec["obj"]))
                    records.append((rec["rv"], EventType(rec["type"]), obj))
                except (ValueError, KeyError) as e:
                    raise JournalCorrupt(
                        f"{path} byte {good_end}: undecodable complete "
                        f"record: {e}"
                    ) from e
                good_end += len(line)
        with open(path, "ab") as fix:  # drop the torn tail on disk too
            fix.truncate(good_end)
        return records

    def _open_journal(self) -> None:
        """Replay snapshot + every WAL segment (merged by rv), then open
        segments for append lazily. Records at/below the snapshot rv are
        skipped — a legacy or straggler file can never roll applied state
        backwards."""
        from tfk8s_tpu.api import serde  # api layer; no import cycle

        os.makedirs(self._journal_dir, exist_ok=True)
        snap_rv = 0
        if os.path.exists(self._snapshot_path):
            with open(self._snapshot_path) as f:
                snap = json.load(f)
            snap_rv = snap["rv"]
            self._last_rv = snap_rv
            for data in snap["objects"]:
                obj = freeze(serde.decode_object(data))
                self._bucket(obj.kind)[obj.metadata.key] = obj
        self._base_rv = snap_rv
        records: List[Tuple[int, EventType, Any]] = []
        for path in self._segment_paths_on_disk():
            records.extend(self._read_segment(path))
        records.sort(key=lambda r: r[0])
        for rv, etype, obj in records:
            if rv <= snap_rv:
                continue  # already folded into the snapshot
            bucket = self._bucket(obj.kind)
            if etype == EventType.DELETED:
                bucket.pop(obj.metadata.key, None)
            else:
                bucket[obj.metadata.key] = obj
            self._last_rv = max(self._last_rv, rv)
            self._history.append((rv, WatchEvent(etype, obj)))
            self._wal_records += 1
        self._rv = itertools.count(self._last_rv + 1)

    def _segment(self, kind: str) -> _Segment:
        seg = self._segments.get(kind)
        if seg is None:
            with self._lock:
                if self._closed:
                    # a commit that captured journaling=True just before
                    # close() must fail loudly, not lazily re-create a
                    # segment file in a directory the owner believes dead
                    raise StoreError("store closed; refusing journal append")
                seg = self._segments.get(kind)
                if seg is None:
                    seg = _Segment(self._segment_path(kind), self._fsync)
                    self._segments[kind] = seg
        return seg

    def _journal(self, etype: EventType, obj: Any) -> None:
        """Append one event record to the object's KIND segment — called
        under the kind lock (not the store-wide commit lock), BEFORE the
        mutation is applied or fanned out, so nothing observable ever
        precedes the WAL. Per-kind segments mean two kinds' writers
        serialize and append concurrently; within a segment rv order holds
        because the kind lock covers the whole write.

        A failed append leaves the segment byte-identical to its last good
        state (see :class:`_Segment`). If even the rollback fails, the
        journal is poisoned and every further mutation is refused —
        availability is the right thing to sacrifice for a store whose
        point is durability."""
        from tfk8s_tpu.api import serde

        if self._poisoned:
            raise StoreError(
                "journal poisoned by an earlier unrecoverable write error; "
                "refusing mutations (restart the apiserver to re-replay)"
            )
        rec = {
            "rv": obj.metadata.resource_version,
            "type": etype.value,
            "obj": serde.to_dict(obj),
        }
        try:
            self._segment(obj.kind).append((json.dumps(rec) + "\n").encode())
        except (OSError, ValueError) as e:
            if getattr(e, "rollback_failed", False):
                self._poisoned = True
                log.error(
                    "journal: could not roll back failed append; poisoning "
                    "the store (segments intact through rv %d)", self._last_rv,
                )
            raise
        with self._lock:
            self._wal_records += 1

    def _compact(self) -> None:
        """Atomic snapshot of full state, then truncate every segment (and
        drop a legacy single-stream WAL). Watchers holding pre-snapshot
        rvs will relist via Gone after a restart — exactly etcd compaction
        semantics.

        Ordering matters: the snapshot (and, under fsync, its directory
        entry) must be durable BEFORE any segment is truncated, or a power
        cut between the two could leave the old snapshot + empty segments
        — losing everything since the previous compaction.

        Runs synchronously under the store lock with ``_inflight == 0``
        (enforced by the caller): a commit that journaled but has not yet
        applied would otherwise have its record truncated while missing
        from the snapshot — an acked-write hole. The pause is single-digit
        ms at this store's scale every ``compact_every`` writes."""
        from tfk8s_tpu.api import serde

        snap = {
            "rv": self._last_rv,
            "objects": [
                serde.to_dict(obj)
                for bucket in self._objects.values()
                for obj in bucket.values()
            ],
        }
        tmp = self._snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f)
            f.flush()
            if self._fsync:
                os.fsync(f.fileno())
        os.replace(tmp, self._snapshot_path)
        if self._fsync:
            # persist the rename itself before dropping the segments
            dir_fd = os.open(self._journal_dir, os.O_RDONLY)
            try:
                os.fsync(dir_fd)
            finally:
                os.close(dir_fd)
        # truncate through the live handles — no close/reopen window in
        # which a failure could leave the store without a WAL handle
        for seg in self._segments.values():
            seg.truncate()
        # stale on-disk files with no live handle (a kind not written
        # since restart, or the legacy single-stream WAL): their records
        # are all <= the snapshot rv now — remove them so replay never
        # re-reads them
        open_paths = {seg.path for seg in self._segments.values()}
        for path in self._segment_paths_on_disk():
            if path not in open_paths:
                try:
                    os.remove(path)
                except OSError:
                    pass  # replay skips <=snapshot-rv records anyway
        self._wal_records = 0

    def close(self) -> None:
        with self._lock:
            self._closed = True
            for seg in self._segments.values():
                seg.close()  # takes each segment's IO mutex: in-flight
                # appends finish before their handle closes
            self._segments = {}

    # -- internals ----------------------------------------------------------

    def _bump(self) -> int:
        self._last_rv = next(self._rv)
        return self._last_rv

    def _insert_history(self, rv: int, ev: WatchEvent) -> None:
        """Keep the replay ring rv-ascending. Commits of DIFFERENT kinds
        can reach the apply step out of rv order (rv assignment and apply
        are separate commit-lock sections, with the kind-parallel journal
        append between them); a short bubble from the tail restores order.
        Called under the commit lock."""
        h = self._history
        h.append((rv, ev))
        i = len(h) - 1
        while i > 0 and h[i - 1][0] > rv:
            h[i - 1], h[i] = h[i], h[i - 1]
            i -= 1

    def _commit(self, etype: EventType, stored: Any, apply) -> Any:
        """The write barrier: assign the rv (commit lock), FREEZE the
        object (the one structural walk per write — every read after this
        shares the frozen instance), journal to the kind's WAL segment
        (kind-parallel: only the kind lock is held), then apply the bucket
        mutation + history + watch fanout (commit lock again).
        Journal-before-apply keeps the log write-AHEAD: a failed append
        (ENOSPC, dead disk) raises to the client with NO state change, so
        readers can never observe an object that a restart would forget.
        Returns the frozen stored object."""
        with self._lock:
            # a forced compaction is waiting for in-flight commits to
            # drain: don't start a new journal window until it has run
            # (in-flight commits themselves never wait here, so the
            # drain — and this stall — is bounded)
            while self._compact_pending:
                self._compact_cv.wait()
            journaling = self._journal_dir is not None and not self._closed
            stored.metadata.resource_version = self._bump()
            if journaling:
                self._inflight += 1
        frozen_obj = freeze(stored)
        if journaling:
            try:
                self._journal(etype, frozen_obj)
            except BaseException:
                with self._lock:
                    self._inflight -= 1
                    if self._inflight == 0 and self._compact_pending:
                        # the commit this forced compaction was waiting on
                        # failed its append: unstall writers; the next
                        # successful write re-triggers compaction
                        # (_wal_records is still over threshold)
                        self._compact_pending = False
                        self._compact_cv.notify_all()
                raise
        with self._lock:
            apply()
            if journaling:
                self._inflight -= 1
                # compact only AFTER the mutation is applied, and only
                # with no other commit mid-flight — a snapshot taken while
                # a journaled-but-unapplied record exists would miss it
                # and the truncation would destroy its only copy. A
                # compaction failure must NOT fail the (already committed
                # and journaled) mutation: log it and retry at the next
                # write, when _wal_records will still be over threshold.
                if self._wal_records >= self._compact_every:
                    if self._inflight == 0:
                        try:
                            self._compact()
                        except OSError as e:
                            log.warning(
                                "journal: compaction failed (will retry): %s",
                                e,
                            )
                        finally:
                            if self._compact_pending:
                                self._compact_pending = False
                                self._compact_cv.notify_all()
                    elif self._wal_records >= (
                        self._compact_every * FORCE_COMPACT_FACTOR
                    ):
                        # overlapping commits have starved the
                        # opportunistic check past FORCE_COMPACT_FACTOR x
                        # the threshold: stall new commits at rv-assign so
                        # the in-flight set drains; the last one to apply
                        # takes the _inflight == 0 branch above and
                        # releases the waiters
                        self._compact_pending = True
            self._insert_history(
                stored.metadata.resource_version, WatchEvent(etype, frozen_obj)
            )
            kind = frozen_obj.kind
            for wkind, w in self._watchers:
                if wkind == kind:
                    # one shared frozen object; only the tiny per-watcher
                    # event wrapper is allocated here
                    if w._push(WatchEvent(etype, frozen_obj)) and (
                        self._metrics is not None
                    ):
                        self._metrics.inc(
                            "tfk8s_watch_coalesced_total", 1.0, {"kind": kind}
                        )
        return frozen_obj

    def _bucket(self, kind: str) -> Dict[str, Any]:
        bucket = self._objects.get(kind)
        if bucket is None:
            bucket = self._objects.setdefault(kind, {})
        return bucket

    # -- CRUD ---------------------------------------------------------------

    def create(self, obj: Any) -> Any:
        with self._kind_lock(obj.kind):
            bucket = self._bucket(obj.kind)
            k = obj.metadata.key
            if k in bucket:
                raise AlreadyExists(f"{obj.kind} {k} already exists")
            stored = copy.deepcopy(obj)  # the write-barrier copy
            stored.metadata.uid = stored.metadata.uid or uuid.uuid4().hex
            stored.metadata.creation_timestamp = (
                stored.metadata.creation_timestamp or time.time()
            )
            self._commit(
                EventType.ADDED, stored, apply=lambda: bucket.__setitem__(k, stored)
            )
            return copy.deepcopy(stored)

    def get(self, kind: str, namespace: str, name: str) -> Any:
        """Returns the SHARED frozen stored instance (zero-copy read);
        mutate via ``thaw()`` only."""
        with self._kind_lock(kind):
            try:
                return self._bucket(kind)[_key(namespace, name)]
            except KeyError:
                raise NotFound(f"{kind} {namespace}/{name} not found") from None

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> Tuple[List[Any], int]:
        """Returns (items, resource_version) — the rv is the point to start
        watching from (List-then-Watch, images/informer1.png). Items are
        the SHARED frozen stored instances: the namespace/label filter
        runs directly on stored objects and nothing is copied — a
        selective list over a large bucket costs only the matches'
        references."""
        with self._kind_lock(kind):
            items = [
                obj
                for obj in self._bucket(kind).values()
                if (namespace is None or obj.metadata.namespace == namespace)
                and (
                    not label_selector
                    or match_labels(label_selector, obj.metadata.labels)
                )
            ]
            return items, self._last_rv

    def update(self, obj: Any) -> Any:
        """Write with optimistic-concurrency check. Clearing the last
        finalizer on a deletion-marked object completes the delete."""
        with self._kind_lock(obj.kind):
            bucket = self._bucket(obj.kind)
            k = obj.metadata.key
            if k not in bucket:
                raise NotFound(f"{obj.kind} {k} not found")
            current = bucket[k]
            if obj.metadata.resource_version != current.metadata.resource_version:
                raise Conflict(
                    f"{obj.kind} {k}: resource_version "
                    f"{obj.metadata.resource_version} != {current.metadata.resource_version}"
                )
            stored = copy.deepcopy(obj)  # the write-barrier copy
            stored.metadata.uid = current.metadata.uid
            stored.metadata.creation_timestamp = current.metadata.creation_timestamp
            # deletion_timestamp is set by delete(), never by clients
            stored.metadata.deletion_timestamp = current.metadata.deletion_timestamp
            if (
                stored.metadata.deletion_timestamp is not None
                and not stored.metadata.finalizers
            ):
                self._commit(
                    EventType.DELETED, stored, apply=lambda: bucket.pop(k)
                )
                return copy.deepcopy(stored)
            self._commit(
                EventType.MODIFIED, stored, apply=lambda: bucket.__setitem__(k, stored)
            )
            return copy.deepcopy(stored)

    def update_status(self, obj: Any) -> Any:
        """Status-subresource write: applies ONLY ``obj.status`` (same
        optimistic-concurrency rules as update). Spec and metadata edits
        riding along are discarded — the real apiserver's subresource
        isolation, so a status writer can never clobber a concurrent spec
        change it hasn't seen."""
        with self._kind_lock(obj.kind):
            bucket = self._bucket(obj.kind)
            k = obj.metadata.key
            if k not in bucket:
                raise NotFound(f"{obj.kind} {k} not found")
            current = bucket[k]
            if obj.metadata.resource_version != current.metadata.resource_version:
                raise Conflict(
                    f"{obj.kind} {k}: resource_version "
                    f"{obj.metadata.resource_version} != {current.metadata.resource_version}"
                )
            if not hasattr(current, "status"):
                raise StoreError(f"{obj.kind} has no status subresource")
            stored = copy.deepcopy(current)  # thaws the frozen current
            stored.status = copy.deepcopy(obj.status)
            self._commit(
                EventType.MODIFIED, stored, apply=lambda: bucket.__setitem__(k, stored)
            )
            return copy.deepcopy(stored)

    def patch(
        self,
        kind: str,
        namespace: str,
        name: str,
        patch: Dict[str, Any],
        subresource: Optional[str] = None,
        admit=None,
    ) -> Any:
        """JSON merge-patch (RFC 7386) against the stored object — the
        PATCH verb the reference's typed client is built on
        (k8s-operator.md:33-34): writers touch only the fields they own,
        so an operator's status write and a CLI spec write never fight
        over resourceVersion the way whole-object PUTs do.

        ``patch`` is in the Kubernetes WIRE form (camelCase keys, as
        ``serde.to_wire`` produces). Unlike update(), no resourceVersion
        is required — last-writer-wins on the touched fields; a patch
        that DOES carry ``metadata.resourceVersion`` turns it into an
        optimistic precondition (k8s semantics). Server-owned metadata
        (uid, creationTimestamp, deletionTimestamp) cannot be patched.
        ``subresource='status'`` confines the patch to ``status`` exactly
        as update_status confines PUT. ``admit`` (server-side) runs on the
        MERGED object before anything commits — a rejected patch leaves no
        trace, the same boundary a validating webhook gives PUT."""
        from tfk8s_tpu.api import serde

        with self._kind_lock(kind):
            bucket = self._bucket(kind)
            k = _key(namespace, name)
            if k not in bucket:
                raise NotFound(f"{kind} {k} not found")
            current = bucket[k]
            md = patch.get("metadata")
            if md is not None and not isinstance(md, dict):
                # the apiserver rejects non-object ROOTS with 400; a
                # non-object metadata SUBTREE would otherwise crash the
                # resourceVersion read below as a 500 — same
                # request-content class: 422
                raise Invalid(
                    f"{kind} {k}: patch metadata must be an object, got "
                    f"{type(md).__name__}"
                )
            # the caller's patch is never mutated (no defensive deepcopy
            # needed): the rv precondition is read in place — if it rides
            # into the merge it is overwritten by the commit's fresh rv
            pre_rv = (md or {}).get("resourceVersion")
            if pre_rv is not None:
                try:
                    pre_rv = int(pre_rv)
                except (TypeError, ValueError):
                    # malformed precondition is a 422 on the request, not
                    # a 500 out of int() (ADVICE r5)
                    raise Invalid(
                        f"{kind} {k}: metadata.resourceVersion precondition "
                        f"must be numeric, got {pre_rv!r}"
                    ) from None
                if pre_rv != current.metadata.resource_version:
                    raise Conflict(
                        f"{kind} {k}: resourceVersion precondition {pre_rv} "
                        f"!= {current.metadata.resource_version}"
                    )
            if subresource == "status":
                # fast path: merge ONLY the status subtree — the
                # controller's per-reconcile write rides this, and a
                # full-object encode→merge→decode measured ~3x slower
                # than the subtree (control_plane bench, status_patches
                # vs creates). Identity/metadata/spec are untouched by
                # construction, so none of the protections below apply.
                if not hasattr(current, "status"):
                    raise StoreError(f"{kind} has no status subresource")
                merged_status = merge_patch(
                    serde.to_wire(current.status), patch.get("status", {})
                )
                stored = copy.deepcopy(current)  # thaws the frozen current
                # an explicit {"status": null} resets to the DEFAULT
                # status (key deletion semantics), never to None — a
                # None status would crash every later status reader
                stored.status = serde.from_dict(
                    type(current.status), merged_status or {}
                )
                self._commit(
                    EventType.MODIFIED, stored,
                    apply=lambda: bucket.__setitem__(k, stored),
                )
                return copy.deepcopy(stored)
            if subresource is not None:
                raise StoreError(f"unknown subresource {subresource!r}")
            # main-resource writes never touch status (subresource
            # isolation, mirroring update()); shallow-copy instead of
            # mutating the caller's patch
            if "status" in patch:
                patch = {pk: pv for pk, pv in patch.items() if pk != "status"}
            cur_wire = serde.to_wire(current)
            merged = merge_patch(cur_wire, patch)
            # identity is immutable under PATCH (the real apiserver rejects
            # name changes): restore kind/apiVersion/name/namespace BEFORE
            # decoding — a patched kind would otherwise re-type the object
            # into the wrong dataclass inside the old kind's bucket
            merged["kind"] = current.kind
            merged["apiVersion"] = cur_wire["apiVersion"]
            merged.setdefault("metadata", {})
            merged["metadata"]["name"] = current.metadata.name
            merged["metadata"]["namespace"] = current.metadata.namespace
            stored = serde.decode_object(merged)
            stored.metadata.uid = current.metadata.uid
            stored.metadata.creation_timestamp = current.metadata.creation_timestamp
            stored.metadata.deletion_timestamp = current.metadata.deletion_timestamp
            if admit is not None and subresource is None:
                admit(stored)  # raises -> nothing committed
            if (
                stored.metadata.deletion_timestamp is not None
                and not stored.metadata.finalizers
            ):
                # stripping the last finalizer via PATCH completes the
                # delete, exactly like update()
                self._commit(EventType.DELETED, stored, apply=lambda: bucket.pop(k))
                return copy.deepcopy(stored)
            self._commit(
                EventType.MODIFIED, stored, apply=lambda: bucket.__setitem__(k, stored)
            )
            return copy.deepcopy(stored)

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        """Finalizer-aware delete (k8s-operator.md:36-43): with finalizers
        present only ``deletion_timestamp`` is set; otherwise remove."""
        with self._kind_lock(kind):
            bucket = self._bucket(kind)
            k = _key(namespace, name)
            if k not in bucket:
                raise NotFound(f"{kind} {k} not found")
            current = bucket[k]
            if current.metadata.finalizers:
                if current.metadata.deletion_timestamp is None:
                    marked = copy.deepcopy(current)  # thaws the frozen current
                    marked.metadata.deletion_timestamp = time.time()
                    self._commit(
                        EventType.MODIFIED, marked,
                        apply=lambda: bucket.__setitem__(k, marked),
                    )
                    return copy.deepcopy(marked)
                return copy.deepcopy(current)
            removed = copy.deepcopy(current)  # thaws the frozen current
            self._commit(EventType.DELETED, removed, apply=lambda: bucket.pop(k))
            return copy.deepcopy(removed)

    # -- watch --------------------------------------------------------------

    def watch(
        self,
        kind: str,
        since_rv: Optional[int] = None,
        queue_limit: Optional[int] = None,
    ) -> Watch:
        """Open an event stream for ``kind``. With ``since_rv``, replay
        buffered events with rv > since_rv first; raise :class:`Gone` if the
        buffer no longer reaches back that far. Delivered event objects are
        the shared frozen stored instances (WatchEvent docstring);
        ``queue_limit`` overrides the store's per-watcher pending bound."""
        with self._kind_lock(kind), self._lock:
            w = Watch(
                queue_limit=self._watch_queue_limit
                if queue_limit is None
                else queue_limit
            )
            if since_rv is not None and since_rv < self._last_rv:
                oldest_buffered = self._history[0][0] if self._history else None
                # Unreplayable when the bookmark predates the compaction
                # floor (_base_rv: events folded into the snapshot before
                # this process started) or fell off the history ring — the
                # client must relist (410), the same contract as a
                # compacted etcd. An empty ring above the floor is NOT
                # Gone: the only missing events are commits still
                # mid-flight, and this watcher (registered under the
                # commit lock) receives them at their fanout.
                if since_rv < self._base_rv or (
                    oldest_buffered is not None and since_rv < oldest_buffered - 1
                ):
                    raise Gone(
                        f"resource_version {since_rv} is too old "
                        f"(oldest buffered: {oldest_buffered})"
                    )
                for rv, ev in self._history:
                    if rv > since_rv and ev.object.kind == kind:
                        w._push(WatchEvent(ev.type, ev.object))
            self._watchers.append((kind, w))
            return w

    def stop_watch(self, w: Watch) -> None:
        with self._lock:
            self._watchers = [(k, x) for k, x in self._watchers if x is not w]
        w.stop()

    @property
    def resource_version(self) -> int:
        with self._lock:
            return self._last_rv
