"""Self-signed PKI for the apiserver — dev/test certificate plumbing.

The reference's client stack exists to carry TLS + credentials to a
secured apiserver: ``clientcmd.BuildConfigFromFlags(kubeconfig)`` →
``rest.Config`` → ``rest.RESTClientFor`` (`/root/reference/k8s-operator.md:93-97`,
images/tf5-tf6) — a real (GKE) apiserver is always HTTPS + authn. This
module is the `kubeadm init phase certs` analogue: mint a CA and issue
server/client certs so the hermetic cluster can run the SAME secured
wire the north star requires, and tests can prove the 401/403 boundary.

Everything returns PEM bytes; nothing here touches global state. Uses the
``cryptography`` package (baked into the image).
"""

from __future__ import annotations

import datetime
import ipaddress
import os
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

# Dev certs: 10 years, like kubeadm's CA default. Short-lived rotation is
# a deployment concern; the hermetic cluster only needs validity.
_VALID_DAYS = 3650


@dataclass
class CertKeyPair:
    """A PEM certificate + its PEM private key."""

    cert_pem: bytes
    key_pem: bytes

    def write(self, directory: str, name: str) -> Tuple[str, str]:
        """Write ``<name>.crt`` / ``<name>.key`` under ``directory``;
        returns their paths. Key files are chmod 0600 (same discipline as
        kubeconfig credentials)."""
        os.makedirs(directory, exist_ok=True)
        cert_path = os.path.join(directory, f"{name}.crt")
        key_path = os.path.join(directory, f"{name}.key")
        with open(cert_path, "wb") as f:
            f.write(self.cert_pem)
        with open(key_path, "wb") as f:
            f.write(self.key_pem)
        os.chmod(key_path, 0o600)
        return cert_path, key_path


def _key() -> ec.EllipticCurvePrivateKey:
    # P-256: small certs, fast handshakes; what GKE's own CA issues.
    return ec.generate_private_key(ec.SECP256R1())


def _key_pem(key) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )


def _name(cn: str, org: Optional[str] = None) -> x509.Name:
    attrs = [x509.NameAttribute(NameOID.COMMON_NAME, cn)]
    if org:
        # client-cert group convention: k8s reads O= as the user's groups
        attrs.append(x509.NameAttribute(NameOID.ORGANIZATION_NAME, org))
    return x509.Name(attrs)


def _validity(builder: x509.CertificateBuilder) -> x509.CertificateBuilder:
    now = datetime.datetime.now(datetime.timezone.utc)
    return builder.not_valid_before(
        now - datetime.timedelta(minutes=5)  # clock-skew slack
    ).not_valid_after(now + datetime.timedelta(days=_VALID_DAYS))


def generate_ca(cn: str = "tfk8s-ca") -> CertKeyPair:
    """Mint a self-signed CA (the cluster root of trust)."""
    key = _key()
    name = _name(cn)
    builder = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .add_extension(x509.BasicConstraints(ca=True, path_length=0), critical=True)
        .add_extension(
            x509.KeyUsage(
                digital_signature=True, key_cert_sign=True, crl_sign=True,
                content_commitment=False, key_encipherment=False,
                data_encipherment=False, key_agreement=False,
                encipher_only=False, decipher_only=False,
            ),
            critical=True,
        )
    )
    cert = _validity(builder).sign(key, hashes.SHA256())
    return CertKeyPair(
        cert.public_bytes(serialization.Encoding.PEM), _key_pem(key)
    )


def issue_cert(
    ca: CertKeyPair,
    cn: str,
    sans: Sequence[str] = ("127.0.0.1", "localhost"),
    client: bool = False,
    org: Optional[str] = None,
) -> CertKeyPair:
    """Issue a leaf cert signed by ``ca``.

    ``client=False`` → serverAuth EKU + SubjectAltNames (IPs recognized
    and encoded as IPAddress entries, everything else DNS);
    ``client=True`` → clientAuth EKU, identity = CN (groups = O, the k8s
    client-cert convention).
    """
    ca_key = serialization.load_pem_private_key(ca.key_pem, password=None)
    ca_cert = x509.load_pem_x509_certificate(ca.cert_pem)
    key = _key()
    builder = (
        x509.CertificateBuilder()
        .subject_name(_name(cn, org))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .add_extension(x509.BasicConstraints(ca=False, path_length=None), critical=True)
        .add_extension(
            x509.ExtendedKeyUsage(
                [ExtendedKeyUsageOID.CLIENT_AUTH if client
                 else ExtendedKeyUsageOID.SERVER_AUTH]
            ),
            critical=False,
        )
    )
    if not client:
        alt: list = []
        for san in sans:
            try:
                alt.append(x509.IPAddress(ipaddress.ip_address(san)))
            except ValueError:
                alt.append(x509.DNSName(san))
        builder = builder.add_extension(
            x509.SubjectAlternativeName(alt), critical=False
        )
    cert = _validity(builder).sign(ca_key, hashes.SHA256())
    return CertKeyPair(
        cert.public_bytes(serialization.Encoding.PEM), _key_pem(key)
    )


def cert_common_name(der_or_pem_cert: bytes) -> str:
    """CN of a certificate (DER from ``getpeercert(True)`` or PEM)."""
    try:
        cert = x509.load_der_x509_certificate(der_or_pem_cert)
    except ValueError:
        cert = x509.load_pem_x509_certificate(der_or_pem_cert)
    cns = cert.subject.get_attributes_for_oid(NameOID.COMMON_NAME)
    return cns[0].value if cns else ""
