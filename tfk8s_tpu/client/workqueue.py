"""Deduplicating, rate-limited work queue — SURVEY.md C16.

Implements the k8s workqueue contract the sample controller relies on
(``workqueue.NewNamedRateLimitingQueue(DefaultControllerRateLimiter(),...)``,
k8s-operator.md:87,108):

- **Dedup**: an item added while queued coalesces; an item added while
  *being processed* is marked dirty and requeued when ``done()`` is called —
  so one worker never processes the same key concurrently with another,
  which is the single-writer guarantee the whole reconcile design leans on
  (SURVEY.md §5 'Race detection').
- **Get/Done accounting** (k8s-operator.md:155,172): every ``get()`` must be
  paired with ``done()``.
- **Rate limiting**: ``add_rate_limited`` applies max(per-item exponential
  backoff, overall token bucket); ``forget`` resets an item's failure count.
- **Shutdown**: ``shut_down()`` drains waiters; ``get()`` returns
  ``(None, True)`` — the ``queue.ShutDown()`` path (k8s-operator.md:200-202).
- **Instrumentation** (the k8s workqueue MetricsProvider, optional): with
  a ``metrics`` registry the queue exports depth (gauge), time-in-queue
  (histogram, add→get per item), and requeues (counter), all labeled
  ``{queue="<name>"}`` — the three numbers that tell a saturated control
  plane apart from a slow one.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Hashable, List, Optional, Set, Tuple

from tfk8s_tpu.client.ratelimit import MaxOfRateLimiter, default_controller_rate_limiter


class WorkQueue:
    """FIFO with dedup + processing accounting."""

    def __init__(self, name: str = "", metrics=None):
        self.name = name
        self._cond = threading.Condition()
        self._queue: List[Hashable] = []
        self._dirty: Set[Hashable] = set()
        self._processing: Set[Hashable] = set()
        self._shutting_down = False
        self._metrics = metrics
        self._labels = {"queue": name or "default"}
        # item -> monotonic add time (first add wins: a coalesced re-add
        # must not reset the clock — the waiting work is the old one's)
        self._added_at: dict = {}
        # item -> the queue latency its most recent get() observed, for
        # the controller's retroactive `dequeue` span
        self._last_latency: dict = {}
        if metrics is not None:
            metrics.describe(
                "workqueue.depth", "Items waiting in the work queue."
            )
            metrics.describe(
                "workqueue.queue_seconds",
                "Time an item waited in the queue before a worker took it.",
            )
            metrics.describe(
                "workqueue.requeues_total",
                "Items re-added while processing or via rate-limited retry.",
            )

    def _export_depth_locked(self) -> None:
        # call sites skip the call entirely when no registry is attached
        # (the hot add/get path must not pay even the no-op frame)
        self._metrics.set_gauge(
            "workqueue.depth", float(len(self._queue)), self._labels
        )

    def add(self, item: Hashable) -> None:
        with self._cond:
            if self._shutting_down or item in self._dirty:
                return
            self._dirty.add(item)
            self._added_at.setdefault(item, time.monotonic())
            if item in self._processing:
                if self._metrics is not None:
                    self._metrics.inc("workqueue.requeues_total", 1.0, self._labels)
                return  # will requeue on done()
            self._queue.append(item)
            if self._metrics is not None:
                self._export_depth_locked()
            self._cond.notify()

    def get(self, timeout: Optional[float] = None) -> Tuple[Optional[Hashable], bool]:
        """Blocks for the next item. Returns ``(item, False)`` or
        ``(None, True)`` when shutting down (or ``(None, False)`` on
        timeout)."""
        with self._cond:
            deadline = None if timeout is None else time.monotonic() + timeout
            while not self._queue and not self._shutting_down:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None, False
                self._cond.wait(remaining)
            if not self._queue:
                return None, True  # shutting down and drained
            item = self._queue.pop(0)
            self._processing.add(item)
            self._dirty.discard(item)
            added = self._added_at.pop(item, None)
            if added is not None:
                latency = time.monotonic() - added
                self._last_latency[item] = latency
                if self._metrics is not None:
                    self._metrics.observe(
                        "workqueue.queue_seconds", latency, self._labels
                    )
            if self._metrics is not None:
                self._export_depth_locked()
            return item, False

    def pop_queue_latency(self, item: Hashable) -> Optional[float]:
        """Seconds the item just dequeued spent waiting (consumed on
        read) — lets the caller attach the wait to its trace."""
        with self._cond:
            return self._last_latency.pop(item, None)

    def done(self, item: Hashable) -> None:
        with self._cond:
            # unconsumed latency is stale once processing ends — drop it
            # so the dict stays bounded by in-flight items
            self._last_latency.pop(item, None)
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._added_at.setdefault(item, time.monotonic())
                if self._metrics is not None:
                    self._export_depth_locked()
                self._cond.notify()

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)

    def shut_down(self) -> None:
        with self._cond:
            self._shutting_down = True
            self._cond.notify_all()

    @property
    def shutting_down(self) -> bool:
        with self._cond:
            return self._shutting_down


class DelayingQueue(WorkQueue):
    """WorkQueue + ``add_after``: a background timer thread moves items into
    the queue when their delay expires."""

    def __init__(self, name: str = "", metrics=None):
        super().__init__(name, metrics=metrics)
        self._heap: List[Tuple[float, int, Hashable]] = []
        self._seq = itertools.count()
        self._timer_cond = threading.Condition()
        self._timer = threading.Thread(target=self._timer_loop, daemon=True)
        self._timer.start()

    def add_after(self, item: Hashable, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._timer_cond:
            heapq.heappush(self._heap, (time.monotonic() + delay, next(self._seq), item))
            self._timer_cond.notify()

    def _timer_loop(self) -> None:
        while True:
            with self._timer_cond:
                while not self._heap:
                    self._timer_cond.wait(0.5)
                    if self.shutting_down and not self._heap:
                        return
                when, _, item = self._heap[0]
                now = time.monotonic()
                if when > now:
                    self._timer_cond.wait(when - now)
                    continue
                heapq.heappop(self._heap)
            self.add(item)


class RateLimitingQueue(DelayingQueue):
    """The ``NewNamedRateLimitingQueue`` analogue."""

    def __init__(
        self,
        name: str = "",
        rate_limiter: Optional[MaxOfRateLimiter] = None,
        metrics=None,
    ):
        super().__init__(name, metrics=metrics)
        self.rate_limiter = rate_limiter or default_controller_rate_limiter()

    def add_rate_limited(self, item: Hashable) -> None:
        if self._metrics is not None:
            self._metrics.inc("workqueue.requeues_total", 1.0, self._labels)
        self.add_after(item, self.rate_limiter.when(item))

    def forget(self, item: Hashable) -> None:
        self.rate_limiter.forget(item)

    def num_requeues(self, item: Hashable) -> int:
        return self.rate_limiter.retries(item)
