"""L2 client layer + L0 fake substrate (SURVEY.md C10-C16).

- ``store``      in-memory cluster state with List/Watch + finalizers (L0 fake)
- ``clientset``  typed, token-bucket rate-limited clients
- ``fake``       action-recording test double with reactors
- ``informer``   reflector -> delta stream -> indexed cache -> callbacks
- ``listers``    read-only cache access
- ``workqueue``  dedup'ing rate-limited queue
- ``ratelimit``  token bucket + per-item backoff limiters
"""

from tfk8s_tpu.api.frozen import FrozenObjectError, freeze, is_frozen, thaw  # noqa: F401
from tfk8s_tpu.client.store import (  # noqa: F401
    AlreadyExists,
    ClusterStore,
    Conflict,
    EventType,
    Gone,
    Invalid,
    NotFound,
    Watch,
    WatchEvent,
)
from tfk8s_tpu.client.clientset import Clientset, RESTConfig, TypedClient  # noqa: F401
from tfk8s_tpu.client.fake import Action, FakeClientset  # noqa: F401
from tfk8s_tpu.client.informer import (  # noqa: F401
    DeletedFinalStateUnknown,
    Indexer,
    ResourceEventHandler,
    SharedIndexInformer,
    deletion_handling_key,
    meta_namespace_key,
    wait_for_cache_sync,
)
from tfk8s_tpu.client.listers import Lister  # noqa: F401
from tfk8s_tpu.client.workqueue import (  # noqa: F401
    DelayingQueue,
    RateLimitingQueue,
    WorkQueue,
)
