"""Fake clientset — the test double of the L2 clients (SURVEY.md C12).

Like the reference's ``clientset/versioned/fake`` package, this serves CRUD
+ watch from an in-memory tracker and **records every action** so tests
assert on what the controller *did* (create/update/delete verbs) rather
than on cluster state alone — the exact hermetic-test shape of SURVEY.md §4.
Reactors let tests inject failures (conflicts, transient errors) to drive
the controller's retry paths.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from tfk8s_tpu.client.clientset import Clientset, RESTConfig, TypedClient
from tfk8s_tpu.client.store import ClusterStore, Watch


@dataclasses.dataclass
class Action:
    verb: str  # create | get | list | update | update_status | delete | watch
    kind: str
    namespace: str
    name: str = ""


# A reactor receives the Action and may raise, or return (handled, result).
Reactor = Callable[[Action, Any], Tuple[bool, Any]]


class _RecordingClient(TypedClient):
    def __init__(self, parent: "FakeClientset", *args, **kw):
        super().__init__(*args, **kw)
        self._parent = parent

    def _react(self, action: Action, obj: Any = None):
        return self._parent._dispatch(action, obj)

    def _do_create(self, obj: Any) -> Any:
        # Overriding the unmetered body (not create itself) keeps
        # per-object action records and reactors working when the
        # controller batches a gang through create_many.
        a = Action("create", self.kind, self._ns(obj), obj.metadata.name)
        handled, result = self._react(a, obj)
        if handled:
            return result
        if obj.kind == "TPUJob":
            # admission parity with the real apiserver (_admit): defaults
            # are applied by the API machinery before persisting, so the
            # STORED object carries them — controllers must not need a
            # whole-object write to make defaults durable. (Validation is
            # deliberately skipped: tests create odd specs on purpose.)
            from tfk8s_tpu.api import set_defaults

            set_defaults(obj)
        elif obj.kind == "TPUServe":
            from tfk8s_tpu.api import set_serve_defaults

            set_serve_defaults(obj)
        return super()._do_create(obj)

    def get(self, name: str) -> Any:
        a = Action("get", self.kind, self._ns(), name)
        handled, result = self._react(a)
        return result if handled else super().get(name)

    def list(self, label_selector: Optional[Dict[str, str]] = None):
        a = Action("list", self.kind, self.namespace or "*")
        handled, result = self._react(a)
        return result if handled else super().list(label_selector)

    def update(self, obj: Any) -> Any:
        a = Action("update", self.kind, self._ns(obj), obj.metadata.name)
        handled, result = self._react(a, obj)
        return result if handled else super().update(obj)

    def update_status(self, obj: Any) -> Any:
        a = Action("update_status", self.kind, self._ns(obj), obj.metadata.name)
        handled, result = self._react(a, obj)
        return result if handled else super().update_status(obj)

    def patch(self, name: str, patch) -> Any:
        a = Action("patch", self.kind, self._ns(), name)
        handled, result = self._react(a, patch)
        return result if handled else super().patch(name, patch)

    def patch_status(self, name: str, patch) -> Any:
        a = Action("patch_status", self.kind, self._ns(), name)
        handled, result = self._react(a, patch)
        return result if handled else super().patch_status(name, patch)

    def delete(self, name: str) -> Any:
        a = Action("delete", self.kind, self._ns(), name)
        handled, result = self._react(a)
        return result if handled else super().delete(name)

    def watch(self, since_rv: Optional[int] = None) -> Watch:
        a = Action("watch", self.kind, self.namespace or "*")
        self._react(a)
        return super().watch(since_rv)


class FakeClientset(Clientset):
    """Clientset over a private store, with action recording + reactors."""

    def __init__(self, store: Optional[ClusterStore] = None):
        # Generous limits: fakes shouldn't slow tests down.
        super().__init__(store or ClusterStore(), RESTConfig(qps=1e6, burst=1_000_000))
        self._actions: List[Action] = []
        self._reactors: List[Tuple[str, str, Reactor]] = []
        self._lock = threading.Lock()

    @property
    def store(self) -> ClusterStore:
        return self._store

    def _dispatch(self, action: Action, obj: Any) -> Tuple[bool, Any]:
        with self._lock:
            self._actions.append(action)
            reactors = list(self._reactors)
        for verb, kind, fn in reactors:
            if verb in ("*", action.verb) and kind in ("*", action.kind):
                handled, result = fn(action, obj)
                if handled:
                    return True, result
        return False, None

    def prepend_reactor(self, verb: str, kind: str, fn: Reactor) -> None:
        with self._lock:
            self._reactors.insert(0, (verb, kind, fn))

    def actions(self, verb: Optional[str] = None, kind: Optional[str] = None) -> List[Action]:
        with self._lock:
            return [
                a
                for a in self._actions
                if (verb is None or a.verb == verb) and (kind is None or a.kind == kind)
            ]

    def clear_actions(self) -> None:
        with self._lock:
            self._actions.clear()

    def _client(self, kind: str, namespace: Optional[str]) -> _RecordingClient:
        return _RecordingClient(self, self._store, kind, namespace, self._limiter)

    def tpujobs(self, namespace: Optional[str] = "default"):
        return self._client("TPUJob", namespace)

    def tpuserves(self, namespace: Optional[str] = "default"):
        return self._client("TPUServe", namespace)

    def pods(self, namespace: Optional[str] = "default"):
        return self._client("Pod", namespace)

    def services(self, namespace: Optional[str] = "default"):
        return self._client("Service", namespace)

    def generic(self, kind: str, namespace: Optional[str] = "default"):
        return self._client(kind, namespace)
