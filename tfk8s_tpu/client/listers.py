"""Listers: read-only indexed access over an informer's cache — SURVEY.md
C14 (``pkg/client/listers/tensorflow/v1alpha1/tfjob.go``; the
``store.Indexer.GetByKey(key)`` read path at k8s-operator.md:160).

Results are the SHARED frozen cached instances (copy-on-write contract,
``api/frozen.py``): a lister read costs a dict lookup, and mutating a
result raises ``FrozenObjectError`` instead of corrupting the cache.
Controllers that edit an object first take a private copy (the TPUJob
controller's ``serde.roundtrip`` / ``thaw``).
"""

from __future__ import annotations

from typing import Any, List, Optional

from tfk8s_tpu.client.informer import Indexer
from tfk8s_tpu.client.store import NotFound, match_labels


class Lister:
    def __init__(self, indexer: Indexer, kind: str = ""):
        self._indexer = indexer
        self.kind = kind

    def get(self, namespace: str, name: str) -> Any:
        obj = self._indexer.get_by_key(f"{namespace}/{name}")
        if obj is None:
            raise NotFound(f"{self.kind} {namespace}/{name} not in cache")
        return obj

    def get_by_key(self, key: str) -> Optional[Any]:
        """Cache read; None means 'object deleted' — the branch the sample
        worker takes at k8s-operator.md:162-164."""
        return self._indexer.get_by_key(key)

    def list(
        self,
        namespace: Optional[str] = None,
        label_selector: Optional[dict] = None,
    ) -> List[Any]:
        items = self._indexer.list(namespace)
        if label_selector:
            items = [o for o in items if match_labels(label_selector, o.metadata.labels)]
        return items
