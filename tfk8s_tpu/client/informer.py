"""Shared informer machinery — SURVEY.md C13.

The architecture is the reference's exactly (images/informer1.png at
k8s-operator.md:60): a **Reflector** List/Watches the (fake) apiserver,
feeds a local **indexed store**, and dispatches OnAdd/OnUpdate/OnDelete
callbacks from which controllers enqueue keys. Reads during reconcile hit
the local store, never the server (k8s-operator.md:160).

Protocol details carried over:

- List-then-Watch from the returned resource_version; on a ``Gone`` (410)
  the reflector **relists** and the store ``replace()`` computes the diff —
  items that vanished during the gap are delivered as deletions with the
  last-known state (the DeletedFinalStateUnknown path,
  k8s-operator.md:162-164 'deleted-object handling').
- ``wait_for_cache_sync`` blocks until the initial list has been replayed
  into handlers (cache.WaitForCacheSync, k8s-operator.md:192).
- Optional periodic **resync** re-delivers OnUpdate for every cached object
  — the level-triggered safety net.

Copy-on-write (client-go's shared-informer discipline, enforced by
``api/frozen.py``): the indexer stores FROZEN objects and every read —
``get_by_key``, ``list``, handler dispatch — returns the shared frozen
instance by reference. Handlers and lister consumers must treat objects
as read-only (mutation raises ``FrozenObjectError``); a consumer that
needs a mutable view thaws its own copy. Objects arriving from a local
:class:`~tfk8s_tpu.client.store.ClusterStore` are already frozen (no-op);
objects decoded off a remote watch are frozen once on cache admission.

The reflector consumes the watch in BATCHES (``Watch.next_batch``) and
coalesces per object key before touching the cache: N rapid pod updates
for one job collapse into one cache apply + one handler dispatch (one
workqueue add) instead of N — the burst behavior that kept the
workqueue's mean depth pinned at ~54 in the pre-COW bench.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from tfk8s_tpu.api.frozen import freeze
from tfk8s_tpu.client.store import EventType, Gone, WatchEvent
from tfk8s_tpu.utils.logging import get_logger

log = get_logger("informer")

# how many queued watch events one reflector wakeup drains at most
_BATCH_MAX = 256


def meta_namespace_key(obj: Any) -> str:
    """MetaNamespaceKeyFunc: ``namespace/name``."""
    return obj.metadata.key


@dataclasses.dataclass
class DeletedFinalStateUnknown:
    """Wrapper delivered to OnDelete when the deletion was observed via a
    relist gap rather than a watch event (cache.DeletionHandlingMeta-
    NamespaceKeyFunc's reason to exist, k8s-operator.md:132-139)."""

    key: str
    obj: Any


def deletion_handling_key(obj: Any) -> str:
    if isinstance(obj, DeletedFinalStateUnknown):
        return obj.key
    return meta_namespace_key(obj)


class Indexer:
    """Thread-safe keyed cache with a namespace index — the informer's local
    store (``GetByKey`` read path, k8s-operator.md:160). Stores frozen
    objects and shares them by reference on every read (module
    docstring): a cache hit costs a dict lookup, never a deep copy."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._items: Dict[str, Any] = {}

    def get_by_key(self, key: str) -> Optional[Any]:
        with self._lock:
            return self._items.get(key)

    def list(self, namespace: Optional[str] = None) -> List[Any]:
        with self._lock:
            return [
                o
                for o in self._items.values()
                if namespace is None or o.metadata.namespace == namespace
            ]

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._items)

    def add(self, obj: Any) -> None:
        with self._lock:
            self._items[meta_namespace_key(obj)] = freeze(obj)

    def delete(self, key: str) -> None:
        with self._lock:
            self._items.pop(key, None)

    def replace(self, objs: List[Any]) -> List[Any]:
        """Atomically swap contents; returns the displaced objects that are
        absent from the new set (for DeletedFinalStateUnknown delivery)."""
        with self._lock:
            new = {meta_namespace_key(o): freeze(o) for o in objs}
            gone = [o for k, o in self._items.items() if k not in new]
            self._items = new
            return gone


@dataclasses.dataclass
class ResourceEventHandler:
    """OnAdd/OnUpdate/OnDelete callback set (k8s-operator.md:121-128).
    Handlers receive the SHARED frozen cached objects — read-only."""

    on_add: Optional[Callable[[Any], None]] = None
    on_update: Optional[Callable[[Any, Any], None]] = None
    on_delete: Optional[Callable[[Any], None]] = None


class SharedIndexInformer:
    """Reflector + indexer + handler dispatch for one kind."""

    def __init__(
        self, client, resync_period: float = 0.0, name: str = "", metrics=None
    ):
        """``client`` is a TypedClient-shaped object with ``list()`` and
        ``watch(since_rv)`` — the ListWatch pair (k8s-operator.md:110-118).
        With a ``metrics`` registry the informer counts delivered deltas
        by type, per-key coalesced deltas, resync sweeps, and relists,
        labeled ``{informer="<name>"}`` — a relist storm or resync flood
        shows up on /metrics instead of only in latency."""
        self._client = client
        self._resync_period = resync_period
        self.name = name or getattr(client, "kind", "informer")
        self._metrics = metrics
        if metrics is not None:
            metrics.describe(
                "informer.deltas_total",
                "Watch/list deltas delivered to handlers, by type.",
            )
            metrics.describe(
                "informer.coalesced_deltas_total",
                "Same-key watch events collapsed into one cache apply + "
                "one dispatch by reflector batching.",
            )
            metrics.describe(
                "informer.resyncs_total",
                "Periodic resync sweeps re-delivering the cached set.",
            )
            metrics.describe(
                "informer.relists_total",
                "Full relists (initial sync, 410 Gone, error recovery).",
            )
        self.indexer = Indexer()
        self._handlers: List[ResourceEventHandler] = []
        self._synced = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stop: Optional[threading.Event] = None
        self._watch = None
        self._lock = threading.Lock()

    # -- public api ---------------------------------------------------------

    def add_event_handler(self, handler: ResourceEventHandler) -> None:
        with self._lock:
            self._handlers.append(handler)

    @property
    def has_synced(self) -> bool:
        return self._synced.is_set()

    def run(self, stop: threading.Event) -> None:
        """Start the reflector loop in its own thread (the ``go
        informer.Run(stopCh)`` of k8s-operator.md:189)."""
        self._stop = stop
        self._thread = threading.Thread(
            target=self._reflector_loop, name=f"reflector-{self.name}", daemon=True
        )
        self._thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            # unblock a pending watch read
            if self._watch is not None:
                self._watch.stop()
            self._thread.join(timeout)

    # -- handler dispatch ---------------------------------------------------

    def _count_delta(self, delta_type: str) -> None:
        if self._metrics is not None:
            self._metrics.inc(
                "informer.deltas_total", 1.0,
                {"informer": self.name, "type": delta_type},
            )

    def _dispatch_add(self, obj: Any) -> None:
        self._count_delta("add")
        for h in list(self._handlers):
            if h.on_add:
                self._guard(h.on_add, obj)

    def _dispatch_update(self, old: Any, new: Any) -> None:
        self._count_delta("update")
        for h in list(self._handlers):
            if h.on_update:
                self._guard(h.on_update, old, new)

    def _dispatch_delete(self, obj: Any) -> None:
        self._count_delta("delete")
        for h in list(self._handlers):
            if h.on_delete:
                self._guard(h.on_delete, obj)

    def _guard(self, fn, *args) -> None:
        # A handler exception must not kill the reflector (which would force
        # a relist storm); handlers are not this thread's code.
        try:
            fn(*args)
        except Exception:  # noqa: BLE001
            log.exception("%s: event handler raised", self.name)

    # -- reflector ----------------------------------------------------------

    def _list_and_sync(self) -> int:
        """Initial (or recovery) List: replace the cache, emit synthetic
        events for the diff, return the rv to watch from. Objects already
        cached are delivered as updates (old, new) — not as adds — so
        update filters keep working across relists; objects that vanished
        during a watch gap are delivered as DeletedFinalStateUnknown."""
        if self._metrics is not None:
            self._metrics.inc(
                "informer.relists_total", 1.0, {"informer": self.name}
            )
        items, rv = self._client.list()
        old_objs = {k: self.indexer.get_by_key(k) for k in self.indexer.keys()}
        displaced = self.indexer.replace(items)
        for obj in displaced:
            self._dispatch_delete(DeletedFinalStateUnknown(meta_namespace_key(obj), obj))
        # dispatch the frozen CACHED instances, not the raw list items —
        # one freeze on admission, shared everywhere after
        for obj in items:
            key = meta_namespace_key(obj)
            cached = self.indexer.get_by_key(key)
            old = old_objs.get(key)
            if old is None:
                self._dispatch_add(cached)
            else:
                self._dispatch_update(old, cached)
        return rv

    def _reflector_loop(self) -> None:
        assert self._stop is not None
        backoff = 0.05
        rv: Optional[int] = None
        last_resync = time.monotonic()
        while not self._stop.is_set():
            try:
                if rv is None:
                    rv = self._list_and_sync()
                    self._synced.set()
                try:
                    self._watch = self._client.watch(since_rv=rv)
                except Gone:
                    log.info("%s: watch rv %s too old; relisting", self.name, rv)
                    rv = None
                    continue
                backoff = 0.05
                while not self._stop.is_set():
                    evs = self._watch.next_batch(_BATCH_MAX, timeout=0.2)
                    if not evs:
                        if self._watch._stopped:  # server closed the stream
                            break
                        if (
                            self._resync_period
                            and time.monotonic() - last_resync > self._resync_period
                        ):
                            last_resync = time.monotonic()
                            if self._metrics is not None:
                                self._metrics.inc(
                                    "informer.resyncs_total", 1.0,
                                    {"informer": self.name},
                                )
                            for obj in self.indexer.list():
                                self._dispatch_update(obj, obj)
                        continue
                    rv = max(
                        rv or 0,
                        max(ev.object.metadata.resource_version for ev in evs),
                    )
                    self._handle_batch(evs)
            except Exception:  # noqa: BLE001 — reflector must survive anything
                log.exception("%s: reflector error; backing off %.2fs", self.name, backoff)
                self._stop.wait(backoff)
                backoff = min(backoff * 2, 5.0)
                rv = None  # full relist on recovery
            finally:
                if self._watch is not None:
                    self._watch.stop()
                    self._watch = None

    def _handle_batch(self, evs: List[WatchEvent]) -> None:
        """Per-key delta coalescing: within one drained batch a newer
        event for a key SUPERSEDES its older pending one — N rapid status
        updates for one pod become one cache apply + one handler pass
        (one workqueue add downstream). A DELETED is a barrier in both
        directions of a recreate: a delete is never superseded by the
        re-ADD that follows it (consumers' delete paths do real work —
        the kubelet stops the old pod's runner on delete, and the uid
        changes across the gap), so delete+recreate dispatches BOTH.
        Ordering follows each surviving event's position, with superseded
        keys moving to their last occurrence — causal order of what is
        actually delivered is preserved."""
        if len(evs) == 1:
            self._handle_event(evs[0])
            return
        out: List[Optional[WatchEvent]] = []
        last_idx: Dict[str, int] = {}
        coalesced = 0
        for ev in evs:
            key = meta_namespace_key(ev.object)
            idx = last_idx.get(key)
            if idx is not None and out[idx] is not None and (
                out[idx].type != EventType.DELETED
            ):
                out[idx] = None  # superseded by the newer event
                coalesced += 1
            out.append(ev)
            last_idx[key] = len(out) - 1
        if coalesced and self._metrics is not None:
            self._metrics.inc(
                "informer.coalesced_deltas_total", float(coalesced),
                {"informer": self.name},
            )
        for ev in out:
            if ev is not None:
                self._handle_event(ev)

    def _handle_event(self, ev) -> None:
        key = meta_namespace_key(ev.object)
        if ev.type == EventType.ADDED:
            old = self.indexer.get_by_key(key)
            self.indexer.add(ev.object)
            if old is None:
                self._dispatch_add(ev.object)
            else:  # replayed ADD for an object we already have
                self._dispatch_update(old, ev.object)
        elif ev.type == EventType.MODIFIED:
            old = self.indexer.get_by_key(key)
            self.indexer.add(ev.object)
            if old is None:
                # a coalesced ADD+MODIFY (or a modify for an object the
                # cache never saw): the consumer-visible delta is an add
                self._dispatch_add(ev.object)
            else:
                self._dispatch_update(old, ev.object)
        elif ev.type == EventType.DELETED:
            self.indexer.delete(key)
            self._dispatch_delete(ev.object)


def wait_for_cache_sync(
    stop: threading.Event, *informers: SharedIndexInformer, timeout: float = 30.0
) -> bool:
    """Block until every informer has replayed its initial List
    (cache.WaitForCacheSync, k8s-operator.md:192)."""
    deadline = time.monotonic() + timeout
    for inf in informers:
        remaining = deadline - time.monotonic()
        if remaining <= 0 or stop.is_set():
            return False
        if not inf._synced.wait(remaining):
            return False
    return True
