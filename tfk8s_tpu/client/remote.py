"""Remote cluster client: the ClusterStore interface over HTTP.

The other half of the process boundary (client/apiserver.py): a
:class:`RemoteStore` presents the exact CRUD+watch surface of the
in-memory :class:`~tfk8s_tpu.client.store.ClusterStore`, but every call is
a REST request to ``/apis/<group>/<version>/namespaces/*/<plural>/...``
(the path shape of k8s-operator.md:33-34). A
:class:`~tfk8s_tpu.client.clientset.Clientset` built over a RemoteStore is
therefore a *real* remote client — the informers, controller, and kubelet
run unchanged against it, which is the swap the reference performs with
``clientcmd.BuildConfigFromFlags → NewForConfig``
(k8s-operator.md:92-102, images/tf4-tf6).

Kubeconfig: a small JSON file ``{"server": "http://host:port", "qps": ...,
"burst": ...}`` — :func:`load_kubeconfig` + :func:`clientset_from_kubeconfig`
mirror the reference's kubeconfig-flag path (`k8s-operator.md:206-207`).

Watch streams: one long-lived HTTP response per watch, newline-delimited
JSON events pumped into a :class:`~tfk8s_tpu.client.store.Watch` by a
reader thread; ``stop()`` closes the socket, which the server notices via
its heartbeat write. HTTP errors map back to the store's exception types
(404 NotFound / 409 AlreadyExists|Conflict / 410 Gone), so reflector
relist-on-Gone works identically across the wire.
"""

from __future__ import annotations

import json
import socket
import threading
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from tfk8s_tpu import API_VERSION
from tfk8s_tpu.api import serde
from tfk8s_tpu.client.apiserver import KIND_TO_PLURAL
from tfk8s_tpu.client.clientset import Clientset, RESTConfig
from tfk8s_tpu.client.store import (
    AlreadyExists,
    Conflict,
    EventType,
    Gone,
    NotFound,
    StoreError,
    Watch,
    WatchEvent,
)
from tfk8s_tpu.utils.logging import get_logger

log = get_logger("remote")

_TIMEOUT_S = 30.0
# Watch-stream read deadline: several server heartbeat intervals (the
# server writes a HEARTBEAT line every 2s when idle). A silent peer death
# — power loss, network partition with no FIN/RST — surfaces as
# socket.timeout in the pump, which ends the watch; the reflector then
# relists, exactly the liveness contract the heartbeats exist for.
_WATCH_READ_TIMEOUT_S = 10.0


def _map_error(status: int, reason: str, message: str) -> StoreError:
    if status == 404:
        return NotFound(message)
    if status == 409 and reason == "AlreadyExists":
        return AlreadyExists(message)
    if status == 409:
        return Conflict(message)
    if status == 410:
        return Gone(message)
    return StoreError(f"HTTP {status} {reason}: {message}")


class RemoteWatch(Watch):
    """Watch fed by a reader thread draining one HTTP watch response."""

    def __init__(self, resp) -> None:
        super().__init__()
        self._resp = resp
        self._thread = threading.Thread(
            target=self._pump, daemon=True, name="remote-watch"
        )
        self._thread.start()

    def _pump(self) -> None:
        try:
            for raw in self._resp:
                if self._stopped:
                    break
                line = raw.strip()
                if not line:
                    continue
                data = json.loads(line)
                if data.get("type") == "HEARTBEAT":
                    continue
                self._push(
                    WatchEvent(
                        EventType(data["type"]), serde.decode_object(data["object"])
                    )
                )
        except (OSError, ValueError):
            pass  # connection torn down (stop() or server shutdown)
        finally:
            self.stop()

    def stop(self) -> None:
        super().stop()
        try:
            self._resp.close()
        except OSError:
            pass


class RemoteStore:
    """ClusterStore-shaped facade over the HTTP apiserver."""

    def __init__(self, base_url: str, timeout: float = _TIMEOUT_S):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    # -- request plumbing ---------------------------------------------------

    def _path(self, kind: str, namespace: Optional[str], name: Optional[str] = None) -> str:
        plural = KIND_TO_PLURAL[kind]
        if namespace is None:
            p = f"/apis/{API_VERSION}/{plural}"
        else:
            p = f"/apis/{API_VERSION}/namespaces/{namespace}/{plural}"
        if name is not None:
            p += f"/{urllib.parse.quote(name)}"
        return p

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[Dict[str, Any]] = None,
        query: Optional[Dict[str, str]] = None,
        stream: bool = False,
    ):
        url = self.base_url + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url, data=data, method=method,
            headers={"Content-Type": "application/json"} if data else {},
        )
        try:
            resp = urllib.request.urlopen(
                req, timeout=_WATCH_READ_TIMEOUT_S if stream else self.timeout
            )
        except urllib.error.HTTPError as e:
            payload = {}
            try:
                payload = json.loads(e.read() or b"{}")
            except ValueError:
                pass
            raise _map_error(
                e.code, payload.get("reason", ""), payload.get("message", str(e))
            ) from None
        except urllib.error.URLError as e:
            raise StoreError(f"apiserver unreachable at {url}: {e.reason}") from None
        if stream:
            return resp
        return json.loads(resp.read() or b"{}")

    # -- the ClusterStore surface ------------------------------------------

    def create(self, obj: Any) -> Any:
        data = self._request(
            "POST",
            self._path(obj.kind, obj.metadata.namespace or "default"),
            body=serde.to_wire(obj),
        )
        return serde.decode_object(data)

    def get(self, kind: str, namespace: str, name: str) -> Any:
        data = self._request("GET", self._path(kind, namespace, name))
        return serde.decode_object(data)

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> Tuple[List[Any], int]:
        query: Dict[str, str] = {}
        if label_selector:
            query["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in sorted(label_selector.items())
            )
        data = self._request("GET", self._path(kind, namespace), query=query or None)
        items = [serde.decode_object(d) for d in data.get("items", [])]
        # k8s ListMeta.resourceVersion (string); legacy top-level int kept
        # for mixed-version rollouts
        rv = data.get("metadata", {}).get(
            "resourceVersion", data.get("resourceVersion", 0)
        )
        return items, int(rv)

    def update(self, obj: Any) -> Any:
        data = self._request(
            "PUT",
            self._path(obj.kind, obj.metadata.namespace or "default", obj.metadata.name),
            body=serde.to_wire(obj),
        )
        return serde.decode_object(data)

    def update_status(self, obj: Any) -> Any:
        data = self._request(
            "PUT",
            self._path(obj.kind, obj.metadata.namespace or "default", obj.metadata.name)
            + "/status",
            body=serde.to_wire(obj),
        )
        return serde.decode_object(data)

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        data = self._request("DELETE", self._path(kind, namespace, name))
        return serde.decode_object(data)

    def watch(self, kind: str, since_rv: Optional[int] = None) -> Watch:
        query = {"watch": "1"}
        if since_rv is not None:
            query["resourceVersion"] = str(since_rv)
        resp = self._request(
            "GET", self._path(kind, None), query=query, stream=True
        )
        return RemoteWatch(resp)

    def stop_watch(self, w: Watch) -> None:
        w.stop()

    def healthz(self) -> bool:
        try:
            data = self._request("GET", "/healthz")
            return data.get("status") == "ok"
        except StoreError:
            return False


@dataclass
class Kubeconfig:
    """Minimal kubeconfig: where the apiserver lives + client limits."""

    server: str
    qps: float = 50.0
    burst: int = 100
    user_agent: str = "tfk8s-tpu-operator"


def load_kubeconfig(path: str) -> Kubeconfig:
    with open(path) as f:
        data = json.load(f)
    return Kubeconfig(
        server=data["server"],
        qps=float(data.get("qps", 50.0)),
        burst=int(data.get("burst", 100)),
        user_agent=data.get("user_agent", "tfk8s-tpu-operator"),
    )


def clientset_from_kubeconfig(path_or_cfg) -> Clientset:
    """``BuildConfigFromFlags → NewForConfig`` in one step
    (k8s-operator.md:92-102): kubeconfig → RemoteStore → rate-limited
    Clientset."""
    cfg = (
        path_or_cfg
        if isinstance(path_or_cfg, Kubeconfig)
        else load_kubeconfig(path_or_cfg)
    )
    store = RemoteStore(cfg.server)
    return Clientset.new_for_config(
        store,
        RESTConfig(qps=cfg.qps, burst=cfg.burst, user_agent=cfg.user_agent),
    )
