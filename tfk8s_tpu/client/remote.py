"""Remote cluster client: the ClusterStore interface over HTTP.

The other half of the process boundary (client/apiserver.py): a
:class:`RemoteStore` presents the exact CRUD+watch surface of the
in-memory :class:`~tfk8s_tpu.client.store.ClusterStore`, but every call is
a REST request to ``/apis/<group>/<version>/namespaces/*/<plural>/...``
(the path shape of k8s-operator.md:33-34). A
:class:`~tfk8s_tpu.client.clientset.Clientset` built over a RemoteStore is
therefore a *real* remote client — the informers, controller, and kubelet
run unchanged against it, which is the swap the reference performs with
``clientcmd.BuildConfigFromFlags → NewForConfig``
(k8s-operator.md:92-102, images/tf4-tf6).

Kubeconfig: :func:`load_kubeconfig` accepts BOTH a small flat JSON file
``{"server": "http://host:port", "qps": ..., "token": ..., ...}`` and a
real Kubernetes kubeconfig (YAML or JSON: clusters/users/contexts with
``certificate-authority-data`` etc.) — the reference's kubeconfig-flag
path (`k8s-operator.md:206-207`, ``clientcmd.BuildConfigFromFlags`` at
:93). Credentials ride every request the way ``rest.Config`` carries
them (images/tf5-tf6): the CA (path or inline PEM) pins the server cert,
``token`` becomes ``Authorization: Bearer``, and a client cert/key pair
is presented for mTLS; ``user_agent`` is the DefaultKubernetesUserAgent
equivalent.

Watch streams: one long-lived HTTP response per watch, newline-delimited
JSON events pumped into a :class:`~tfk8s_tpu.client.store.Watch` by a
reader thread; ``stop()`` closes the socket, which the server notices via
its heartbeat write. HTTP errors map back to the store's exception types
(404 NotFound / 409 AlreadyExists|Conflict / 410 Gone), so reflector
relist-on-Gone works identically across the wire.
"""

from __future__ import annotations

import atexit
import base64
import json
import os
import shutil
import socket
import ssl
import tempfile
import threading
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from tfk8s_tpu import API_VERSION
from tfk8s_tpu.api import serde
from tfk8s_tpu.client.apiserver import KIND_TO_PLURAL
from tfk8s_tpu.client.clientset import Clientset, RESTConfig
from tfk8s_tpu.client.store import (
    AlreadyExists,
    Conflict,
    EventType,
    Forbidden,
    Gone,
    Invalid,
    NotFound,
    StoreError,
    Unauthorized,
    Unavailable,
    Watch,
    WatchEvent,
)
from tfk8s_tpu.utils.logging import get_logger

log = get_logger("remote")

_TIMEOUT_S = 30.0
# Watch-stream read deadline: several server heartbeat intervals (the
# server writes a HEARTBEAT line every 2s when idle). A silent peer death
# — power loss, network partition with no FIN/RST — surfaces as
# socket.timeout in the pump, which ends the watch; the reflector then
# relists, exactly the liveness contract the heartbeats exist for.
_WATCH_READ_TIMEOUT_S = 10.0


def _map_error(status: int, reason: str, message: str) -> StoreError:
    if status == 401:
        return Unauthorized(message)
    if status == 403:
        return Forbidden(message)
    if status == 404:
        return NotFound(message)
    if status == 409 and reason == "AlreadyExists":
        return AlreadyExists(message)
    if status == 409:
        return Conflict(message)
    if status == 410:
        return Gone(message)
    if status == 422:
        # typed (callers can catch Invalid) but message-compatible with
        # the generic branch this status used to fall through to
        return Invalid(f"HTTP {status} {reason}: {message}")
    if status >= 500:
        # server-side failure: transient by contract, retryable
        return Unavailable(f"HTTP {status} {reason}: {message}")
    return StoreError(f"HTTP {status} {reason}: {message}")


class RemoteWatch(Watch):
    """Watch fed by a reader thread draining one HTTP watch response."""

    def __init__(self, resp) -> None:
        super().__init__()
        self._resp = resp
        self._thread = threading.Thread(
            target=self._pump, daemon=True, name="remote-watch"
        )
        self._thread.start()

    def _pump(self) -> None:
        try:
            for raw in self._resp:
                if self._stopped:
                    break
                line = raw.strip()
                if not line:
                    continue
                data = json.loads(line)
                if data.get("type") == "HEARTBEAT":
                    continue
                self._push(
                    WatchEvent(
                        EventType(data["type"]), serde.decode_object(data["object"])
                    )
                )
        except (OSError, ValueError):
            pass  # connection torn down (stop() or server shutdown)
        finally:
            self.stop()

    def stop(self) -> None:
        super().stop()
        try:
            self._resp.close()
        except OSError:
            pass


class RemoteStore:
    """ClusterStore-shaped facade over the HTTP(S) apiserver.

    ``token`` rides as ``Authorization: Bearer`` on every request;
    ``ssl_context`` carries the CA pin and any client cert (build one from
    a kubeconfig with :func:`build_ssl_context`)."""

    def __init__(
        self,
        base_url: str,
        timeout: float = _TIMEOUT_S,
        token: Optional[str] = None,
        ssl_context: Optional[ssl.SSLContext] = None,
        user_agent: str = "tfk8s-tpu-operator",
    ):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.token = token
        self.ssl_context = ssl_context
        self.user_agent = user_agent

    # -- request plumbing ---------------------------------------------------

    def _path(self, kind: str, namespace: Optional[str], name: Optional[str] = None) -> str:
        plural = KIND_TO_PLURAL[kind]
        if namespace is None:
            p = f"/apis/{API_VERSION}/{plural}"
        else:
            p = f"/apis/{API_VERSION}/namespaces/{namespace}/{plural}"
        if name is not None:
            p += f"/{urllib.parse.quote(name)}"
        return p

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[Dict[str, Any]] = None,
        query: Optional[Dict[str, str]] = None,
        stream: bool = False,
        content_type: str = "application/json",
    ):
        url = self.base_url + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        data = json.dumps(body).encode() if body is not None else None
        headers: Dict[str, str] = {"User-Agent": self.user_agent}
        if data:
            headers["Content-Type"] = content_type
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        req = urllib.request.Request(url, data=data, method=method, headers=headers)
        try:
            resp = urllib.request.urlopen(
                req,
                timeout=_WATCH_READ_TIMEOUT_S if stream else self.timeout,
                context=self.ssl_context,
            )
        except urllib.error.HTTPError as e:
            payload = {}
            try:
                payload = json.loads(e.read() or b"{}")
            except ValueError:
                pass
            raise _map_error(
                e.code, payload.get("reason", ""), payload.get("message", str(e))
            ) from None
        except urllib.error.URLError as e:
            raise Unavailable(
                f"apiserver unreachable at {url}: {e.reason}"
            ) from None
        if stream:
            return resp
        return json.loads(resp.read() or b"{}")

    # -- the ClusterStore surface ------------------------------------------

    def create(self, obj: Any) -> Any:
        data = self._request(
            "POST",
            self._path(obj.kind, obj.metadata.namespace or "default"),
            body=serde.to_wire(obj),
        )
        return serde.decode_object(data)

    def get(self, kind: str, namespace: str, name: str) -> Any:
        data = self._request("GET", self._path(kind, namespace, name))
        return serde.decode_object(data)

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> Tuple[List[Any], int]:
        query: Dict[str, str] = {}
        if label_selector:
            query["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in sorted(label_selector.items())
            )
        data = self._request("GET", self._path(kind, namespace), query=query or None)
        items = [serde.decode_object(d) for d in data.get("items", [])]
        # k8s ListMeta.resourceVersion (string); legacy top-level int kept
        # for mixed-version rollouts
        rv = data.get("metadata", {}).get(
            "resourceVersion", data.get("resourceVersion", 0)
        )
        return items, int(rv)

    def update(self, obj: Any) -> Any:
        data = self._request(
            "PUT",
            self._path(obj.kind, obj.metadata.namespace or "default", obj.metadata.name),
            body=serde.to_wire(obj),
        )
        return serde.decode_object(data)

    def update_status(self, obj: Any) -> Any:
        data = self._request(
            "PUT",
            self._path(obj.kind, obj.metadata.namespace or "default", obj.metadata.name)
            + "/status",
            body=serde.to_wire(obj),
        )
        return serde.decode_object(data)

    def patch(
        self,
        kind: str,
        namespace: str,
        name: str,
        patch: Dict[str, Any],
        subresource: Optional[str] = None,
        admit=None,  # server-side concern; accepted for surface parity
    ) -> Any:
        path = self._path(kind, namespace, name)
        if subresource:
            path += f"/{subresource}"
        data = self._request(
            "PATCH", path, body=patch,
            content_type="application/merge-patch+json",
        )
        return serde.decode_object(data)

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        data = self._request("DELETE", self._path(kind, namespace, name))
        return serde.decode_object(data)

    def watch(self, kind: str, since_rv: Optional[int] = None) -> Watch:
        query = {"watch": "1"}
        if since_rv is not None:
            query["resourceVersion"] = str(since_rv)
        resp = self._request(
            "GET", self._path(kind, None), query=query, stream=True
        )
        return RemoteWatch(resp)

    def stop_watch(self, w: Watch) -> None:
        w.stop()

    def healthz(self) -> bool:
        try:
            data = self._request("GET", "/healthz")
            return data.get("status") == "ok"
        except StoreError:
            return False


@dataclass
class Kubeconfig:
    """The ``rest.Config`` equivalent: where the apiserver lives, the
    credentials to present, and client limits. CA/client-cert material may
    be a file path or inline PEM (the ``*-data`` kubeconfig fields)."""

    server: str
    qps: float = 50.0
    burst: int = 100
    user_agent: str = "tfk8s-tpu-operator"
    token: str = ""
    certificate_authority: str = ""  # path to CA bundle (PEM)
    certificate_authority_data: str = ""  # inline PEM
    client_certificate: str = ""  # path (PEM)
    client_key: str = ""  # path (PEM)
    client_certificate_data: str = ""  # inline PEM
    client_key_data: str = ""  # inline PEM
    insecure_skip_tls_verify: bool = False


def _b64_or_pem(value: str) -> str:
    """kubeconfig ``*-data`` fields are base64(PEM); accept raw PEM too."""
    if value.lstrip().startswith("-----BEGIN"):
        return value
    return base64.b64decode(value).decode()


def _from_k8s_kubeconfig(data: Dict[str, Any]) -> Kubeconfig:
    """Parse the real kubeconfig shape (clusters/users/contexts +
    current-context), honoring ``*-data`` inline credentials. A context
    naming a nonexistent cluster/user is an ERROR (kubectl parity) —
    silently picking another cluster would connect somewhere else with
    the wrong credentials."""
    by_name = lambda items, key: {i["name"]: i[key] for i in items or []}  # noqa: E731
    clusters = by_name(data.get("clusters"), "cluster")
    users = by_name(data.get("users"), "user")
    contexts = by_name(data.get("contexts"), "context")
    if not clusters:
        raise ValueError("kubeconfig has no clusters")
    ctx_name = data.get("current-context") or next(iter(contexts), "")
    if ctx_name and ctx_name not in contexts:
        # a dangling current-context must error too — falling back to
        # the first cluster would silently connect somewhere else
        raise ValueError(
            f'kubeconfig current-context "{ctx_name}" does not exist'
        )
    ctx = contexts.get(ctx_name, {})

    def pick(pool: Dict[str, Any], ref: str, what: str) -> Dict[str, Any]:
        if ref:
            if ref not in pool:
                raise ValueError(
                    f'kubeconfig context "{ctx_name}" references unknown '
                    f'{what} "{ref}"'
                )
            return pool[ref]
        return next(iter(pool.values()), {})

    cluster = pick(clusters, ctx.get("cluster", ""), "cluster")
    user = pick(users, ctx.get("user", ""), "user")
    return Kubeconfig(
        server=cluster["server"],
        certificate_authority=cluster.get("certificate-authority", ""),
        certificate_authority_data=_b64_or_pem(
            cluster.get("certificate-authority-data", "") or ""
        ),
        insecure_skip_tls_verify=bool(cluster.get("insecure-skip-tls-verify", False)),
        token=user.get("token", ""),
        client_certificate=user.get("client-certificate", ""),
        client_key=user.get("client-key", ""),
        client_certificate_data=_b64_or_pem(user.get("client-certificate-data", "") or ""),
        client_key_data=_b64_or_pem(user.get("client-key-data", "") or ""),
    )


def load_kubeconfig(path: str) -> Kubeconfig:
    """Load either format: a real kubeconfig (YAML/JSON with ``clusters``)
    or the flat JSON dev form."""
    with open(path) as f:
        raw = f.read()
    try:
        data = json.loads(raw)
    except ValueError:
        import yaml  # kubeconfigs in the wild are YAML

        data = yaml.safe_load(raw)
    if "clusters" in data:
        return _from_k8s_kubeconfig(data)
    return Kubeconfig(
        server=data["server"],
        qps=float(data.get("qps", 50.0)),
        burst=int(data.get("burst", 100)),
        user_agent=data.get("user_agent", "tfk8s-tpu-operator"),
        token=data.get("token", ""),
        certificate_authority=data.get("certificate_authority", ""),
        # *_data fields accept base64(PEM) or raw PEM in BOTH formats —
        # the field name mirrors the k8s convention, so honor it here too
        certificate_authority_data=_b64_or_pem(
            data.get("certificate_authority_data", "") or ""
        ),
        client_certificate=data.get("client_certificate", ""),
        client_key=data.get("client_key", ""),
        client_certificate_data=_b64_or_pem(
            data.get("client_certificate_data", "") or ""
        ),
        client_key_data=_b64_or_pem(data.get("client_key_data", "") or ""),
        insecure_skip_tls_verify=bool(data.get("insecure_skip_tls_verify", False)),
    )


def build_ssl_context(cfg: Kubeconfig) -> Optional[ssl.SSLContext]:
    """TLS client context from kubeconfig credentials: CA pin (path or
    inline PEM) + optional client cert/key for mTLS. Returns None for
    plain-HTTP servers. Server certs must carry the host as a SAN
    (hostname verification stays ON unless insecure_skip_tls_verify)."""
    if not cfg.server.startswith("https"):
        return None
    ctx = ssl.create_default_context(
        cafile=cfg.certificate_authority or None,
        cadata=cfg.certificate_authority_data or None,
    )
    if cfg.insecure_skip_tls_verify:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    if cfg.client_certificate and cfg.client_key:
        ctx.load_cert_chain(cfg.client_certificate, cfg.client_key)
    elif cfg.client_certificate_data and cfg.client_key_data:
        ctx.load_cert_chain(
            *_stage_client_pair(cfg.client_certificate_data, cfg.client_key_data)
        )
    return ctx


# load_cert_chain needs files; inline PEM pairs are staged into private
# tempdirs ONCE per distinct pair (rebuilding clients must not leak a new
# key file per call) and removed at interpreter exit.
_staged_pairs: Dict[Tuple[str, str], Tuple[str, str]] = {}
_staged_dirs: List[str] = []


def _stage_client_pair(cert_pem: str, key_pem: str) -> Tuple[str, str]:
    pair = (cert_pem, key_pem)
    if pair not in _staged_pairs:
        d = tempfile.mkdtemp(prefix="tfk8s-client-cert-")
        cert_path = os.path.join(d, "client.crt")
        key_path = os.path.join(d, "client.key")
        with open(cert_path, "w") as f:
            f.write(cert_pem)
        with open(key_path, "w") as f:
            f.write(key_pem)
        os.chmod(key_path, 0o600)  # kubeconfig-credential discipline
        _staged_pairs[pair] = (cert_path, key_path)
        _staged_dirs.append(d)
    return _staged_pairs[pair]


@atexit.register
def _cleanup_staged_pairs() -> None:
    for d in _staged_dirs:
        shutil.rmtree(d, ignore_errors=True)
    _staged_dirs.clear()
    _staged_pairs.clear()


def store_from_kubeconfig(cfg: Kubeconfig) -> RemoteStore:
    """Kubeconfig → credentialed RemoteStore (rest.RESTClientFor parity)."""
    return RemoteStore(
        cfg.server,
        token=cfg.token or None,
        ssl_context=build_ssl_context(cfg),
        user_agent=cfg.user_agent,
    )


def clientset_from_kubeconfig(path_or_cfg) -> Clientset:
    """``BuildConfigFromFlags → NewForConfig`` in one step
    (k8s-operator.md:92-102): kubeconfig → RemoteStore → rate-limited
    Clientset."""
    cfg = (
        path_or_cfg
        if isinstance(path_or_cfg, Kubeconfig)
        else load_kubeconfig(path_or_cfg)
    )
    return Clientset.new_for_config(
        store_from_kubeconfig(cfg),
        RESTConfig(qps=cfg.qps, burst=cfg.burst, user_agent=cfg.user_agent),
    )
