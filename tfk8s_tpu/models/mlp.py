"""MNIST-scale MLP — BASELINE.json configs[0]: 'MNIST 2-layer MLP,
single-worker TFJob (CPU-only ref)'. The functional target is end-to-end
convergence through the control plane (SURVEY.md §6).

Data is synthetic-but-learnable (hermetic, zero dataset I/O): a fixed
random teacher matrix labels Gaussian images, so accuracy measurably
climbs from ~10% chance to >90% within a few hundred steps.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from tfk8s_tpu.parallel.sharding import shard_constraint  # noqa: F401 (re-export convenience)
from tfk8s_tpu.runtime.train import TrainTask, run_task

IMAGE_DIM = 784
NUM_CLASSES = 10
_TEACHER_SEED = 1234


class MLP(nn.Module):
    """2-layer MLP; kernels carry logical axes so the same model shards
    under fsdp/tensor meshes without edits."""

    hidden: int = 256
    classes: int = NUM_CLASSES

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = nn.Dense(
            self.hidden,
            kernel_init=nn.with_partitioning(
                nn.initializers.lecun_normal(), ("embed", "mlp")
            ),
            name="fc1",
        )(x)
        x = nn.relu(x)
        x = nn.Dense(
            self.classes,
            kernel_init=nn.with_partitioning(
                nn.initializers.lecun_normal(), ("mlp", "vocab")
            ),
            name="fc2",
        )(x)
        return x


def _teacher() -> np.ndarray:
    return np.random.default_rng(_TEACHER_SEED).standard_normal(
        (IMAGE_DIM, NUM_CLASSES)
    ).astype(np.float32)


_TEACHER = _teacher()


def make_batch(rng: np.random.Generator, batch_size: int) -> Dict[str, np.ndarray]:
    """Margin-filtered teacher labels: samples whose top-2 logit gap is
    small (ambiguous, near a decision boundary) are resampled, keeping the
    task cleanly separable so convergence is fast and the e2e target
    meaningful."""
    xs, ys, need = [], [], batch_size
    while need > 0:
        x = rng.standard_normal((2 * need, IMAGE_DIM)).astype(np.float32)
        logits = x @ _TEACHER
        part = np.partition(logits, -2, axis=-1)
        margin = part[:, -1] - part[:, -2]
        keep = margin > 12.0  # ~ 0.4 sigma of the logit scale; keeps ~half
        x, y = x[keep][:need], np.argmax(logits[keep], axis=-1)[:need]
        xs.append(x)
        ys.append(y.astype(np.int32))
        need -= len(x)
    return {"image": np.concatenate(xs), "label": np.concatenate(ys)}


def make_task(batch_size: int = 128, hidden: int = 256) -> TrainTask:
    model = MLP(hidden=hidden)

    def init(rng):
        return model.init(rng, jnp.zeros((1, IMAGE_DIM), jnp.float32))["params"]

    def loss_fn(params, batch, rng) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        logits = model.apply({"params": params}, batch["image"])
        loss = jnp.mean(
            optax_softmax_xent(logits, batch["label"])
        )
        acc = jnp.mean((jnp.argmax(logits, -1) == batch["label"]).astype(jnp.float32))
        return loss, {"accuracy": acc}

    return TrainTask(
        name="mnist-mlp",
        init=init,
        loss_fn=loss_fn,
        make_batch=make_batch,
        batch_size=batch_size,
        targets={"accuracy": 0.9},
    )


def optax_softmax_xent(logits: jax.Array, labels: jax.Array) -> jax.Array:
    import optax

    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


def train(env: Dict[str, str], stop: Optional[Any] = None) -> None:
    """TPUJob entrypoint: ``tfk8s_tpu.models.mlp:train``."""
    env = dict(env)
    env.setdefault("TFK8S_TRAIN_STEPS", "300")
    env.setdefault("TFK8S_LEARNING_RATE", "3e-3")
    run_task(make_task(), env, stop)


def evaluate(env: Dict[str, str], stop: Optional[Any] = None) -> None:
    """TPUJob entrypoint for the Evaluator replica type:
    ``tfk8s_tpu.models.mlp:evaluate`` — evaluates each new checkpoint the
    training replicas write (runtime.train.run_eval)."""
    from tfk8s_tpu.runtime.train import run_eval

    env = dict(env)
    # must mirror train()'s default: the evaluator exits after evaluating
    # this step, so both replicas need the same notion of "final"
    env.setdefault("TFK8S_TRAIN_STEPS", "300")
    run_eval(make_task(), env, stop)
