"""GPT-style decoder-only causal LM — the autoregressive pretraining
family (beyond BASELINE.json's five configs; the modern default workload
a TPU training framework must serve).

The model is the shared encoder stack (models/transformer.py) with
``causal=True`` layers and a tied output head; next-token cross-entropy
over shifted targets. Every attention impl composes through the same
mesh policy as BERT (``transformer.select_attn_fn``): XLA, Pallas
flash (causal kernels, bottom-right aligned), ring attention on long
sequence-sharded meshes (the causal ring skips above-diagonal blocks),
and Ulysses. Gradients all-reduce over ``data`` as XLA collectives.

Hermetic data: the same fixed affine chain as BERT
(``t[i+1] = (a*t[i] + b) mod V`` with random restarts) WITHOUT masking —
the next token is deterministic except at restarts, so causal LM loss
falls to the restart-entropy floor fast and convergence is testable
without a corpus. The reference has no model code at all
(k8s-operator.md:6); this is data-plane surface the north star requires.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tfk8s_tpu.models.transformer import TransformerConfig, apply_with_aux
from tfk8s_tpu.runtime.train import TrainTask, run_task



def GPTLM(cfg: TransformerConfig, attn_fn: Optional[Any] = None):
    """Decoder-only causal LM: the SHARED BertWithHead stack with
    ``causal=True`` — one module serves both families (a wiring fix to
    the stack cannot miss one of them). Returns a flax module instance;
    the factory shape keeps the GPT-side name without duplicating the
    class."""
    from tfk8s_tpu.models.bert import BertWithHead

    return BertWithHead(cfg, attn_fn=attn_fn, causal=True)


def base_config(**overrides) -> TransformerConfig:
    """GPT-2-small shape: 12 layers / 768 hidden / 12 heads / 3072 mlp."""
    kw = dict(
        vocab_size=32000, embed_dim=768, num_heads=12, head_dim=64,
        mlp_dim=3072, num_layers=12, max_len=1024,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def tiny_config(**overrides) -> TransformerConfig:
    """Test-scale config (runs in seconds on the CPU backend)."""
    kw = dict(
        vocab_size=64, embed_dim=32, num_heads=4, head_dim=8,
        mlp_dim=64, num_layers=2, max_len=64,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def mid_config(**overrides) -> TransformerConfig:
    """Serving-bench scale: big enough that a decode step's FLOPs
    dominate XLA per-op overhead on a CPU host (where the tiny config is
    overhead-bound and padded batch rows are nearly free), small enough
    that a mixed-length serving sweep still runs in seconds — the honest
    stand-in for a real serving model when measuring scheduling, not
    kernels."""
    kw = dict(
        vocab_size=256, embed_dim=128, num_heads=4, head_dim=32,
        mlp_dim=512, num_layers=4, max_len=256,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def make_batch_fn(vocab: int, seq_len: int):
    from tfk8s_tpu.models.bert import make_chain_tokens

    def make_batch(rng: np.random.Generator, batch_size: int) -> Dict[str, np.ndarray]:
        toks = make_chain_tokens(rng, batch_size, seq_len, vocab)
        return {"input": toks.astype(np.int32)}

    return make_batch


def lm_loss_and_metrics(
    logits: jax.Array, ids: jax.Array
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Next-token objective: position i predicts token i+1 (the final
    position has no target and is dropped)."""
    shift_logits = logits[:, :-1]
    shift_targets = ids[:, 1:]
    per_tok = optax.softmax_cross_entropy_with_integer_labels(
        shift_logits, shift_targets
    )
    loss = jnp.mean(per_tok)
    acc = jnp.mean(
        (jnp.argmax(shift_logits, -1) == shift_targets).astype(jnp.float32)
    )
    return loss, {"next_token_accuracy": acc}


def make_task(
    cfg: Optional[TransformerConfig] = None,
    seq_len: int = 128,
    batch_size: int = 64,
    targets: Optional[Dict[str, float]] = None,
    attn_fn: Optional[Any] = None,
) -> TrainTask:
    cfg = cfg or base_config()
    seq_len = min(seq_len, cfg.max_len)
    model = GPTLM(cfg, attn_fn=attn_fn)

    def init(rng):
        # full batch shape: ring attention's shard_map needs the batch dim
        # divisible by the data axis even at trace time
        return model.init(rng, jnp.zeros((batch_size, seq_len), jnp.int32))["params"]

    def loss_fn(params, batch, rng) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        logits, aux = apply_with_aux(model, cfg, params, batch["input"])
        loss, metrics = lm_loss_and_metrics(logits, batch["input"])
        if cfg.num_experts > 0:
            metrics["moe_aux"] = aux
            loss = loss + cfg.moe_aux_weight * aux
        return loss, metrics

    return TrainTask(
        name="gpt-lm",
        init=init,
        loss_fn=loss_fn,
        make_batch=make_batch_fn(cfg.vocab_size, seq_len),
        batch_size=batch_size,
        targets=targets or {},
    )


def init_cache(cfg: TransformerConfig, batch_size: int):
    """A CLEAN KV cache for incremental decode; buffers are
    ``cfg.decode_cache_len or cfg.max_len`` long — right-size per
    request, the cache traffic scales with the buffer (see
    ``transformer.clean_cache`` for why init's own cache is unusable)."""
    from tfk8s_tpu.models.bert import BertWithHead
    from tfk8s_tpu.models.transformer import clean_cache

    return clean_cache(
        BertWithHead(cfg, causal=True, decode=True),
        jnp.zeros((batch_size, 1), jnp.int32),
    )


def clean_pages(cfg: TransformerConfig):
    """Zeroed per-layer K/V page pools for the block-paged decoder
    (``cfg.kv_page_size``/``cfg.kv_max_pages`` must be set). Layout comes
    from the module itself via ``eval_shape`` — the same discipline as
    ``transformer.clean_cache`` — so a pool-layout change in
    MultiHeadAttention cannot silently diverge from this initializer."""
    from tfk8s_tpu.models.bert import BertWithHead

    module = BertWithHead(cfg, causal=True, paged=True)
    mpp = cfg.pages_per_slot()
    shapes = jax.eval_shape(
        lambda: module.init(
            jax.random.key(0),
            jnp.zeros((1, 1), jnp.int32),
            pos_offset=jnp.zeros((1,), jnp.int32),
            page_tables=jnp.zeros((1, mpp), jnp.int32),
        )["pages"]
    )
    return jax.tree_util.tree_map(lambda a: jnp.zeros(a.shape, a.dtype), shapes)


def _paged_apply(cfg, params, pages, tokens, page_tables, positions):
    import dataclasses as _dc

    from tfk8s_tpu.models.bert import BertWithHead

    # inference: no memory pressure, remat would only slow the step
    dec = BertWithHead(_dc.replace(cfg, remat=False), causal=True, paged=True)
    logits, mut = dec.apply(
        {"params": params, "pages": pages},
        tokens,
        pos_offset=positions,
        page_tables=page_tables,
        mutable=["pages"],
    )
    return logits.astype(jnp.float32), mut["pages"]


def decode_step_packed(
    cfg: TransformerConfig,
    params,
    pages,
    state: jax.Array,  # [slots, 2 + pages_per_slot] int32
    sampling: Optional[Tuple[jax.Array, jax.Array]] = None,
) -> Tuple[jax.Array, jax.Array, Any]:
    """ONE token step for the whole slot batch against the block-paged
    KV cache — every slot's token is embedded at its OWN absolute
    position, its K/V scattered into its OWN pages, and attention
    gathers each slot's page list, so slots holding requests of
    different prompt lengths and ages ride the same compiled step
    (continuous batching's device half; admission/retirement is
    host-side in runtime/server.DecodeLoopExecutor). Fused with greedy
    selection and the position advance, over ONE packed int32 state
    array — column 0 the last token, column 1 the position, columns 2+
    the page table (inactive rows are all-zero: trash page, garbage
    output by contract — the caller ignores them). Keeping
    argmax and the +1 on device means the loop's steady state transfers
    ``slots`` int32 per step instead of a logits matrix; the decode loop
    keeps the state array device-resident and re-materializes it in ONE
    host->device transfer when a row changes (three separate arrays
    measured ~0.25 ms per rebuild on the CPU backend; one packs to
    ~0.1 ms). Returns ``(emitted [slots] int32, new_state, new_pages)``
    with the token/position columns already advanced for the next
    step.

    ``sampling``, when given, is the packed per-row knob pair
    ``(samp_f [slots, 2] f32 (temperature, top_p), samp_i [slots, 2]
    i32 (top_k, seed))`` — rows with ``temperature <= 0`` keep the
    argmax pick bit-identical to the no-sampling path, sampled rows
    draw via :func:`sample_tokens` folded at the row's position
    column (the absolute position of the input token — the
    :func:`generate` convention, so streams survive resume)."""
    tokens, positions, tables = state[:, 0], state[:, 1], state[:, 2:]
    logits, pages = _paged_apply(
        cfg, params, pages, tokens[:, None], tables, positions
    )
    if sampling is None:
        nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
    else:
        samp_f, samp_i = sampling
        nxt = sample_tokens(
            logits[:, 0], samp_f[:, 0], samp_i[:, 0], samp_f[:, 1],
            samp_i[:, 1], positions,
        )
    new_state = state.at[:, 0].set(nxt).at[:, 1].add(1)
    return nxt, new_state, pages


def prefill_step_packed(
    cfg: TransformerConfig,
    params,
    pages,
    batch: jax.Array,  # [slots, C + 1 + pages_per_slot] int32
    sampling: Optional[Tuple[jax.Array, jax.Array]] = None,
) -> Tuple[jax.Array, Any]:
    """Batched chunked prefill: EVERY admitted request's next prompt
    slice rides one ``[slots, C]`` dispatch (rows pack ``C`` chunk
    tokens, the chunk's base position, then the page table; idle rows
    are all-zero — they write into the trash page). One admission burst
    costs one dispatch per chunk ROUND instead of one per request.
    Returns ``(per-position picks [slots, C] int32, new_pages)``;
    the caller reads a finishing row's pick at its last real prompt
    position. ``sampling`` is the same per-row knob pair as
    :func:`decode_step_packed`; column ``j``'s pick folds at
    ``positions[r] + j`` so a finishing row's first emitted token folds
    at ``prompt_len - 1`` — bit-identical to :func:`generate`'s first
    pick for that seed."""
    mpp = cfg.pages_per_slot()
    c = batch.shape[1] - 1 - mpp
    chunk, positions, tables = batch[:, :c], batch[:, c], batch[:, c + 1:]
    logits, pages = _paged_apply(cfg, params, pages, chunk, tables, positions)
    if sampling is None:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), pages
    return _sample_packed(logits, positions, sampling), pages


def prefill_into_slots(
    cfg: TransformerConfig,
    params,
    pages,
    chunk: jax.Array,       # [1, C] int32 — one request's prompt slice
    page_table: jax.Array,  # [1, pages_per_slot] int32
    position: jax.Array,    # [1] int32 — absolute position of chunk[0]
) -> Tuple[jax.Array, Any]:
    """Chunked prefill: write a prompt slice's K/V into the request's
    freshly allocated pages in ONE multi-token forward (C-parallel
    matmuls instead of C single-token steps), attending to the pages
    already filled by earlier chunks or a shared cached prefix. Prompts
    of ANY length ride this one [1, C] compile — pad the final slice to
    C with junk tokens; their K/V land beyond the prompt and are
    overwritten by decode before ever becoming visible (the intra-chunk
    prefix mask hides them from real queries). Returns ``(logits
    [1, C, vocab] fp32, new_pages)``; the caller reads the last REAL
    prompt position's row to pick the first generated token."""
    return _paged_apply(cfg, params, pages, chunk, page_table, position)


def filter_logits(
    logits: jax.Array,  # [b, vocab] float
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """Nucleus/top-k logit filtering for sampled decode, jit-safe (static
    shapes, no data-dependent control flow — it runs inside the decode
    scan). Disallowed tokens go to -inf; the surviving set is:

    - ``top_k > 0``: tokens scoring at or above the k-th highest logit —
      ties AT the threshold all survive, so more than k tokens can
      remain on tied logits (the same semantics as HF's
      ``TopKLogitsWarper``);
    - ``top_p < 1``: the smallest prefix of the descending-probability
      ordering whose cumulative mass reaches p (the argmax token always
      survives, so the filter can never empty the distribution).

    Both filters compose (intersection), matching the common serving
    semantics (HF ``top_k``+``top_p``)."""
    neg = jnp.asarray(-jnp.inf, logits.dtype)
    # ONE descending sort serves both filters — this runs per token
    # inside the decode scan, and a second O(V log V) sort at 32k vocab
    # would double the filter's hot-path cost
    sorted_desc = (
        jnp.sort(logits, axis=-1)[:, ::-1]
        if (top_k and top_k > 0) or top_p < 1.0
        else None
    )
    if top_k and top_k > 0:
        kth = sorted_desc[:, min(top_k, logits.shape[-1]) - 1][:, None]
        logits = jnp.where(logits < kth, neg, logits)
        if top_p < 1.0:  # apply the same cut to the sorted view
            sorted_desc = jnp.where(sorted_desc < kth, neg, sorted_desc)
    if top_p < 1.0:
        sorted_logits = sorted_desc
        probs = jax.nn.softmax(sorted_logits.astype(jnp.float32), axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep while the mass BEFORE this token is still < p (exclusive
        # cumsum) — the first token is always kept
        keep_sorted = (cum - probs) < top_p
        # threshold = score of the last kept token in the ordering; every
        # token scoring below it is cut. Ties at the threshold survive
        # together — acceptable (standard) nucleus behavior.
        thresh = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1
        )[:, None]
        logits = jnp.where(logits < thresh, neg, logits)
    return logits


def filter_logits_rows(
    logits: jax.Array,  # [b, vocab] float
    top_k: jax.Array,   # [b] int32 — 0 disables the row's top-k cut
    top_p: jax.Array,   # [b] float32 — 1.0 disables the row's nucleus cut
) -> jax.Array:
    """Per-ROW vectorized :func:`filter_logits` for the packed serving
    step: every row carries its own top-k/top-p, so one dispatch filters
    a continuous batch of requests with different sampling params. Rows
    whose knobs are disabled (``top_k == 0`` / ``top_p == 1``) pass
    through untouched; active rows reproduce ``filter_logits``'s
    semantics EXACTLY (same single descending sort, same tie-at-the-
    threshold survival, same exclusive-cumsum nucleus cut — asserted
    bit-for-bit against per-row ``filter_logits`` calls in
    tests/test_sched.py)."""
    neg = jnp.asarray(-jnp.inf, logits.dtype)
    vocab = logits.shape[-1]
    sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
    k_active = (top_k > 0)[:, None]
    kth = jnp.take_along_axis(
        sorted_desc,
        (jnp.clip(top_k, 1, vocab) - 1).astype(jnp.int32)[:, None],
        axis=-1,
    )
    logits = jnp.where(k_active & (logits < kth), neg, logits)
    sorted_desc = jnp.where(k_active & (sorted_desc < kth), neg, sorted_desc)
    p_active = (top_p < 1.0)[:, None]
    probs = jax.nn.softmax(sorted_desc.astype(jnp.float32), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = (cum - probs) < top_p[:, None].astype(jnp.float32)
    thresh = jnp.min(
        jnp.where(keep_sorted, sorted_desc, jnp.inf), axis=-1
    )[:, None]
    return jnp.where(p_active & (logits < thresh), neg, logits)


def sample_tokens(
    logits: jax.Array,       # [n, vocab] fp32 raw logits
    temperature: jax.Array,  # [n] f32 — <= 0 pins the row to greedy argmax
    top_k: jax.Array,        # [n] i32
    top_p: jax.Array,        # [n] f32
    seeds: jax.Array,        # [n] i32 per-request PRNG seed
    folds: jax.Array,        # [n] i32 ABSOLUTE position fold index
) -> jax.Array:
    """The packed per-row pick: greedy rows (``temperature <= 0``) take
    ``argmax`` over the RAW logits — bit-identical to the pre-sampling
    packed step — and sampled rows draw from
    ``softmax(filter_logits(logits / temperature, top_k, top_p))`` under
    a key folded from the row's own seed by ABSOLUTE position, the same
    convention as :func:`generate` (so a request resumed mid-stream —
    preempt/spill/restore, or a KV handoff — continues the identical
    sampled stream). Each row's draw uses a ``[1, vocab]`` categorical,
    matching the key→bits layout of ``generate`` at batch 1."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    t_safe = jnp.where(temperature > 0.0, temperature, 1.0)
    filtered = filter_logits_rows(
        logits / t_safe[:, None].astype(logits.dtype), top_k, top_p
    )

    def draw(seed, fold, row):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), fold)
        return jax.random.categorical(key, row[None, :], axis=-1)[0]

    drawn = jax.vmap(draw)(seeds, folds, filtered).astype(jnp.int32)
    return jnp.where(temperature > 0.0, drawn, greedy)


def _sample_packed(logits, positions, sampling):
    """Shared pick for the packed entry points: ``logits`` is
    ``[slots, C, vocab]``, ``positions`` the per-row base position, and
    ``sampling = (samp_f [slots, 2] f32 (temperature, top_p),
    samp_i [slots, 2] i32 (top_k, seed))``. Column ``c`` of row ``r``
    folds at ``positions[r] + c`` — the absolute position of the token
    whose logits that column holds."""
    samp_f, samp_i = sampling
    slots, c, vocab = logits.shape
    folds = (positions[:, None] + jnp.arange(c, dtype=positions.dtype))
    rep = lambda v: jnp.repeat(v, c)
    picks = sample_tokens(
        logits.reshape(slots * c, vocab),
        rep(samp_f[:, 0]), rep(samp_i[:, 0]), rep(samp_f[:, 1]),
        rep(samp_i[:, 1]), folds.reshape(-1),
    )
    return picks.reshape(slots, c)


def verify_step_packed(
    cfg: TransformerConfig,
    params,
    pages,
    state: jax.Array,   # [slots, 2 + pages_per_slot] int32
    drafts: jax.Array,  # [slots, k] int32 draft-proposed tokens
    sampling: Optional[Tuple[jax.Array, jax.Array]] = None,
) -> Tuple[jax.Array, Any]:
    """Speculative-decode verification: ONE packed chunk forward scores
    the row's last token plus its ``k`` draft proposals at positions
    ``P .. P+k`` (``P`` = the state's position column), returning the
    target model's OWN pick for every one of those positions —
    ``picks[:, j]`` is the token the target would emit at position
    ``P+j+1``, computed with exactly the per-row pick (:func:`sample_
    tokens`, fold ``P+j``) a non-speculative step at that position would
    use. The caller accepts the longest prefix where
    ``picks[:, j] == drafts[:, j]`` and appends ``picks[:, a]`` as the
    correction token — so the emitted stream is token-identical to
    non-speculative decoding REGARDLESS of draft quality (a bad draft
    only shrinks the accepted prefix to 0, degenerating to one token per
    verify step).

    The chunk's K/V scatter writes every proposal's K/V — including
    rejected ones — but that is safe by the paged-attention overwrite-
    before-read order: a later step re-scatters the TRUE token's K/V at
    a stale position before any gather reads it, and the position-
    visibility mask hides not-yet-reached positions entirely."""
    tokens, positions, tables = state[:, 0], state[:, 1], state[:, 2:]
    chunk = jnp.concatenate(
        [tokens[:, None], drafts.astype(jnp.int32)], axis=1
    )
    logits, pages = _paged_apply(cfg, params, pages, chunk, tables, positions)
    if sampling is None:
        picks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        picks = _sample_packed(logits, positions, sampling)
    return picks, pages


def prefill_cache(
    cfg: TransformerConfig,
    params,
    prompt: jax.Array,  # [b, prompt_len] int32
) -> Tuple[jax.Array, Any]:
    """Batched prefill: ONE causal full forward over the prompt, sowing
    every layer's K/V projections, then seed a decode cache from them —
    prompt processing becomes prompt_len-parallel MXU matmuls instead of
    prompt_len single-token dispatch steps (measured ~2x end-to-end
    generation at GPT-2-small, prompt 128 + 128 generated).

    ``cfg.decode_cache_len`` must already be set (the callers pin it to
    the request). Returns ``(prompt_logits [b, plen, V], cache)`` with
    the cache positioned at prompt_len; decode-mode steps continue from
    there. The sown K/V are bit-identical to what token-at-a-time
    prefill would have written (same projections, same dtype), asserted
    in tests/test_gpt.py."""
    import dataclasses as _dc

    from tfk8s_tpu.models.bert import BertWithHead

    b, plen = prompt.shape
    cache_len = cfg.decode_cache_len or cfg.max_len
    if plen > cache_len:
        raise ValueError(f"prompt_len {plen} exceeds cache_len {cache_len}")
    # remat would interpose jax.checkpoint between the sow and the
    # mutable-collection return; inference has no memory pressure — drop it
    fwd = BertWithHead(
        _dc.replace(cfg, remat=False), causal=True, sow_kv=True
    )
    logits, mut = fwd.apply(
        {"params": params}, prompt, mutable=["kv_cache"]
    )
    sown = mut["kv_cache"]
    cache = init_cache(cfg, b)
    for layer_name, layer_cache in cache.items():
        attn = layer_cache["attn"]
        k = sown[layer_name]["attn"]["prefill_k"][0]  # sow stores a 1-tuple
        v = sown[layer_name]["attn"]["prefill_v"][0]
        attn["cached_key"] = jax.lax.dynamic_update_slice(
            attn["cached_key"], k.astype(attn["cached_key"].dtype),
            (0, 0, 0, 0),
        )
        attn["cached_value"] = jax.lax.dynamic_update_slice(
            attn["cached_value"], v.astype(attn["cached_value"].dtype),
            (0, 0, 0, 0),
        )
        attn["cache_index"] = jnp.asarray(plen, jnp.int32)
    return logits, cache


def generate(
    cfg: TransformerConfig,
    params,
    prompt: jax.Array,  # [b, prompt_len] int32
    num_tokens: int,
    rng: Optional[jax.Array] = None,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    batched_prefill: bool = True,
    eos_id: Optional[int] = None,
    pad_id: int = 0,
) -> jax.Array:
    """Jit-compatible KV-cache decoding — greedy or sampled. Default
    (``batched_prefill=True``): ONE full causal forward processes the
    prompt and seeds the cache (``prefill_cache`` — prompt-parallel MXU
    matmuls), then a ``lax.scan`` decodes ``num_tokens - 1`` single-token
    steps; measured 1.47x end-to-end over the scan path at GPT-2-small.
    ``batched_prefill=False`` keeps the original single scan over
    prompt_len + num_tokens uniform single-token steps; both paths are
    static-shape (no recompilation per position) and produce IDENTICAL
    tokens (test-asserted). Returns the ``[b, num_tokens]`` continuation.

    ``rng=None`` (or ``temperature=0``) is greedy argmax. Otherwise
    tokens are drawn from ``softmax(filter_logits(logits / temperature,
    top_k, top_p))`` with a key folded from ``rng`` by ABSOLUTE step
    index — the sampled stream does not depend on which prefill path ran.

    ``eos_id`` enables stop-token semantics (requires
    ``batched_prefill``): a row's EOS is emitted, every later position is
    ``pad_id``, and the decode runs as a ``lax.while_loop`` that EXITS
    EARLY on device once EVERY row has finished — the batch costs its
    LONGEST completion instead of always paying ``num_tokens`` (output
    stays a static ``[b, num_tokens]``, pad-filled).

    The per-layer K/V buffers are ``[b, cache_len, h, d]`` with
    cache_len RIGHT-SIZED to this request (prompt + generation) — the
    per-step cache traffic scales with the buffer length, a measured
    2.5x decode win vs max_len-sized buffers. A caller-pinned
    ``cfg.decode_cache_len`` (e.g. a bucketed size for compile-cache
    reuse across request lengths) is honored as long as it fits."""
    b, prompt_len = prompt.shape
    total = prompt_len + num_tokens
    if num_tokens < 1:
        # uniform no-op across both paths (the batched-prefill branch
        # would otherwise fabricate one token from the prompt logits)
        return jnp.zeros((b, 0), prompt.dtype)
    if total > cfg.max_len:
        raise ValueError(
            f"prompt_len + num_tokens = {total} exceeds max_len={cfg.max_len}"
        )
    import dataclasses as _dc

    from tfk8s_tpu.models.bert import BertWithHead

    # right-size the KV buffers to THIS request: cache update/attention
    # traffic scales with the buffer length, not the filled length
    # (measured 2.5x at 256 vs 1024); params are untouched — the
    # positional table keeps its trained [max_len, embed] shape. An
    # explicit caller bucket wins if it fits (compile-cache reuse).
    if cfg.decode_cache_len is not None and cfg.decode_cache_len < total:
        raise ValueError(
            f"decode_cache_len={cfg.decode_cache_len} is smaller than "
            f"prompt_len + num_tokens = {total}"
        )
    if cfg.decode_cache_len is None:
        cfg = _dc.replace(cfg, decode_cache_len=total)
    decoder = BertWithHead(cfg, causal=True, decode=True)
    sampled = rng is not None and temperature > 0.0

    def pick(step_logits, fold_i):
        """Next token from fp32 logits; the rng fold is indexed by the
        ABSOLUTE step so the batched-prefill and scan paths sample the
        identical stream (asserted in tests)."""
        if sampled:
            filtered = filter_logits(
                step_logits / temperature, top_k=top_k, top_p=top_p
            )
            return jax.random.categorical(
                jax.random.fold_in(rng, fold_i), filtered, axis=-1
            ).astype(prompt.dtype)
        return jnp.argmax(step_logits, axis=-1).astype(prompt.dtype)

    if eos_id is not None and not batched_prefill:
        raise ValueError("eos_id requires batched_prefill=True")

    if batched_prefill:
        # ONE full forward processes the prompt (prompt-parallel matmuls)
        prompt_logits, cache = prefill_cache(cfg, params, prompt)
        tok0 = pick(prompt_logits[:, -1].astype(jnp.float32), prompt_len - 1)

        def decode_one(cache, tok, j):
            logits, mut = decoder.apply(
                {"params": params, "cache": cache},
                tok[:, None],
                pos_offset=prompt_len + j,
                mutable=["cache"],
            )
            return mut["cache"], pick(
                logits[:, 0].astype(jnp.float32), prompt_len + j
            )

        if eos_id is None:
            def dstep(carry, j):
                cache, tok = carry
                cache, nxt = decode_one(cache, tok, j)
                return (cache, nxt), nxt

            (_, _), rest = jax.lax.scan(
                dstep, (cache, tok0), jnp.arange(num_tokens - 1)
            )
            return jnp.concatenate(
                [tok0[:, None], jnp.swapaxes(rest, 0, 1)], axis=1
            )

        # EOS path: while_loop with on-device early exit when every row
        # has emitted its stop token
        pad = jnp.asarray(pad_id, prompt.dtype)
        out = jnp.full((b, num_tokens), pad).at[:, 0].set(tok0)
        done0 = tok0 == eos_id

        def cond(st):
            j, _cache, _tok, _out, done = st
            return (j < num_tokens - 1) & ~jnp.all(done)

        def body(st):
            j, cache, tok, out, done = st
            # finished rows keep feeding pad — their cache rows are dead
            cache, nxt = decode_one(cache, jnp.where(done, pad, tok), j)
            emitted = jnp.where(done, pad, nxt)
            out = jax.lax.dynamic_update_slice(
                out, emitted[:, None], (0, j + 1)
            )
            return j + 1, cache, emitted, out, done | (emitted == eos_id)

        _j, _cache, _tok, out, _done = jax.lax.while_loop(
            cond, body, (jnp.asarray(0, jnp.int32), cache, tok0, out, done0)
        )
        return out

    cache = init_cache(cfg, b)
    # prompt extended with a zero tail so the scan can index one stream
    tokens = jnp.concatenate(
        [prompt, jnp.zeros((b, num_tokens), prompt.dtype)], axis=1
    )

    def step(carry, i):
        cache, tok = carry
        logits, mut = decoder.apply(
            {"params": params, "cache": cache},
            tok[:, None],
            pos_offset=i,
            mutable=["cache"],
        )
        nxt = pick(logits[:, 0].astype(jnp.float32), i)
        # while still inside the prompt, feed the next PROMPT token;
        # afterwards feed the model's own prediction
        in_prompt = i + 1 < prompt_len
        forced = jax.lax.dynamic_slice_in_dim(
            tokens, jnp.minimum(i + 1, total - 1), 1, axis=1
        )[:, 0]
        nxt_in = jnp.where(in_prompt, forced, nxt)
        return (mut["cache"], nxt_in), nxt

    (_, _), outs = jax.lax.scan(
        step, (cache, tokens[:, 0]), jnp.arange(total)
    )
    # outs[i] is the prediction for position i+1; the continuation starts
    # at position prompt_len, predicted at step prompt_len-1
    return jnp.swapaxes(outs, 0, 1)[:, prompt_len - 1 : total - 1]


def greedy_generate(
    cfg: TransformerConfig,
    params,
    prompt: jax.Array,
    num_tokens: int,
) -> jax.Array:
    """Greedy argmax decoding — ``generate`` without an rng."""
    return generate(cfg, params, prompt, num_tokens)


def beam_generate(
    cfg: TransformerConfig,
    params,
    prompt: jax.Array,  # [b, prompt_len] int32
    num_tokens: int,
    num_beams: int = 4,
    return_all: bool = False,
):
    """Beam-search decoding with the KV cache, fully jittable: one
    batched prefill (``prefill_cache``) at batch ``b``, then the cache
    is tiled to ``b*num_beams`` rows and a decode scan keeps the
    ``num_beams`` highest-total-log-prob continuations per batch row — each step
    re-gathers the cache by parent beam (``jnp.take`` over the batch
    dim), so beam reordering stays on device with static shapes.

    Sequences are fixed-length (no EOS short-circuit: the hermetic
    vocabularies here have no EOS; add one by masking its logit
    downstream). Returns the best continuation ``[b, num_tokens]``, or
    with ``return_all`` the tuple ``(sequences [b, k, num_tokens],
    scores [b, k])`` sorted best-first. ``num_beams=1`` reproduces
    greedy decoding exactly (asserted in tests)."""
    import dataclasses as _dc

    from tfk8s_tpu.models.bert import BertWithHead

    b, prompt_len = prompt.shape
    k, V = num_beams, cfg.vocab_size
    total = prompt_len + num_tokens
    if num_tokens < 1:
        raise ValueError("beam search needs num_tokens >= 1")
    if total > cfg.max_len:
        raise ValueError(
            f"prompt_len + num_tokens = {total} exceeds max_len={cfg.max_len}"
        )
    if cfg.decode_cache_len is not None and cfg.decode_cache_len < total:
        raise ValueError(
            f"decode_cache_len={cfg.decode_cache_len} < {total}"
        )
    if cfg.decode_cache_len is None:
        cfg = _dc.replace(cfg, decode_cache_len=total)
    decoder = BertWithHead(cfg, causal=True, decode=True)

    def one_token(cache, tok, pos):
        logits, mut = decoder.apply(
            {"params": params, "cache": cache},
            tok[:, None],
            pos_offset=pos,
            mutable=["cache"],
        )
        return mut["cache"], logits[:, 0].astype(jnp.float32)

    # -- batched prefill at batch b (one full forward, see prefill_cache)
    prompt_logits, cache = prefill_cache(cfg, params, prompt)
    logp0 = jax.nn.log_softmax(
        prompt_logits[:, -1].astype(jnp.float32), axis=-1
    )  # [b, V]

    # -- init beams from ONE source beam: top-k first tokens ------------
    scores, tok0 = jax.lax.top_k(logp0, k)  # [b, k] each
    tile = lambda x: (
        jnp.repeat(x, k, axis=0) if getattr(x, "ndim", 0) >= 2 else x
    )
    cache = jax.tree_util.tree_map(tile, cache)  # [b*k, ...] rows
    seqs = jnp.zeros((b * k, num_tokens), prompt.dtype)
    seqs = seqs.at[:, 0].set(tok0.reshape(b * k).astype(prompt.dtype))
    row_base = (jnp.arange(b)[:, None] * k)  # [b, 1]

    def step(carry, i):
        # generates token i+1 given token i (column i of seqs)
        cache, scores, seqs = carry
        tok = seqs[:, i].astype(prompt.dtype)
        cache, logits = one_token(cache, tok, prompt_len + i)
        logp = jax.nn.log_softmax(logits, axis=-1)  # [b*k, V]
        cand = (scores.reshape(b * k)[:, None] + logp).reshape(b, k * V)
        new_scores, flat = jax.lax.top_k(cand, k)  # [b, k]
        parent = (row_base + flat // V).reshape(b * k)  # absolute rows
        new_tok = (flat % V).reshape(b * k).astype(prompt.dtype)
        gather = lambda x: (
            jnp.take(x, parent, axis=0) if getattr(x, "ndim", 0) >= 2 else x
        )
        cache = jax.tree_util.tree_map(gather, cache)
        seqs = jnp.take(seqs, parent, axis=0).at[:, i + 1].set(new_tok)
        return (cache, new_scores, seqs), ()

    (cache, scores, seqs), _ = jax.lax.scan(
        step, (cache, scores, seqs), jnp.arange(num_tokens - 1)
    )
    seqs = seqs.reshape(b, k, num_tokens)
    if return_all:
        return seqs, scores  # top_k keeps beams sorted best-first
    return seqs[:, 0]


def load_hf_gpt2(hf_model) -> Tuple[TransformerConfig, Any]:
    """Import a Hugging Face ``GPT2LMHeadModel`` (torch) into this
    framework's ``(cfg, params)``.

    The stacks are topologically identical — pre-LN blocks (ln_1 → attn →
    residual, ln_2 → mlp → residual), learned absolute positions, final
    LN, tied lm_head — so the mapping is a pure relabel/reshape:

    - ``wte``/``wpe``            → ``embed.tok.embedding`` / ``embed.pos``
    - ``h.i.ln_1``/``ln_2``      → ``layer{i}.ln_attn`` / ``ln_mlp``
    - ``h.i.attn.c_attn`` (fused qkv, Conv1D [in, 3*out])
                                 → ``attn.{q,k,v}`` kernels [embed, h, d]
    - ``h.i.attn.c_proj``        → ``attn.out`` kernel [h, d, embed]
    - ``h.i.mlp.c_fc``/``c_proj``→ ``mlp.wi`` / ``mlp.wo``
    - ``ln_f``                   → ``ln_final``

    HF's Conv1D already stores kernels [in, out] (no transpose needed);
    activations here run the same tanh-approx gelu HF calls gelu_new,
    and ``ln_eps`` is set to the checkpoint's layer_norm_epsilon.
    Numerical agreement with the torch forward is asserted in
    tests/test_gpt.py::test_hf_gpt2_import_matches_torch_logits."""
    sd = {k: v.detach().cpu().numpy() for k, v in hf_model.state_dict().items()}
    hc = hf_model.config
    if hc.n_embd % hc.n_head:
        raise ValueError(f"n_embd {hc.n_embd} not divisible by n_head {hc.n_head}")
    # refuse configs whose FORWARD differs from this stack — importing
    # them would complete and then silently produce wrong logits
    act = getattr(hc, "activation_function", "gelu_new")
    if act not in ("gelu_new", "gelu_pytorch_tanh"):
        raise ValueError(
            f"activation_function={act!r} unsupported — this stack runs "
            "tanh-approx gelu (gelu_new); erf-gelu/relu checkpoints would "
            "import cleanly but decode wrong"
        )
    if not getattr(hc, "scale_attn_weights", True):
        raise ValueError("scale_attn_weights=False is unsupported")
    if getattr(hc, "scale_attn_by_inverse_layer_idx", False):
        raise ValueError("scale_attn_by_inverse_layer_idx is unsupported")
    if getattr(hc, "reorder_and_upcast_attn", False):
        raise ValueError("reorder_and_upcast_attn is unsupported")
    head_dim = hc.n_embd // hc.n_head
    cfg = TransformerConfig(
        vocab_size=hc.vocab_size,
        embed_dim=hc.n_embd,
        num_heads=hc.n_head,
        head_dim=head_dim,
        mlp_dim=getattr(hc, "n_inner", None) or 4 * hc.n_embd,
        num_layers=hc.n_layer,
        max_len=hc.n_positions,
        ln_eps=float(hc.layer_norm_epsilon),
        dtype=jnp.float32,  # import at full precision; caller may cast
    )
    f32 = lambda a: jnp.asarray(a, jnp.float32)
    e, h, d = cfg.embed_dim, cfg.num_heads, head_dim

    def ln(prefix):
        return {"scale": f32(sd[f"{prefix}.weight"]),
                "bias": f32(sd[f"{prefix}.bias"])}

    params = {
        "embed": {
            "tok": {"embedding": f32(sd["transformer.wte.weight"])},
            "pos": f32(sd["transformer.wpe.weight"]),
        },
        "ln_final": ln("transformer.ln_f"),
    }
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}"
        qkv_w = sd[f"{p}.attn.c_attn.weight"]  # [e, 3e], Conv1D = [in, out]
        qkv_b = sd[f"{p}.attn.c_attn.bias"]  # [3e]
        wq, wk, wv = np.split(qkv_w, 3, axis=1)
        bq, bk, bv = np.split(qkv_b, 3)
        params[f"layer{i}"] = {
            "ln_attn": ln(f"{p}.ln_1"),
            "ln_mlp": ln(f"{p}.ln_2"),
            "attn": {
                "q": {"kernel": f32(wq.reshape(e, h, d)),
                      "bias": f32(bq.reshape(h, d))},
                "k": {"kernel": f32(wk.reshape(e, h, d)),
                      "bias": f32(bk.reshape(h, d))},
                "v": {"kernel": f32(wv.reshape(e, h, d)),
                      "bias": f32(bv.reshape(h, d))},
                "out": {
                    "kernel": f32(
                        sd[f"{p}.attn.c_proj.weight"].reshape(h, d, e)
                    ),
                    "bias": f32(sd[f"{p}.attn.c_proj.bias"]),
                },
            },
            "mlp": {
                "wi": {"kernel": f32(sd[f"{p}.mlp.c_fc.weight"]),
                       "bias": f32(sd[f"{p}.mlp.c_fc.bias"])},
                "wo": {"kernel": f32(sd[f"{p}.mlp.c_proj.weight"]),
                       "bias": f32(sd[f"{p}.mlp.c_proj.bias"])},
            },
        }
    return cfg, params


def save_hf_gpt2(cfg: TransformerConfig, params) -> "Any":
    """Export this framework's ``(cfg, params)`` to a Hugging Face
    ``GPT2LMHeadModel`` — the inverse of ``load_hf_gpt2`` (same pure
    relabel/reshape, run backwards), so a model trained here can be
    served by any HF-compatible stack. Round-trip equality is asserted
    in tests/test_gpt.py::test_hf_gpt2_export_roundtrip."""
    import torch
    from transformers import GPT2Config, GPT2LMHeadModel

    from tfk8s_tpu.parallel.sharding import unbox

    params = jax.tree_util.tree_map(np.asarray, unbox(params))
    e, h, d = cfg.embed_dim, cfg.num_heads, cfg.head_dim
    if e != h * d:
        raise ValueError(
            f"HF GPT-2 requires embed_dim == num_heads*head_dim; got "
            f"{e} != {h}*{d}"
        )
    if cfg.num_experts > 0:
        raise ValueError(
            "MoE models have no GPT-2 equivalent — dense-distill or "
            "export per-expert weights yourself"
        )
    hf = GPT2LMHeadModel(
        GPT2Config(
            vocab_size=cfg.vocab_size, n_positions=cfg.max_len, n_embd=e,
            n_layer=cfg.num_layers, n_head=h, n_inner=cfg.mlp_dim,
            layer_norm_epsilon=cfg.ln_eps, activation_function="gelu_new",
            resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        )
    )
    # copy=True: jax-backed numpy views are read-only and torch warns on
    # (and forbids mutating) non-writable storage
    t = lambda a: torch.asarray(np.array(a, np.float32, copy=True))
    sd = {
        "transformer.wte.weight": t(params["embed"]["tok"]["embedding"]),
        "transformer.wpe.weight": t(params["embed"]["pos"]),
        "transformer.ln_f.weight": t(params["ln_final"]["scale"]),
        "transformer.ln_f.bias": t(params["ln_final"]["bias"]),
        "lm_head.weight": t(params["embed"]["tok"]["embedding"]),  # tied
    }
    for i in range(cfg.num_layers):
        lp, p = params[f"layer{i}"], f"transformer.h.{i}"
        at = lp["attn"]
        sd[f"{p}.ln_1.weight"] = t(lp["ln_attn"]["scale"])
        sd[f"{p}.ln_1.bias"] = t(lp["ln_attn"]["bias"])
        sd[f"{p}.ln_2.weight"] = t(lp["ln_mlp"]["scale"])
        sd[f"{p}.ln_2.bias"] = t(lp["ln_mlp"]["bias"])
        sd[f"{p}.attn.c_attn.weight"] = t(
            np.concatenate(
                [at[k]["kernel"].reshape(e, e) for k in ("q", "k", "v")],
                axis=1,
            )
        )
        sd[f"{p}.attn.c_attn.bias"] = t(
            np.concatenate([at[k]["bias"].reshape(e) for k in ("q", "k", "v")])
        )
        sd[f"{p}.attn.c_proj.weight"] = t(at["out"]["kernel"].reshape(e, e))
        sd[f"{p}.attn.c_proj.bias"] = t(at["out"]["bias"])
        sd[f"{p}.mlp.c_fc.weight"] = t(lp["mlp"]["wi"]["kernel"])
        sd[f"{p}.mlp.c_fc.bias"] = t(lp["mlp"]["wi"]["bias"])
        sd[f"{p}.mlp.c_proj.weight"] = t(lp["mlp"]["wo"]["kernel"])
        sd[f"{p}.mlp.c_proj.bias"] = t(lp["mlp"]["wo"]["bias"])
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    # attn.bias / attn.masked_bias are derived causal-mask buffers HF
    # regenerates; anything else missing is a mapping bug
    real_missing = [
        m for m in missing if not m.endswith((".attn.bias", ".attn.masked_bias"))
    ]
    if real_missing or unexpected:
        raise ValueError(
            f"state_dict mismatch: missing={real_missing} "
            f"unexpected={list(unexpected)}"
        )
    return hf.eval()


def task_for_mesh(
    mesh,
    cfg: Optional[TransformerConfig] = None,
    **task_kw,
) -> TrainTask:
    """Build the task with the attention impl the mesh calls for — the
    SAME policy as BERT (``transformer.select_attn_fn``); causal
    masking rides inside each impl (flash's bottom-right-aligned kernels,
    the ring's src-indexed block masks, Ulysses' global mask)."""
    from tfk8s_tpu.models.transformer import select_attn_fn

    cfg = cfg or base_config()
    seq_len = min(task_kw.get("seq_len", 128), cfg.max_len)
    attn_fn = select_attn_fn(mesh, cfg, seq_len)
    return make_task(cfg=cfg, attn_fn=attn_fn, **task_kw)


def train(env: Dict[str, str], stop: Optional[Any] = None) -> None:
    """TPUJob entrypoint: ``tfk8s_tpu.models.gpt:train``.
    ``TFK8S_MODEL_PRESET=tiny`` selects the test-scale config;
    ``TFK8S_ATTENTION_IMPL`` pins an attention impl; ``TFK8S_NUM_EXPERTS``
    > 0 enables MoE layers over the ``expert`` mesh axis."""
    from tfk8s_tpu.runtime.launcher import (
        ProcessContext,
        build_mesh,
        initialize_distributed,
    )

    env = dict(env)
    env.setdefault("TFK8S_TRAIN_STEPS", "100")
    env.setdefault("TFK8S_LEARNING_RATE", "1e-4")
    seq = int(env.get("TFK8S_SEQ_LEN", "128"))
    batch = int(env.get("TFK8S_BATCH_SIZE", "64"))
    preset = tiny_config if env.get("TFK8S_MODEL_PRESET") == "tiny" else base_config
    cfg_kw = dict(
        num_experts=int(env.get("TFK8S_NUM_EXPERTS", "0")),
        moe_top_k=int(env.get("TFK8S_MOE_TOP_K", "1")),
        attention_impl=env.get("TFK8S_ATTENTION_IMPL", "auto"),
    )
    if env.get("TFK8S_VOCAB_SIZE"):
        # size the model to a custom tokenizer (data/tokenizer.py) — text
        # fine-tuning through a job spec needs the vocab on the env
        # contract, same as seq/batch
        cfg_kw["vocab_size"] = int(env["TFK8S_VOCAB_SIZE"])
    cfg = preset(**cfg_kw)
    ctx = ProcessContext.from_env(env)
    initialize_distributed(ctx, env)
    mesh = build_mesh(ctx)
    task = task_for_mesh(mesh, cfg=cfg, seq_len=seq, batch_size=batch)
    run_task(task, env, stop, mesh=mesh)
