"""DLRM / Wide&Deep CTR model — BASELINE.json configs[4]:
'Wide&Deep / DLRM (ParameterServerStrategy → TPUEmbedding)'.

This is the honest TPU translation of the reference's parameter-server
half (k8s-operator.md:6; SURVEY.md §2 'PS-semantics mapping', §7 hard
part 3): instead of PS processes hosting big embedding tables behind
gRPC, the tables are *sharded by annotation* over the mesh — each
categorical feature's table carries logical axes ``("vocab", "embed")``,
so the vocab dim splits over the ``tensor`` axis (TPUEmbedding-style
model parallelism) while the dense MLPs run data-parallel. GSPMD emits
the gather + all-to-all; no parameter server exists.

Architecture (standard DLRM):
  bottom MLP(dense features) ┐
                             ├─ pairwise dot interaction ─ top MLP ─ CTR logit
  embedding lookups (sparse) ┘

Hermetic data: clicks are generated from a ground-truth low-rank
feature-affinity model, so log-loss falls measurably.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from tfk8s_tpu.runtime.train import TrainTask, run_task


def _mlp_dense(features: int, name: str):
    # dense MLPs run data-parallel: input dim replicated (odd widths like
    # dense_features=13 must not shard), hidden widths split via "mlp",
    # the scalar logit layer fully replicated
    names = (None, "mlp") if features > 1 else (None, None)
    return nn.Dense(
        features,
        dtype=jnp.bfloat16,
        param_dtype=jnp.float32,
        kernel_init=nn.with_partitioning(
            nn.initializers.lecun_normal(), names
        ),
        name=name,
    )


class Mlp(nn.Module):
    layers: Sequence[int]
    name_prefix: str = "fc"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        for i, width in enumerate(self.layers):
            x = _mlp_dense(width, f"{self.name_prefix}{i}")(x)
            if i < len(self.layers) - 1:
                x = nn.relu(x)
        return x


class DLRM(nn.Module):
    """num_tables categorical features, one sharded table each."""

    vocab_sizes: Sequence[int]
    embed_dim: int = 64
    dense_features: int = 13
    # bottom MLP must end at embed_dim so its output stacks with the
    # embeddings for the dot interaction
    bottom_layers: Optional[Sequence[int]] = None
    top_layers: Sequence[int] = (512, 256, 1)

    def _bottom(self) -> Sequence[int]:
        if self.bottom_layers is not None:
            return self.bottom_layers
        return (512, 256, self.embed_dim)

    @nn.compact
    def __call__(self, dense: jax.Array, sparse: jax.Array) -> jax.Array:
        # sparse: [batch, num_tables] int ids
        embs = []
        for t, vocab in enumerate(self.vocab_sizes):
            table = nn.Embed(
                vocab,
                self.embed_dim,
                param_dtype=jnp.float32,
                embedding_init=nn.with_partitioning(
                    nn.initializers.normal(0.01), ("vocab", "embed")
                ),
                name=f"table{t}",
            )
            embs.append(table(sparse[:, t]).astype(jnp.bfloat16))

        bottom = Mlp(self._bottom(), name="bottom")(dense.astype(jnp.bfloat16))
        feats = jnp.stack([bottom] + embs, axis=1)  # [b, 1+T, embed_dim]

        # pairwise dot interaction, upper triangle (DLRM-style)
        inter = jnp.einsum("bne,bme->bnm", feats, feats)
        n = feats.shape[1]
        iu, ju = jnp.triu_indices(n, k=1)
        inter_flat = inter[:, iu, ju]

        top_in = jnp.concatenate([bottom, inter_flat.astype(jnp.bfloat16)], axis=-1)
        logit = Mlp(self.top_layers, name="top")(top_in)
        return logit[:, 0].astype(jnp.float32)


# -- synthetic learnable CTR data --------------------------------------------

_GT_SEED = 777


@functools.lru_cache(maxsize=None)
def _ground_truth(vocab_sizes: Tuple[int, ...], dense_features: int, rank: int = 4):
    rng = np.random.default_rng(_GT_SEED)
    table_vecs = [
        rng.standard_normal((v, rank)).astype(np.float32) for v in vocab_sizes
    ]
    dense_w = rng.standard_normal((dense_features, rank)).astype(np.float32)
    return table_vecs, dense_w


def make_batch_fn(vocab_sizes: Tuple[int, ...], dense_features: int):
    table_vecs, dense_w = _ground_truth(vocab_sizes, dense_features)

    def make_batch(rng: np.random.Generator, batch_size: int) -> Dict[str, np.ndarray]:
        dense = rng.standard_normal((batch_size, dense_features)).astype(np.float32)
        sparse = np.stack(
            [rng.integers(0, v, size=batch_size) for v in vocab_sizes], axis=1
        )
        # click probability from latent-factor affinities
        latent = dense @ dense_w
        for t, vecs in enumerate(table_vecs):
            latent = latent + vecs[sparse[:, t]]
        score = np.sum(latent, axis=-1) / np.sqrt(latent.shape[-1])
        p = 1.0 / (1.0 + np.exp(-1.5 * score))
        click = (rng.random(batch_size) < p).astype(np.float32)
        return {
            "dense": dense,
            "sparse": sparse.astype(np.int32),
            "click": click,
        }

    return make_batch


def make_task(
    vocab_sizes: Sequence[int] = (100_000,) * 8,
    embed_dim: int = 64,
    dense_features: int = 13,
    batch_size: int = 4096,
    targets: Optional[Dict[str, float]] = None,
) -> TrainTask:
    vocab_sizes = tuple(vocab_sizes)
    model = DLRM(
        vocab_sizes=vocab_sizes, embed_dim=embed_dim, dense_features=dense_features
    )

    def init(rng):
        return model.init(
            rng,
            jnp.zeros((1, dense_features), jnp.float32),
            jnp.zeros((1, len(vocab_sizes)), jnp.int32),
        )["params"]

    def loss_fn(params, batch, rng) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        logit = model.apply({"params": params}, batch["dense"], batch["sparse"])
        loss = jnp.mean(optax.sigmoid_binary_cross_entropy(logit, batch["click"]))
        acc = jnp.mean(((logit > 0) == (batch["click"] > 0.5)).astype(jnp.float32))
        return loss, {"click_accuracy": acc}

    return TrainTask(
        name="dlrm",
        init=init,
        loss_fn=loss_fn,
        make_batch=make_batch_fn(vocab_sizes, dense_features),
        batch_size=batch_size,
        targets=targets or {},
    )


def train(env: Dict[str, str], stop: Optional[Any] = None) -> None:
    """TPUJob entrypoint: ``tfk8s_tpu.models.dlrm:train``. The job's mesh
    ``tensor`` axis is the embedding-shard axis — the PS replica set's
    honest TPU translation (tables sharded by annotation, no PS
    processes; SURVEY.md §7 hard part 3)."""
    env = dict(env)
    env.setdefault("TFK8S_TRAIN_STEPS", "100")
    env.setdefault("TFK8S_LEARNING_RATE", "1e-3")
    batch = int(env.get("TFK8S_BATCH_SIZE", "4096"))
    vocab_raw = env.get("TFK8S_VOCAB_SIZES", "")
    vocab = (
        tuple(int(v) for v in vocab_raw.split(","))
        if vocab_raw
        else (100_000,) * 8
    )
    task = make_task(
        vocab_sizes=vocab,
        embed_dim=int(env.get("TFK8S_EMBED_DIM", "64")),
        batch_size=batch,
    )
    run_task(task, env, stop)
