"""Model families (SURVEY.md §7 step 6, BASELINE.json config order):
MNIST MLP, ResNet-50, BERT-base MLM, T5-base seq2seq, DLRM/Wide&Deep —
plus GPT-style causal LM (decoder-only autoregressive pretraining, the
modern default workload) and the pipelined BERT variant.
Each exposes ``make_task()`` (a runtime TrainTask) and a ``train`` TPUJob
entrypoint.
"""
