"""Shared transformer building blocks for the BERT / T5 model families
(BASELINE.json configs[2], configs[3]).

TPU-first conventions, applied uniformly:

- All projections are ``nn.DenseGeneral`` with logical-axis partitioning
  (``embed``/``heads``/``kv``/``mlp`` — parallel/sharding.py rules), so
  the Megatron-style tensor split (qkv+mlp-in column-wise, out+mlp-out
  row-wise) falls out of the annotations; GSPMD inserts exactly the two
  all-reduces per block over the ``tensor`` ICI axis.
- bfloat16 activations, float32 params and layer norms.
- No data-dependent Python control flow; masks are computed with lax ops
  so one trace serves every batch.
- ``remat`` flag wraps each layer in ``jax.checkpoint`` — the standard
  HBM-for-FLOPs trade on TPU (SURVEY.md 'HBM bandwidth').
- Attention optionally routes through the ring-attention kernel
  (parallel/ring_attention.py) when the mesh has a nontrivial
  ``sequence`` axis — the long-context path (SURVEY.md §5).

The reference has no model code at all (its operator treats training as a
black box, k8s-operator.md:6); these blocks are the data plane the north
star prescribes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

Dtype = Any


def act_constraint(x, *logical):
    """Trace-time deferral of parallel.sharding.act_constraint — a
    module-level import would cycle (parallel/__init__ pulls in moe,
    which imports TransformerConfig from here)."""
    from tfk8s_tpu.parallel.sharding import act_constraint as _ac

    return _ac(x, *logical)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    embed_dim: int = 768
    num_heads: int = 12
    head_dim: int = 64
    mlp_dim: int = 3072
    num_layers: int = 12
    max_len: int = 512
    dropout: float = 0.0  # keep 0 for determinism; hook exists
    dtype: Dtype = jnp.bfloat16
    remat: bool = False
    # 'auto' | 'full' | 'flash' | 'ring' | 'ulysses'. 'auto' (default)
    # lets the task wrapper pick by mesh/hardware: an SP impl on a
    # sequence-sharded mesh, the Pallas flash kernel on TPU at long
    # sequence, XLA otherwise. Anything else is an explicit pin, honored
    # or rejected loudly (never silently substituted) by task_for_mesh.
    attention_impl: str = "auto"
    # Mixture-of-Experts (EP row, SURVEY.md §2): 0 = dense MLP everywhere;
    # >0 swaps the MLP of every ``moe_every``-th layer for a
    # SwitchMoeBlock with this many experts (parallel/moe.py), whose aux
    # loss is sown into the "losses" collection and added to the
    # objective with weight ``moe_aux_weight`` by the task wrappers.
    num_experts: int = 0
    moe_every: int = 2
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 1e-2
    # LayerNorm epsilon. The flax default (1e-6) is kept for this repo's
    # own checkpoints; HF GPT-2 weights are trained against 1e-5 and the
    # importer (models/gpt.load_hf_gpt2) sets it to match.
    ln_eps: float = 1e-6
    # KV-cache buffer length for incremental decode (None = max_len).
    # Right-size it to the REQUEST (prompt + generation): the per-step
    # cache update/attention traffic scales with the BUFFER length, not
    # the filled length — measured 2.5x decode speedup at 256 vs 1024 on
    # the bench chip. Decoupled from max_len because the positional
    # table is a PARAM shaped [max_len, embed] (trained checkpoints pin
    # it), while the cache is ephemeral serving state.
    decode_cache_len: Optional[int] = None
    # Block-paged KV cache (the vLLM/PagedAttention layout, served by the
    # continuous-batching decode loop in runtime/server.py): each layer's
    # K/V live in a pool of ``kv_max_pages`` fixed ``kv_page_size``-token
    # pages; a request's cache is a per-slot PAGE TABLE into the pool, so
    # long- and short-context requests share HBM without fragmentation
    # and prompts of DIFFERENT lengths ride one compiled step. Both must
    # be set for ``paged=True`` modules; page 0 is reserved as the trash
    # page inactive slots write into (runtime/paging.PageAllocator).
    kv_page_size: Optional[int] = None
    kv_max_pages: Optional[int] = None
    # False drops the flax Partitioned boxes from layer params. Needed
    # inside manual-collective regions (shard_map pipeline stages): flax
    # re-runs initializers under eval_shape at apply time, and a boxed
    # init would emit a sharding constraint naming logical axes the
    # manual mesh doesn't have (models/pipelined.py shards stage params
    # over ``pipeline`` via the stage-stacking rebox instead).
    partition_params: bool = True

    def pages_per_slot(self) -> int:
        """Page-table width: pages needed to cover ``max_len`` tokens."""
        if not self.kv_page_size:
            raise ValueError("kv_page_size is unset; not a paged config")
        return -(-self.max_len // self.kv_page_size)

    def layer_uses_moe(self, layer_idx: int) -> bool:
        """MoE layers interleave dense ones (every ``moe_every``-th layer,
        counting from the top of each group — the Switch/GShard layout)."""
        return (
            self.num_experts > 0
            and layer_idx % self.moe_every == self.moe_every - 1
        )


def _dense(features, names, name, dtype, axis=-1, partition=True):
    init = nn.initializers.xavier_uniform()
    return nn.DenseGeneral(
        features=features,
        axis=axis,
        dtype=dtype,
        param_dtype=jnp.float32,
        kernel_init=nn.with_partitioning(init, names) if partition else init,
        bias_init=nn.initializers.zeros,
        name=name,
    )


class MultiHeadAttention(nn.Module):
    """Self- or cross-attention. ``attn_fn`` lets the caller swap the
    inner softmax(QK^T)V computation (e.g. for ring attention).

    ``decode=True`` enables the autoregressive KV cache: each call
    appends this step's K/V at ``cache_index`` into fixed
    ``[b, max_len, h, d]`` buffers (the ``"cache"`` variable collection)
    and attends over the filled prefix — static shapes throughout, so
    the whole generation loop jits as one ``lax.scan`` (SURVEY.md 'XLA
    semantics': no dynamic shapes)."""

    cfg: TransformerConfig
    causal: bool = False
    attn_fn: Optional[Callable] = None
    decode: bool = False
    # sow this call's raw K/V projections into the "kv_cache" collection —
    # batched prefill (models/gpt.prefill_cache) runs ONE full forward
    # over the prompt and seeds the decode cache from the sown values
    # instead of paying prompt_len single-token steps
    sow_kv: bool = False
    # block-paged KV cache (continuous batching): K/V live in a shared
    # page pool (the "pages" collection), addressed through per-row page
    # tables — one compiled step serves every prompt length and rows
    # admit/retire independently (models/gpt.decode_step_packed)
    paged: bool = False

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        kv: Optional[jax.Array] = None,
        mask: Optional[jax.Array] = None,
        page_tables: Optional[jax.Array] = None,
        positions: Optional[jax.Array] = None,
    ) -> jax.Array:
        cfg = self.cfg
        kv = x if kv is None else kv
        part = cfg.partition_params
        q = _dense((cfg.num_heads, cfg.head_dim), ("embed", "heads", "kv"), "q", cfg.dtype, partition=part)(x)
        k = _dense((cfg.num_heads, cfg.head_dim), ("embed", "heads", "kv"), "k", cfg.dtype, partition=part)(kv)
        v = _dense((cfg.num_heads, cfg.head_dim), ("embed", "heads", "kv"), "v", cfg.dtype, partition=part)(kv)
        q = q / jnp.sqrt(cfg.head_dim).astype(cfg.dtype)
        if self.sow_kv:
            # names must not collide with the q/k/v/out submodule scopes
            self.sow("kv_cache", "prefill_k", k)
            self.sow("kv_cache", "prefill_v", v)

        if self.paged:
            # -- block-paged incremental attention ------------------------
            # One call serves BOTH shapes of the continuous-batching loop:
            # decode ([slots, 1] — every live slot one token) and chunked
            # prefill ([1, chunk] — one request's prompt slice), so the
            # whole mixed-length workload compiles exactly twice. Row r's
            # token t sits at absolute position positions[r] + t; its K/V
            # are scattered into page page_tables[r, p // page_size] at
            # offset p % page_size, and attention gathers the row's whole
            # page list back into a [rows, pages*page_size, h, d] view
            # masked to the filled prefix. Inactive rows point their page
            # table at the reserved trash page 0 (runtime/paging), so
            # their writes can never corrupt a live row.
            if mask is not None:
                raise ValueError(
                    "paged mode computes its own prefix mask; feed "
                    "unpadded per-row token slices (mask=None)"
                )
            if page_tables is None or positions is None:
                raise ValueError("paged mode needs page_tables and positions")
            ps, n_pages = cfg.kv_page_size, cfg.kv_max_pages
            if not ps or not n_pages:
                raise ValueError(
                    "paged mode needs cfg.kv_page_size and cfg.kv_max_pages"
                )
            b, step_len, h, d = k.shape
            k_pages = self.variable(
                "pages", "k_pages", jnp.zeros, (n_pages * ps, h, d), k.dtype
            )
            v_pages = self.variable(
                "pages", "v_pages", jnp.zeros, (n_pages * ps, h, d), v.dtype
            )
            mpp = page_tables.shape[1]
            pos = positions[:, None] + jnp.arange(step_len)  # [b, T] absolute
            # a position past the table must write the TRASH page (0) —
            # merely clamping the page column would land the write in
            # the row's LAST real page and overwrite live prompt K/V
            # (e.g. a prefix-cache hit whose final prefill chunk pads
            # past max_len); the overflowing row's OUTPUT is poisoned
            # below (same contract as the contiguous path's
            # buffer-overflow NaN)
            page_col = jnp.minimum(pos // ps, mpp - 1)
            page_id = jnp.take_along_axis(page_tables, page_col, axis=1)
            page_id = jnp.where(pos < mpp * ps, page_id, 0)
            flat = (page_id * ps + pos % ps).reshape(-1)  # rows of the pool
            kp = k_pages.value.at[flat].set(k.reshape(-1, h, d))
            vp = v_pages.value.at[flat].set(v.reshape(-1, h, d))
            k_pages.value, v_pages.value = kp, vp
            # gather each row's pages back as one contiguous-looking view.
            # PALLAS SEAM: this dense gather always materializes the FULL
            # page-table extent — mpp * ps = pages_per_slot() * page_size
            # tokens per row, filled or not — which is exactly the tile a
            # fused paged-attention kernel would stream instead. Anything
            # that reasons about per-row KV footprint (the scheduler's
            # page-spill math in runtime/server._spill_locked, the
            # allocator's admission reserve) must use the SAME
            # pages_per_slot() accounting, or a kernel swap here changes
            # observable paging behavior (asserted by
            # tests/test_sched.py::TestPagedGatherSeam).
            rows = (
                (page_tables * ps)[:, :, None] + jnp.arange(ps)[None, None, :]
            ).reshape(b, mpp * ps)
            k_all = jnp.take(kp, rows, axis=0)  # [b, mpp*ps, h, d]
            v_all = jnp.take(vp, rows, axis=0)
            # token t sees gathered position j iff j <= positions[r] + t —
            # the causal mask in page-table coordinates (page k of the
            # table covers absolute positions [k*ps, (k+1)*ps))
            visible = (
                jnp.arange(mpp * ps)[None, None, :] <= pos[:, :, None]
            )
            out = dot_product_attention(q, k_all, v_all, mask=visible)
            out = jnp.where(
                (pos < mpp * ps)[:, :, None, None], out, jnp.nan
            )
        elif self.decode:
            b, step_len, h, d = k.shape
            # token-at-a-time generation: a multi-token decode step would
            # need an intra-step causal mask this path deliberately omits
            # (ValueError, not assert — python -O must not disable the
            # guard against silent future leakage)
            if step_len != 1:
                raise ValueError(
                    f"decode mode is incremental (one token per call); "
                    f"got a {step_len}-token step"
                )
            if mask is not None:
                # padded prompts would write pad K/V into the cache and
                # the prefix mask would make them attendable — corrupting
                # every later token silently; refuse instead
                raise ValueError(
                    "decode mode does not support padding masks; feed "
                    "unpadded per-row prompts (mask=None)"
                )
            cache_len = cfg.decode_cache_len or cfg.max_len
            cached_k = self.variable(
                "cache", "cached_key",
                jnp.zeros, (b, cache_len, h, d), k.dtype,
            )
            cached_v = self.variable(
                "cache", "cached_value",
                jnp.zeros, (b, cache_len, h, d), v.dtype,
            )
            cache_index = self.variable(
                "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
            )
            idx = cache_index.value
            k_all = jax.lax.dynamic_update_slice(
                cached_k.value, k, (0, idx, 0, 0)
            )
            v_all = jax.lax.dynamic_update_slice(
                cached_v.value, v, (0, idx, 0, 0)
            )
            cached_k.value, cached_v.value = k_all, v_all
            cache_index.value = idx + step_len
            # only the filled prefix (positions <= current) is visible —
            # this IS the causal mask in incremental form
            valid = (
                jnp.arange(cache_len)[None, :] < idx + step_len
            )
            out = dot_product_attention(
                q, k_all, v_all,
                mask=jnp.broadcast_to(valid, (b, cache_len)),
                causal=False,
            )
            # past the buffer the write index would clamp and the prefix
            # mask would cover a corrupted cache — poison the output
            # instead of returning plausible-looking garbage (idx is
            # traced, so a Python raise can't fire here)
            out = jnp.where(idx < cache_len, out, jnp.nan)
        elif self.attn_fn is not None:
            out = self.attn_fn(q, k, v, mask=mask, causal=self.causal)
        else:
            out = dot_product_attention(q, k, v, mask=mask, causal=self.causal)

        return _dense(
            cfg.embed_dim, ("heads", "kv", "embed"), "out", cfg.dtype, axis=(-2, -1),
            partition=cfg.partition_params,
        )(out)


def dot_product_attention(
    q: jax.Array,  # [b, lq, h, d] (pre-scaled)
    k: jax.Array,  # [b, lk, h, d]
    v: jax.Array,  # [b, lk, h, d]
    mask: Optional[jax.Array] = None,  # [b, lk] key validity or [b, lq, lk]
    causal: bool = False,
) -> jax.Array:
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k)
    neg = jnp.asarray(-1e9, scores.dtype)
    if mask is not None:
        m = mask[:, None, None, :] if mask.ndim == 2 else mask[:, None, :, :]
        scores = jnp.where(m, scores, neg)
    if causal:
        lq, lk = scores.shape[-2], scores.shape[-1]
        cm = jnp.tril(jnp.ones((lq, lk), bool), k=lk - lq)
        scores = jnp.where(cm[None, None], scores, neg)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class MlpBlock(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        h = _dense(cfg.mlp_dim, ("embed", "mlp"), "wi", cfg.dtype,
                   partition=cfg.partition_params)(x)
        h = nn.gelu(h)
        return _dense(cfg.embed_dim, ("mlp", "embed"), "wo", cfg.dtype,
                      partition=cfg.partition_params)(h)


def _ln(name: str, eps: float = 1e-6) -> nn.LayerNorm:
    return nn.LayerNorm(
        epsilon=eps, dtype=jnp.float32, param_dtype=jnp.float32,
        use_bias=True, name=name,
    )


class EncoderLayer(nn.Module):
    """Pre-LN residual block (more stable than post-LN, standard on TPU).

    With ``use_moe`` the MLP is a SwitchMoeBlock; its load-balance aux
    loss is sown into the ``"losses"`` collection (task wrappers apply
    with ``mutable=["losses"]`` and fold it into the objective).
    ``causal=True`` turns the block into a decoder-only (GPT-style)
    layer — same stack, autoregressive attention."""

    cfg: TransformerConfig
    attn_fn: Optional[Callable] = None
    use_moe: bool = False
    causal: bool = False
    decode: bool = False
    sow_kv: bool = False
    paged: bool = False

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        mask: Optional[jax.Array] = None,
        page_tables: Optional[jax.Array] = None,
        positions: Optional[jax.Array] = None,
    ) -> jax.Array:
        cfg = self.cfg
        h = _ln("ln_attn", cfg.ln_eps)(x).astype(cfg.dtype)
        x = x + MultiHeadAttention(
            cfg, causal=self.causal, attn_fn=self.attn_fn,
            decode=self.decode, sow_kv=self.sow_kv, paged=self.paged,
            name="attn"
        )(h, mask=mask, page_tables=page_tables, positions=positions)
        h = _ln("ln_mlp", cfg.ln_eps)(x).astype(cfg.dtype)
        if self.use_moe:
            from tfk8s_tpu.parallel.moe import SwitchMoeBlock

            y, aux = SwitchMoeBlock(
                cfg,
                num_experts=cfg.num_experts,
                capacity_factor=cfg.moe_capacity_factor,
                top_k=cfg.moe_top_k,
                name="moe",
            )(h)
            self.sow("losses", "moe_aux", aux)
            out = x + y
        else:
            out = x + MlpBlock(cfg, name="mlp")(h)
        # partition_params=False marks a manual-collective region
        # (shard_map pipeline stage) where mesh-axis constraints are
        # illegal — skip the activation pin there.
        if cfg.partition_params:
            out = act_constraint(out, "batch", "seq", "embed")
        return out


class DecoderLayer(nn.Module):
    """Causal self-attention + cross-attention + MLP (T5-style decoder).
    ``decode=True`` turns the self-attention into the incremental
    KV-cache path (one token per call); cross-attention stays a plain
    one-query attention over the full encoder output — its K/V
    projections are recomputed per step (a known constant-factor
    optimization: caching them per request would save two enc-length
    matmuls per layer per token)."""

    cfg: TransformerConfig
    attn_fn: Optional[Callable] = None
    decode: bool = False

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        enc: jax.Array,
        enc_mask: Optional[jax.Array] = None,
    ) -> jax.Array:
        cfg = self.cfg
        h = _ln("ln_self", cfg.ln_eps)(x).astype(cfg.dtype)
        x = x + MultiHeadAttention(
            cfg, causal=True, attn_fn=self.attn_fn, decode=self.decode,
            name="self_attn",
        )(h)
        h = _ln("ln_cross", cfg.ln_eps)(x).astype(cfg.dtype)
        x = x + MultiHeadAttention(cfg, attn_fn=self.attn_fn, name="cross_attn")(
            h, kv=enc, mask=enc_mask
        )
        h = _ln("ln_mlp", cfg.ln_eps)(x).astype(cfg.dtype)
        out = x + MlpBlock(cfg, name="mlp")(h)
        if cfg.partition_params:
            out = act_constraint(out, "batch", "seq", "embed")
        return out


def clean_cache(module: nn.Module, *init_args):
    """A CLEAN decode cache (zero buffers, index 0) for ``module`` given
    dummy init args. Never use ``module.init(...)["cache"]`` directly:
    flax runs the module body during init, so that cache already holds
    the init tokens' K/V with a nonzero index — position 0 would be
    garbage. Shared by the GPT and T5 serving paths so a cache-layout
    change in MultiHeadAttention cannot silently miss one of them."""
    shapes = jax.eval_shape(
        lambda: module.init(jax.random.key(0), *init_args)["cache"]
    )
    return jax.tree_util.tree_map(lambda a: jnp.zeros(a.shape, a.dtype), shapes)


class Embedder(nn.Module):
    """Token + learned positional embeddings; the token table is reused
    transposed as the output head (weight tying)."""

    cfg: TransformerConfig

    def setup(self):
        cfg = self.cfg
        self.tok = nn.Embed(
            cfg.vocab_size,
            cfg.embed_dim,
            param_dtype=jnp.float32,
            embedding_init=nn.with_partitioning(
                nn.initializers.normal(0.02), ("vocab", "embed")
            ),
            name="tok",
        )
        self.pos = self.param(
            "pos",
            nn.with_partitioning(nn.initializers.normal(0.02), (None, "embed")),
            (cfg.max_len, cfg.embed_dim),
            jnp.float32,
        )

    def __call__(
        self, ids: jax.Array, pos_offset: Optional[jax.Array] = None
    ) -> jax.Array:
        # Gather-before-use (FSDP convention): reshard the table/pos
        # params to embed-replicated BEFORE the lookup — a cheap rank-2
        # param all-gather over ``fsdp`` — so the [b,l,e] activation is
        # born batch-sharded. Without this the gather inherits the
        # table's fsdp'd embed dim and GSPMD later needs an
        # activation-layout flip it can only do by involuntary full
        # rematerialization (observed on dp×fsdp×tp meshes).
        # ``pos_offset`` (possibly traced) shifts the positional slice —
        # incremental decode feeds one token at absolute position offset.
        # A VECTOR pos_offset ([b]) gives each row its own offset: the
        # paged decode loop steps slots that sit at different absolute
        # positions in one dispatch (gather instead of a shared slice).
        def pos_slice(pos):
            if pos_offset is None:
                return pos[: ids.shape[-1]]
            if getattr(pos_offset, "ndim", 0) >= 1:
                rows = pos_offset[:, None] + jnp.arange(ids.shape[-1])
                return jnp.take(pos, rows, axis=0)  # [b, l, embed]
            return jax.lax.dynamic_slice_in_dim(
                pos, pos_offset, ids.shape[-1], axis=0
            )

        if self.cfg.partition_params:
            table = act_constraint(self.tok.embedding, "vocab", None)
            pos = act_constraint(self.pos, None, None)
            x = jnp.take(table, ids, axis=0) + pos_slice(pos)
            x = act_constraint(x, "batch", "seq", "embed")
        else:
            x = self.tok(ids) + pos_slice(self.pos)
        return x.astype(self.cfg.dtype)

    def logits(self, x: jax.Array) -> jax.Array:
        # tied output head; fp32 logits for a stable softmax
        out = jnp.einsum(
            "...d,vd->...v", x.astype(jnp.float32), self.tok.embedding
        )
        if self.cfg.partition_params:
            out = act_constraint(out, "batch", "seq", "vocab")
        return out


def apply_with_aux(model, cfg: TransformerConfig, params, *inputs):
    """Apply ``model`` collecting sown MoE aux losses -> (out, aux).
    Dense configs skip the mutable plumbing entirely (aux = 0)."""
    if cfg.num_experts > 0:
        out, mods = model.apply({"params": params}, *inputs, mutable=["losses"])
        aux = sum(jax.tree_util.tree_leaves(mods.get("losses", {})), 0.0)
        return out, aux
    return model.apply({"params": params}, *inputs), 0.0


def maybe_remat(layer_cls, cfg: TransformerConfig):
    """jax.checkpoint each layer when cfg.remat — recompute activations in
    the backward pass instead of holding them in HBM."""
    if cfg.remat:
        return nn.remat(layer_cls, prevent_cse=False)
    return layer_cls


def select_attn_fn(mesh, cfg: TransformerConfig, seq_len: int):
    """The mesh-driven attention-impl policy shared by the BERT, GPT and
    T5 families' ``task_for_mesh`` (one copy so their selection cannot
    drift). Every branch is mask-capable — the ring kernel rotates [b, lk]
    key-padding masks with k/v (parallel/ring_attention.py), so padded and
    enc-dec batches keep exact SP on every path.

    On a sequence-sharded mesh: Ulysses head-all-to-all SP while the
    sequence degree divides the per-device head count, ring attention
    beyond it; explicit 'ring'/'ulysses' pins honored anywhere, explicit
    'full'/'flash' pins REJECTED on a sequence-sharded mesh (never
    silently substituted). Otherwise the Pallas flash kernel per
    ops/flash_attention.auto_flash_attn_fn (explicit 'flash', or auto on
    TPU past FLASH_SEQ_THRESHOLD)."""
    from tfk8s_tpu.parallel.mesh import AXIS_SEQUENCE, AXIS_TENSOR
    from tfk8s_tpu.parallel.ring_attention import make_ring_attn_fn
    from tfk8s_tpu.parallel.ulysses import make_ulysses_attn_fn
    # NB: the ops package re-exports the flash_attention *function*,
    # shadowing the submodule attribute — import symbols from the
    # submodule directly.
    from tfk8s_tpu.ops.flash_attention import auto_flash_attn_fn

    seq_sharded = (
        AXIS_SEQUENCE in mesh.axis_names and mesh.shape[AXIS_SEQUENCE] > 1
    )
    if cfg.attention_impl == "ring":
        return make_ring_attn_fn(mesh)
    if cfg.attention_impl == "ulysses":
        return make_ulysses_attn_fn(mesh)
    if seq_sharded:
        if cfg.attention_impl != "auto":
            # an explicit full/flash pin cannot serve a sequence-sharded
            # mesh — refuse rather than silently substituting an SP impl
            raise ValueError(
                f"attention_impl={cfg.attention_impl!r} pinned on a "
                "sequence-sharded mesh; sequence parallelism needs "
                "'auto', 'ring', or 'ulysses'"
            )
        h_local = cfg.num_heads // mesh.shape.get(AXIS_TENSOR, 1)
        if h_local % mesh.shape[AXIS_SEQUENCE] == 0:
            return make_ulysses_attn_fn(mesh)
        return make_ring_attn_fn(mesh)
    return auto_flash_attn_fn(cfg.attention_impl, seq_len)


class Encoder(nn.Module):
    cfg: TransformerConfig
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, ids: jax.Array, mask: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.cfg
        x = Embedder(cfg, name="embed")(ids)
        layer = maybe_remat(EncoderLayer, cfg)
        for i in range(cfg.num_layers):
            x = layer(
                cfg,
                attn_fn=self.attn_fn,
                use_moe=cfg.layer_uses_moe(i),
                name=f"layer{i}",
            )(x, mask)
        return _ln("ln_final", cfg.ln_eps)(x).astype(cfg.dtype)
