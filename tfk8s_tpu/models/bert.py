"""BERT-base MLM pretraining — BASELINE.json configs[2]: 'BERT-base MLM
pretraining (XLA all-reduce over ICI)'. Headline metric: step-time on a
v5p-32-shaped mesh (BASELINE.json "metric"); the reference publishes
nothing (SURVEY.md §6).

The model is the shared encoder stack (models/transformer.py) with a tied
output head; gradients all-reduce over the ``data`` mesh axis as XLA
collectives — the exact north-star replacement for
MultiWorkerMirroredStrategy+NCCL (BASELINE.json north_star).

Hermetic data: sequences follow a fixed affine chain
``t[i+1] = (a*t[i] + b) mod V`` with random restarts, so a masked token is
predictable from either neighbor — MLM loss falls fast and convergence is
testable without a corpus.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from tfk8s_tpu.models.transformer import (
    Embedder,
    EncoderLayer,
    TransformerConfig,
    _ln,
    apply_with_aux,
    maybe_remat,
)
from tfk8s_tpu.runtime.train import TrainTask, run_task

MASK_ID = 0  # reserved mask token; chain tokens live in [1, vocab)
_CHAIN_A, _CHAIN_B = 31, 17
_RESTART_P = 0.05
MASK_RATE = 0.15


class BertWithHead(nn.Module):
    """Encoder + tied-embedding head, exposed as one module so the
    embedding table is shared naturally. ``attn_fn`` swaps the inner
    attention computation (ring attention on sequence-sharded meshes);
    ``causal=True`` makes every layer autoregressive — the SAME stack
    serves the BERT (bidirectional MLM) and GPT (decoder-only LM)
    families, so wiring fixes cannot drift between them."""

    cfg: TransformerConfig
    attn_fn: Optional[Any] = None
    causal: bool = False
    # incremental KV-cache generation (transformer.MultiHeadAttention
    # decode path); only meaningful with causal=True
    decode: bool = False
    # sow per-layer K/V into "kv_cache" during a full forward — batched
    # prefill support (models/gpt.prefill_cache)
    sow_kv: bool = False
    # block-paged KV cache (transformer.MultiHeadAttention paged path):
    # K/V in a shared page pool addressed by per-row page tables, the
    # continuous-batching decode loop's substrate (models/gpt.decode_step_packed)
    paged: bool = False

    def setup(self):
        self.embed = Embedder(self.cfg, name="embed")
        layer = maybe_remat(EncoderLayer, self.cfg)
        self.layers = [
            layer(
                self.cfg,
                attn_fn=self.attn_fn,
                use_moe=self.cfg.layer_uses_moe(i),
                causal=self.causal,
                decode=self.decode,
                sow_kv=self.sow_kv,
                paged=self.paged,
                name=f"layer{i}",
            )
            for i in range(self.cfg.num_layers)
        ]
        self.ln_final = _ln("ln_final", self.cfg.ln_eps)

    def __call__(
        self,
        ids: jax.Array,
        mask: Optional[jax.Array] = None,
        pos_offset: Optional[jax.Array] = None,
        page_tables: Optional[jax.Array] = None,
    ) -> jax.Array:
        # in paged mode pos_offset is the per-row position vector; it
        # feeds BOTH the positional gather and the attention page math
        x = self.embed(ids, pos_offset=pos_offset)
        for layer in self.layers:
            if self.paged:
                x = layer(
                    x, mask, page_tables=page_tables, positions=pos_offset
                )
            else:
                x = layer(x, mask)
        x = self.ln_final(x).astype(self.cfg.dtype)
        return self.embed.logits(x)  # [b, l, vocab], fp32


def base_config(**overrides) -> TransformerConfig:
    """BERT-base: 12 layers / 768 hidden / 12 heads / 3072 mlp."""
    kw = dict(
        vocab_size=30522, embed_dim=768, num_heads=12, head_dim=64,
        mlp_dim=3072, num_layers=12, max_len=512,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def make_chain_tokens(
    rng: np.random.Generator, batch_size: int, seq_len: int, vocab: int
) -> np.ndarray:
    """The hermetic token stream shared by the BERT and GPT families:
    ``t[i+1] = (a*t[i] + b) mod V`` with random restarts — predictable
    from a neighbor, so both MLM and next-token objectives are learnable
    with zero dataset I/O. ONE copy so the families' documented
    data-equivalence cannot drift."""
    toks = np.empty((batch_size, seq_len), np.int64)
    toks[:, 0] = rng.integers(1, vocab, size=batch_size)
    restarts = rng.random((batch_size, seq_len)) < _RESTART_P
    fresh = rng.integers(1, vocab, size=(batch_size, seq_len))
    for i in range(1, seq_len):
        nxt = (_CHAIN_A * toks[:, i - 1] + _CHAIN_B) % (vocab - 1) + 1
        toks[:, i] = np.where(restarts[:, i], fresh[:, i], nxt)
    return toks


def make_batch_fn(vocab: int, seq_len: int):
    def make_batch(rng: np.random.Generator, batch_size: int) -> Dict[str, np.ndarray]:
        toks = make_chain_tokens(rng, batch_size, seq_len, vocab)
        mlm_mask = rng.random((batch_size, seq_len)) < MASK_RATE
        inputs = np.where(mlm_mask, MASK_ID, toks)
        return {
            "input": inputs.astype(np.int32),
            "target": toks.astype(np.int32),
            "mlm_mask": mlm_mask,
        }

    return make_batch


def mlm_loss_and_metrics(
    logits: jax.Array, batch: Dict[str, jax.Array]
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Masked-LM objective shared by the BERT and pipelined families:
    cross-entropy and accuracy over the mlm-masked positions only."""
    per_tok = optax.softmax_cross_entropy_with_integer_labels(
        logits, batch["target"]
    )
    w = batch["mlm_mask"].astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(w), 1.0)
    loss = jnp.sum(per_tok * w) / denom
    acc = jnp.sum(
        (jnp.argmax(logits, -1) == batch["target"]).astype(jnp.float32) * w
    ) / denom
    return loss, {"mlm_accuracy": acc}


def make_task(
    cfg: Optional[TransformerConfig] = None,
    seq_len: int = 128,
    batch_size: int = 64,
    targets: Optional[Dict[str, float]] = None,
    attn_fn: Optional[Any] = None,
) -> TrainTask:
    cfg = cfg or base_config()
    seq_len = min(seq_len, cfg.max_len)
    model = BertWithHead(cfg, attn_fn=attn_fn)

    def init(rng):
        # full batch shape: ring attention's shard_map needs the batch dim
        # divisible by the data axis even at trace time
        return model.init(rng, jnp.zeros((batch_size, seq_len), jnp.int32))["params"]

    def loss_fn(params, batch, rng) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        logits, aux = apply_with_aux(model, cfg, params, batch["input"])
        loss, metrics = mlm_loss_and_metrics(logits, batch)
        if cfg.num_experts > 0:
            metrics["moe_aux"] = aux
            loss = loss + cfg.moe_aux_weight * aux
        return loss, metrics

    return TrainTask(
        name="bert-mlm",
        init=init,
        loss_fn=loss_fn,
        make_batch=make_batch_fn(cfg.vocab_size, seq_len),
        batch_size=batch_size,
        targets=targets or {},
    )


def tiny_config(**overrides) -> TransformerConfig:
    """Test-scale config (runs in seconds on the CPU backend)."""
    kw = dict(
        vocab_size=64, embed_dim=32, num_heads=4, head_dim=8,
        mlp_dim=64, num_layers=2, max_len=64,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def task_for_mesh(
    mesh,
    cfg: Optional[TransformerConfig] = None,
    **task_kw,
) -> TrainTask:
    """Build the task with the attention impl the mesh calls for. On a
    sequence-sharded mesh: Ulysses head-all-to-all SP while the sequence
    degree fits within the per-device head count, ring attention beyond
    it (the long-context recipe — parallel/ulysses.py docstring); either
    is also explicitly selectable via cfg.attention_impl ('ring' /
    'ulysses'). Otherwise the pallas flash kernel when
    cfg.attention_impl == 'flash' — or by default on TPU once the
    sequence length crosses FLASH_SEQ_THRESHOLD (the XLA path's [L, L]
    scores buffer starts dominating HBM; flash's is O(L·d))."""
    from tfk8s_tpu.models.transformer import select_attn_fn

    cfg = cfg or base_config()
    # The EFFECTIVE length — make_task clamps to cfg.max_len — decides
    # the impl; flash's kernel additionally needs the length to divide
    # its q/k blocks, so auto-selection picks the largest dividing
    # candidates via pick_blocks (any 128-multiple length qualifies).
    # Explicit cfg.attention_impl == "flash" trusts the caller's blocks.
    seq_len = min(task_kw.get("seq_len", 128), cfg.max_len)
    attn_fn = select_attn_fn(mesh, cfg, seq_len)
    return make_task(cfg=cfg, attn_fn=attn_fn, **task_kw)


def train(env: Dict[str, str], stop: Optional[Any] = None) -> None:
    """TPUJob entrypoint: ``tfk8s_tpu.models.bert:train``. MoE (EP) is
    job-configurable: ``TFK8S_NUM_EXPERTS`` > 0 swaps every other MLP for
    a SwitchMoeBlock sharded over the mesh's ``expert`` axis.
    ``TFK8S_MODEL_PRESET=tiny`` selects the test-scale config (hermetic
    e2e jobs); ``TFK8S_ATTENTION_IMPL`` pins an attention impl
    (full/flash/ring/ulysses) instead of the mesh-driven default."""
    from tfk8s_tpu.runtime.launcher import ProcessContext, build_mesh, initialize_distributed

    env = dict(env)
    env.setdefault("TFK8S_TRAIN_STEPS", "100")
    env.setdefault("TFK8S_LEARNING_RATE", "1e-4")
    seq = int(env.get("TFK8S_SEQ_LEN", "128"))
    batch = int(env.get("TFK8S_BATCH_SIZE", "64"))
    preset = tiny_config if env.get("TFK8S_MODEL_PRESET") == "tiny" else base_config
    cfg = preset(
        num_experts=int(env.get("TFK8S_NUM_EXPERTS", "0")),
        moe_top_k=int(env.get("TFK8S_MOE_TOP_K", "1")),
        attention_impl=env.get("TFK8S_ATTENTION_IMPL", "auto"),
    )
    ctx = ProcessContext.from_env(env)
    initialize_distributed(ctx, env)
    mesh = build_mesh(ctx)
    task = task_for_mesh(mesh, cfg=cfg, seq_len=seq, batch_size=batch)
    run_task(task, env, stop, mesh=mesh)
