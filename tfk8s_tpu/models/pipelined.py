"""Pipeline-parallel BERT-MLM — the PP row of SURVEY.md §2 wired into a
real model family (the reference has no pipeline construct at all; the
mesh design reserves the ``pipeline`` axis for it, parallel/mesh.py).

Layout: the transformer body (the uniform-shape part — every encoder
layer maps [mb, seq, embed] -> [mb, seq, embed]) streams through the
GPipe schedule of parallel/pipeline.py, with ``num_layers / S`` layers
per stage and the stage dim of every stacked layer parameter sharded
over ``pipeline``. Embedding and the tied output head have non-uniform
shapes, so they live OUTSIDE the pipeline region — computed under the
ordinary GSPMD jit, exactly how the shape-preservation contract of
``pipeline_apply`` is meant to be satisfied for real models.

Composes with data parallelism: a ``pipeline × data`` mesh shards the
per-microbatch batch dim over ``data`` while each data shard pipelines
its own microbatch stream (``pipeline_apply(data_axis=...)``).

Hermetic data: the same affine-chain MLM stream as models/bert.py, so
loss behavior is directly comparable with the non-pipelined family.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from flax.core import meta as flax_meta

from tfk8s_tpu.models import bert
from tfk8s_tpu.models.transformer import (
    Embedder,
    EncoderLayer,
    TransformerConfig,
    _ln,
    maybe_remat,
)
from tfk8s_tpu.parallel import sharding as shd
from tfk8s_tpu.parallel.mesh import AXIS_DATA, AXIS_PIPELINE
from tfk8s_tpu.parallel.pipeline import pipeline_apply, split_microbatches
from tfk8s_tpu.runtime.train import TrainTask, run_task

# stage-stacked parameters get a leading logical axis mapped to the
# pipeline mesh axis (appended to the task's sharding rules)
STAGE_AXIS = "pipeline_stage"
PIPELINE_RULES = shd.DEFAULT_RULES + ((STAGE_AXIS, AXIS_PIPELINE),)


class PipelineStage(nn.Module):
    """One pipeline stage: a run of encoder layers (uniform activation
    shape in and out — the inter-stage contract)."""

    cfg: TransformerConfig
    layers_per_stage: int

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        layer = maybe_remat(EncoderLayer, self.cfg)
        for i in range(self.layers_per_stage):
            x = layer(self.cfg, name=f"layer{i}")(x)
        return x


def _stack_boxed(per_stage: list) -> Any:
    """Stack per-stage boxed param trees along a new leading stage dim,
    rewriting each leaf's Partitioned names to carry STAGE_AXIS first."""

    def one(*leaves):
        if isinstance(leaves[0], flax_meta.Partitioned):
            return flax_meta.Partitioned(
                jnp.stack([l.value for l in leaves], axis=0),
                names=(STAGE_AXIS,) + tuple(leaves[0].names),
            )
        return flax_meta.Partitioned(
            jnp.stack(leaves, axis=0), names=(STAGE_AXIS,)
        )

    return jax.tree_util.tree_map(
        one, *per_stage, is_leaf=lambda x: isinstance(x, flax_meta.Partitioned)
    )


def make_task(
    mesh,
    cfg: Optional[TransformerConfig] = None,
    seq_len: int = 64,
    batch_size: int = 32,
    num_micro: Optional[int] = None,
    targets: Optional[Dict[str, float]] = None,
) -> TrainTask:
    """Pipelined MLM task for ``mesh`` (must carry a ``pipeline`` axis;
    a ``data`` axis composes DP). Reference parity note: the reference's
    only scale-out axis is replica count (k8s-operator.md:6); this is the
    PP strategy its domain model never had."""
    cfg = cfg or bert.tiny_config()
    # Config features the pipeline body doesn't implement must fail fast,
    # not silently train a different model than every other family would.
    assert cfg.num_experts == 0, (
        "pipelined family does not support MoE stages yet; use the "
        "BERT/T5 MoE path (TransformerConfig.num_experts) on a non-"
        "pipeline mesh"
    )
    assert cfg.attention_impl in ("auto", "full"), (
        f"pipelined family supports only full attention inside stages, "
        f"got {cfg.attention_impl!r}"
    )
    num_stages = mesh.shape[AXIS_PIPELINE]
    assert cfg.num_layers % num_stages == 0, (
        f"num_layers {cfg.num_layers} must divide evenly into {num_stages} stages"
    )
    layers_per_stage = cfg.num_layers // num_stages
    num_micro = num_micro or max(2 * num_stages, 4)
    assert batch_size % num_micro == 0, (
        f"batch {batch_size} must divide into {num_micro} microbatches"
    )
    micro_bs = batch_size // num_micro
    data_axis = AXIS_DATA if AXIS_DATA in mesh.axis_names else None
    if data_axis:
        assert micro_bs % mesh.shape[data_axis] == 0, (
            f"microbatch size {micro_bs} (batch {batch_size} / "
            f"{num_micro} microbatches) must divide over the data axis "
            f"({mesh.shape[data_axis]} shards)"
        )

    seq_len = min(seq_len, cfg.max_len)
    embedder = Embedder(cfg)
    # Stage params drop their flax Partitioned boxes (see
    # TransformerConfig.partition_params): inside the shard_map pipeline
    # region flax would re-emit logical-name sharding constraints the
    # manual mesh can't satisfy. Stage sharding comes from the
    # STAGE_AXIS rebox in _stack_boxed instead.
    import dataclasses as _dc

    stage_cfg = _dc.replace(cfg, partition_params=False)
    stage = PipelineStage(stage_cfg, layers_per_stage)
    ln_final = _ln("ln_final", cfg.ln_eps)

    def init(rng):
        r_embed, r_stage, r_ln = jax.random.split(rng, 3)
        ids = jnp.zeros((micro_bs, seq_len), jnp.int32)
        x = jnp.zeros((micro_bs, seq_len, cfg.embed_dim), cfg.dtype)
        embed_vars = embedder.init(r_embed, ids)["params"]
        stages = [
            stage.init(jax.random.fold_in(r_stage, s), x)["params"]
            for s in range(num_stages)
        ]
        ln_vars = ln_final.init(r_ln, x.astype(jnp.float32))["params"]
        return {
            "embed": embed_vars,
            "stages": _stack_boxed(stages),
            "ln_final": ln_vars,
        }

    def loss_fn(params, batch, rng) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        x = embedder.apply({"params": params["embed"]}, batch["input"])
        micro = split_microbatches(x, num_micro)  # [M, mb, s, m]
        y = pipeline_apply(
            lambda p, a: stage.apply({"params": p}, a),
            params["stages"],
            micro,
            mesh,
            data_axis=data_axis,
        )
        y = y.reshape(x.shape)
        y = ln_final.apply({"params": params["ln_final"]}, y).astype(cfg.dtype)
        logits = embedder.apply(
            {"params": params["embed"]}, y, method=Embedder.logits
        )
        return bert.mlm_loss_and_metrics(logits, batch)

    return TrainTask(
        name="bert-mlm-pipelined",
        init=init,
        loss_fn=loss_fn,
        make_batch=bert.make_batch_fn(cfg.vocab_size, seq_len),
        batch_size=batch_size,
        rules=PIPELINE_RULES,
        targets=targets or {},
    )


def train(env: Dict[str, str], stop: Optional[Any] = None) -> None:
    """TPUJob entrypoint: ``tfk8s_tpu.models.pipelined:train``. The job's
    TFK8S_MESH must carry a ``pipeline`` axis; ``TFK8S_NUM_MICRO`` sets
    the microbatch count (more microbatches -> smaller GPipe bubble)."""
    from tfk8s_tpu.runtime.launcher import ProcessContext, build_mesh, initialize_distributed

    env = dict(env)
    env.setdefault("TFK8S_TRAIN_STEPS", "100")
    env.setdefault("TFK8S_LEARNING_RATE", "1e-3")
    ctx = ProcessContext.from_env(env)
    initialize_distributed(ctx, env)
    mesh = build_mesh(ctx)
    cfg = bert.base_config(
        num_layers=int(env.get("TFK8S_NUM_LAYERS", "12")),
    )
    task = make_task(
        mesh,
        cfg=cfg,
        seq_len=int(env.get("TFK8S_SEQ_LEN", "128")),
        batch_size=int(env.get("TFK8S_BATCH_SIZE", "64")),
        num_micro=int(env["TFK8S_NUM_MICRO"]) if "TFK8S_NUM_MICRO" in env else None,
    )
    run_task(task, env, stop, mesh=mesh)
