"""ViT image classifier — the transformer-on-images family, sharing ONE
encoder stack with BERT/GPT/T5 (models/transformer.EncoderLayer), so
every parallelism strategy and attention impl the text families get
(TP via the logical sharding rules, SP via the shared select_attn_fn
policy, flash kernels, MoE layers) applies to vision unchanged.

Beyond the five reference baseline configs (SURVEY.md §6): the reference
operator is model-agnostic, and a framework claiming its capabilities
should demonstrate the SAME agnosticism — a new family is a patch
embedding plus a head around the existing stack, not a new stack.

Hermetic data: the class-conditional template images ResNet trains on
(models/resnet.make_batch_fn), so the two vision families are directly
comparable on one task. With ``TFK8S_INPUT_FILES`` +
``TFK8S_INPUT_FORMAT=image`` the same entrypoint instead trains from
PACKED IMAGE SHARDS through the shared files-input mode (data/images
decode + augmentation pool) — the batch schema is identical, so the
swap is configuration, not code.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from tfk8s_tpu.models.resnet import make_batch_fn
from tfk8s_tpu.models.transformer import (
    EncoderLayer,
    TransformerConfig,
    _dense,
    _ln,
    apply_with_aux,
    maybe_remat,
)
from tfk8s_tpu.runtime.train import TrainTask, run_task


class ViT(nn.Module):
    """Patchify → linear embed (+ learned positions) → shared encoder
    stack → mean-pool → linear head. Mean-pool instead of a CLS token:
    one less sequence position to shard and equal accuracy at this
    scale."""

    cfg: TransformerConfig
    num_classes: int
    patch_size: int
    attn_fn: Optional[Any] = None

    @nn.compact
    def __call__(self, images: jax.Array) -> jax.Array:
        cfg, p = self.cfg, self.patch_size
        b, h, w, c = images.shape
        if h % p or w % p:
            raise ValueError(f"image {h}x{w} not divisible by patch {p}")
        gh, gw = h // p, w // p
        x = images.reshape(b, gh, p, gw, p, c)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, gh * gw, p * p * c)
        x = _dense(cfg.embed_dim, (None, "embed"), "patch_embed", cfg.dtype)(x)
        pos = self.param(
            "pos",
            nn.with_partitioning(nn.initializers.normal(0.02), (None, "embed")),
            (gh * gw, cfg.embed_dim),
            jnp.float32,
        )
        x = (x + pos[None]).astype(cfg.dtype)
        layer = maybe_remat(EncoderLayer, cfg)
        for i in range(cfg.num_layers):
            x = layer(
                cfg,
                attn_fn=self.attn_fn,
                use_moe=cfg.layer_uses_moe(i),
                name=f"layer{i}",
            )(x, None)
        x = _ln("ln_final", cfg.ln_eps)(x).astype(cfg.dtype)
        x = jnp.mean(x, axis=1)
        logits = _dense(
            self.num_classes, ("embed", None), "head", jnp.float32
        )(x)
        return logits.astype(jnp.float32)


def base_config(**overrides) -> TransformerConfig:
    """ViT-Base scale: 12 layers / 768 / 12 heads / 3072 (vocab unused —
    images enter through the patch projection)."""
    kw = dict(
        vocab_size=1, embed_dim=768, num_heads=12, head_dim=64,
        mlp_dim=3072, num_layers=12, max_len=1024,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def tiny_config(**overrides) -> TransformerConfig:
    kw = dict(
        vocab_size=1, embed_dim=32, num_heads=4, head_dim=8,
        mlp_dim=64, num_layers=2, max_len=256,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def make_task(
    cfg: Optional[TransformerConfig] = None,
    num_classes: int = 8,
    image_size: int = 32,
    patch_size: int = 4,
    batch_size: int = 64,
    targets: Optional[Dict[str, float]] = None,
    attn_fn: Optional[Any] = None,
) -> TrainTask:
    cfg = cfg or tiny_config()
    model = ViT(
        cfg, num_classes=num_classes, patch_size=patch_size, attn_fn=attn_fn
    )

    def init(rng):
        # full batch shape: an SP attn_fn's shard_map needs the real batch
        # dim even at trace time (same as bert/t5)
        z = jnp.zeros((batch_size, image_size, image_size, 3), jnp.float32)
        return model.init(rng, z)["params"]

    def loss_fn(params, batch, rng) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        # apply_with_aux collects the sown MoE load-balance loss — same
        # plumbing as the text families, so MoE ViT layers actually get
        # their balancing pressure instead of silently training dense
        logits, aux = apply_with_aux(model, cfg, params, batch["image"])
        loss = jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["label"]
            )
        )
        acc = jnp.mean(
            (jnp.argmax(logits, -1) == batch["label"]).astype(jnp.float32)
        )
        metrics = {"accuracy": acc}
        if cfg.num_experts > 0:
            metrics["moe_aux"] = aux
            loss = loss + cfg.moe_aux_weight * aux
        return loss, metrics

    return TrainTask(
        name="vit",
        init=init,
        loss_fn=loss_fn,
        make_batch=make_batch_fn(num_classes, image_size),
        batch_size=batch_size,
        targets=targets or {},
    )


def task_for_mesh(mesh, cfg: Optional[TransformerConfig] = None, **task_kw):
    """Shared attention policy (transformer.select_attn_fn): the patch
    sequence shards over `sequence` like any token sequence — Ulysses
    within the head count, ring beyond, flash on long patch grids."""
    from tfk8s_tpu.models.transformer import select_attn_fn

    cfg = cfg or tiny_config()
    img = task_kw.get("image_size", 32)
    patch = task_kw.get("patch_size", 4)
    seq_len = (img // patch) ** 2
    return make_task(
        cfg=cfg, attn_fn=select_attn_fn(mesh, cfg, seq_len), **task_kw
    )


def train(env: Dict[str, str], stop: Optional[Any] = None) -> None:
    """TPUJob entrypoint: ``tfk8s_tpu.models.vit:train``. Builds the mesh
    and routes through ``task_for_mesh`` like the text families, so
    ``TFK8S_ATTENTION_IMPL`` pins are honored (or rejected loudly) by the
    shared policy instead of being silently ignored."""
    from tfk8s_tpu.runtime.launcher import (
        ProcessContext,
        build_mesh,
        initialize_distributed,
    )

    env = dict(env)
    env.setdefault("TFK8S_TRAIN_STEPS", "150")
    env.setdefault("TFK8S_LEARNING_RATE", "1e-3")
    preset = tiny_config if env.get("TFK8S_MODEL_PRESET") == "tiny" else base_config
    cfg = preset(
        attention_impl=env.get("TFK8S_ATTENTION_IMPL", "auto"),
        num_experts=int(env.get("TFK8S_NUM_EXPERTS", "0")),
        moe_top_k=int(env.get("TFK8S_MOE_TOP_K", "1")),
    )
    ctx = ProcessContext.from_env(env)
    initialize_distributed(ctx, env)
    mesh = build_mesh(ctx)
    task = task_for_mesh(
        mesh,
        cfg=cfg,
        num_classes=int(env.get("TFK8S_NUM_CLASSES", "8")),
        image_size=int(env.get("TFK8S_IMAGE_SIZE", "32")),
        patch_size=int(env.get("TFK8S_PATCH_SIZE", "4")),
        batch_size=int(env.get("TFK8S_BATCH_SIZE", "64")),
        targets={"accuracy": float(env["TFK8S_TARGET_ACCURACY"])}
        if env.get("TFK8S_TARGET_ACCURACY")
        else None,
    )
    run_task(task, env, stop, mesh=mesh)
