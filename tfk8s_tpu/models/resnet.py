"""ResNet-50 — BASELINE.json configs[1]: 'ResNet-50 ImageNet (TPUStrategy
data-parallel on v5p mesh)'. The headline metric is images/sec/chip
(BASELINE.json "metric"); the reference itself publishes no numbers
(SURVEY.md §6), so this model establishes the baseline.

TPU-first design choices (vs a torch/GPU translation):

- **GroupNorm, not BatchNorm.** BatchNorm needs a cross-replica moment
  all-reduce every layer (or per-replica stats that drift) plus mutable
  running-stat state. GroupNorm is stateless, batch-independent, fuses
  into the surrounding convs under XLA, and keeps the train step a pure
  function — the whole model stays one jittable pure fn.
- **bfloat16 compute, float32 params.** Convs/matmuls run on the MXU in
  bf16; the optimizer update and the norm STATISTICS stay fp32 (flax
  computes them in f32 internally), while norm outputs are bf16 to keep
  activation HBM traffic halved end to end.
- **NHWC layout** — XLA:TPU's native conv layout.
- Kernels carry logical axes (``conv_out`` → fsdp; final dense
  ``embed``/``vocab``) so the same model runs data-parallel or FSDP
  without edits (parallel/sharding.py rules).

Data is hermetic/synthetic: class-conditional templates + noise, so the
loss measurably falls (a learnable task) with zero dataset I/O — same
philosophy as models/mlp.py.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from tfk8s_tpu.runtime.train import TrainTask, run_task

# stage depths for the standard variants
DEPTHS = {
    18: (2, 2, 2, 2),
    34: (3, 4, 6, 3),
    50: (3, 4, 6, 3),
    101: (3, 4, 23, 3),
    152: (3, 8, 36, 3),
}
BOTTLENECK = {50, 101, 152}

_conv_part = functools.partial(
    nn.with_partitioning,
    names=("conv_k", "conv_k", "conv_in", "conv_out"),
)


def _conv(features: int, kernel: int, strides: int = 1, name: Optional[str] = None):
    return nn.Conv(
        features,
        (kernel, kernel),
        strides=(strides, strides),
        padding="SAME",
        use_bias=False,
        dtype=jnp.bfloat16,
        param_dtype=jnp.float32,
        kernel_init=_conv_part(nn.initializers.variance_scaling(2.0, "fan_out", "normal")),
        name=name,
    )


def _groups(channels: int) -> int:
    # 32 groups is the GN paper default; shrink until it divides (small
    # test widths).
    g = min(32, channels)
    while channels % g:
        g //= 2
    return max(g, 1)


class _Identity(nn.Module):
    """Stand-in for an ablated norm (measurement probes only)."""

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        return x


# Measurement-probe switch (tools/roofline.py): True = normal GroupNorm,
# False = every norm is identity, isolating the norm chain's cost in the
# step-time decomposition (PERF_RESNET.md §4). Never a training config.
_NORM_ENABLED = True


@contextlib.contextmanager
def ablate_norm():
    """Scope in which every ResNet norm is identity. Model construction
    AND jit tracing must happen inside the scope (flax traces lazily)."""
    global _NORM_ENABLED
    _NORM_ENABLED = False
    try:
        yield
    finally:
        _NORM_ENABLED = True


def _norm(channels: int, name: Optional[str] = None, scale_init=nn.initializers.ones):
    # dtype=bf16 halves the HBM traffic of every norm/relu chain (+28%
    # measured step throughput at batch 256); numerically safe because
    # flax computes the mean/variance statistics in float32 internally
    # regardless of dtype — only the normalized OUTPUT is bf16.
    if not _NORM_ENABLED:
        return _Identity(name=name)
    return nn.GroupNorm(
        num_groups=_groups(channels), dtype=jnp.bfloat16, param_dtype=jnp.float32,
        scale_init=scale_init, name=name,
    )


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 with 4x expansion; zero-init final norm scale so
    each residual branch starts as identity (standard trick, helps large
    batch — and costs nothing under XLA)."""

    features: int
    strides: int = 1

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        residual = x
        y = _conv(self.features, 1, name="conv1")(x)
        y = nn.relu(_norm(self.features, name="norm1")(y))
        y = _conv(self.features, 3, self.strides, name="conv2")(y)
        y = nn.relu(_norm(self.features, name="norm2")(y))
        y = _conv(self.features * 4, 1, name="conv3")(y)
        y = _norm(self.features * 4, name="norm3", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = _conv(self.features * 4, 1, self.strides, name="proj")(x)
            residual = _norm(self.features * 4, name="proj_norm")(residual)
        return nn.relu(y + residual.astype(y.dtype))


class BasicBlock(nn.Module):
    """3x3 -> 3x3, for ResNet-18/34 (small/test variants)."""

    features: int
    strides: int = 1

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        residual = x
        y = _conv(self.features, 3, self.strides, name="conv1")(x)
        y = nn.relu(_norm(self.features, name="norm1")(y))
        y = _conv(self.features, 3, name="conv2")(y)
        y = _norm(self.features, name="norm2", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = _conv(self.features, 1, self.strides, name="proj")(x)
            residual = _norm(self.features, name="proj_norm")(residual)
        return nn.relu(y + residual.astype(y.dtype))


class ResNet(nn.Module):
    depth: int = 50
    num_classes: int = 1000
    width: int = 64  # stem width; stages are width * (1,2,4,8)

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(jnp.bfloat16)
        x = _conv(self.width, 7, 2, name="stem")(x)
        x = nn.relu(_norm(self.width, name="stem_norm")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        block = BottleneckBlock if self.depth in BOTTLENECK else BasicBlock
        for stage, depth in enumerate(DEPTHS[self.depth]):
            for i in range(depth):
                x = block(
                    self.width * (2 ** stage),
                    strides=2 if stage > 0 and i == 0 else 1,
                    name=f"stage{stage + 1}_block{i + 1}",
                )(x)
        x = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)  # global average pool
        return nn.Dense(
            self.num_classes,
            dtype=jnp.float32,
            kernel_init=nn.with_partitioning(
                nn.initializers.zeros, ("embed", "vocab")
            ),
            name="classifier",
        )(x)


# -- synthetic learnable data -------------------------------------------------

_TEMPLATE_SEED = 4321


@functools.lru_cache(maxsize=None)
def _template(cls: int, image_size: int) -> np.ndarray:
    """ONE class's template, generated lazily from a per-class seed.
    Memory tracks the classes actually sampled: the files-input schema
    probe (make_batch of ONE row) used to pay for the whole bank — at
    the shipped ImageNet config that was a ~600 MB allocation per worker
    for a pipeline that never trains on synthetic data."""
    rng = np.random.default_rng(
        np.random.SeedSequence([_TEMPLATE_SEED, cls, image_size])
    )
    return rng.standard_normal((image_size, image_size, 3)).astype(np.float32)


def make_batch_fn(num_classes: int, image_size: int):
    def make_batch(rng: np.random.Generator, batch_size: int) -> Dict[str, np.ndarray]:
        y = rng.integers(0, num_classes, size=(batch_size,), dtype=np.int64)
        noise = rng.standard_normal((batch_size, image_size, image_size, 3))
        temps = np.stack([_template(int(c), image_size) for c in y])
        x = (0.6 * temps + noise).astype(np.float32)
        return {"image": x, "label": y.astype(np.int32)}

    return make_batch


def make_task(
    depth: int = 50,
    num_classes: int = 1000,
    image_size: int = 224,
    batch_size: int = 256,
    width: int = 64,
    targets: Optional[Dict[str, float]] = None,
) -> TrainTask:
    model = ResNet(depth=depth, num_classes=num_classes, width=width)

    def init(rng):
        return model.init(
            rng, jnp.zeros((1, image_size, image_size, 3), jnp.float32)
        )["params"]

    def loss_fn(params, batch, rng) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        logits = model.apply({"params": params}, batch["image"])
        loss = jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, batch["label"])
        )
        acc = jnp.mean((jnp.argmax(logits, -1) == batch["label"]).astype(jnp.float32))
        return loss, {"accuracy": acc}

    return TrainTask(
        name=f"resnet{depth}",
        init=init,
        loss_fn=loss_fn,
        make_batch=make_batch_fn(num_classes, image_size),
        batch_size=batch_size,
        targets=targets or {},
    )


def train(env: Dict[str, str], stop: Optional[Any] = None) -> None:
    """TPUJob entrypoint: ``tfk8s_tpu.models.resnet:train``.

    With ``TFK8S_INPUT_FILES`` + ``TFK8S_INPUT_FORMAT=image`` the job
    trains from PACKED IMAGE SHARDS (data/images: JPEG decode + seeded
    augmentation on a worker pool) instead of the synthetic generator —
    the files-input manifest ``manifests/examples/resnet50-images.yaml``
    rides this. ``TFK8S_NUM_CLASSES`` must then match the packed
    ``labels.json``; ``TFK8S_TARGET_ACCURACY`` turns the run into a
    convergence check (the pod FAILS when training misses it)."""
    env = dict(env)
    env.setdefault("TFK8S_TRAIN_STEPS", "100")
    env.setdefault("TFK8S_LEARNING_RATE", "1e-3")
    depth = int(env.get("TFK8S_RESNET_DEPTH", "50"))
    batch = int(env.get("TFK8S_BATCH_SIZE", "256"))
    image = int(env.get("TFK8S_IMAGE_SIZE", "224"))
    num_classes = int(env.get("TFK8S_NUM_CLASSES", "1000"))
    width = int(env.get("TFK8S_RESNET_WIDTH", "64"))
    run_task(
        make_task(
            depth=depth,
            batch_size=batch,
            image_size=image,
            num_classes=num_classes,
            width=width,
            targets={"accuracy": float(env["TFK8S_TARGET_ACCURACY"])}
            if env.get("TFK8S_TARGET_ACCURACY")
            else None,
        ),
        env,
        stop,
    )
