"""T5-base encoder-decoder — BASELINE.json configs[3]: 'T5-base seq2seq
(XLA SPMD model-parallel sharding)'. The point of this config is the
GSPMD tensor-parallel path: every projection carries logical axes
(models/transformer.py), so on a mesh with a ``tensor`` axis the weights
shard Megatron-style over ICI — the TP row of SURVEY.md §2's parallelism
table, which the reference lacks entirely.

Architecture notes (kept deliberately close to the shared blocks rather
than a faithful T5 reimplementation — the framework's job is the sharded
execution, not checkpoint compatibility):

- pre-LN blocks, learned positions, tied softmax (models/transformer.py)
  instead of T5's relative-position biases and RMSNorm;
- teacher-forced decoding; loss is cross-entropy over the target
  sequence with padding masked out.

Hermetic data: sequence reversal — target = reversed(source). The
decoder must actually use cross-attention to solve it (a copy-through
would fail), so convergence demonstrates the full enc-dec path.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from tfk8s_tpu.models.transformer import (
    DecoderLayer,
    Embedder,
    EncoderLayer,
    TransformerConfig,
    _ln,
    apply_with_aux,
    maybe_remat,
)
from tfk8s_tpu.runtime.train import TrainTask, run_task

PAD_ID = 0
BOS_ID = 1
# real tokens live in [2, vocab)


class T5(nn.Module):
    """Encoder-decoder with a shared embedding table and tied head.
    ``decode=True`` builds the decoder layers in incremental KV-cache
    mode (one target token per ``decode`` call, ``pos_offset`` carrying
    the absolute position) — the serving path behind
    ``greedy_generate``."""

    cfg: TransformerConfig
    attn_fn: Optional[Any] = None  # e.g. ops.flash_attention (mask-capable)
    decode_mode: bool = False

    def setup(self):
        cfg = self.cfg
        self.embed = Embedder(cfg, name="embed")
        enc_layer = maybe_remat(EncoderLayer, cfg)
        dec_layer = maybe_remat(DecoderLayer, cfg)
        self.enc_layers = [
            enc_layer(
                cfg,
                attn_fn=self.attn_fn,
                use_moe=cfg.layer_uses_moe(i),
                name=f"enc{i}",
            )
            for i in range(cfg.num_layers)
        ]
        self.dec_layers = [
            dec_layer(
                cfg, attn_fn=self.attn_fn, decode=self.decode_mode,
                name=f"dec{i}",
            )
            for i in range(cfg.num_layers)
        ]
        self.enc_ln = _ln("enc_ln", self.cfg.ln_eps)
        self.dec_ln = _ln("dec_ln", self.cfg.ln_eps)

    def encode(self, src: jax.Array) -> Tuple[jax.Array, jax.Array]:
        mask = src != PAD_ID
        x = self.embed(src)
        for layer in self.enc_layers:
            x = layer(x, mask)
        return self.enc_ln(x).astype(self.cfg.dtype), mask

    def decode(
        self,
        tgt_in: jax.Array,
        enc: jax.Array,
        enc_mask: jax.Array,
        pos_offset: Optional[jax.Array] = None,
    ) -> jax.Array:
        x = self.embed(tgt_in, pos_offset=pos_offset)
        for layer in self.dec_layers:
            x = layer(x, enc, enc_mask)
        x = self.dec_ln(x).astype(self.cfg.dtype)
        return self.embed.logits(x)

    def __call__(self, src: jax.Array, tgt_in: jax.Array) -> jax.Array:
        enc, mask = self.encode(src)
        return self.decode(tgt_in, enc, mask)


def base_config(**overrides) -> TransformerConfig:
    """T5-base-scale: 12+12 layers / 768 / 12 heads / 3072."""
    kw = dict(
        vocab_size=32128, embed_dim=768, num_heads=12, head_dim=64,
        mlp_dim=3072, num_layers=12, max_len=512,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def tiny_config(**overrides) -> TransformerConfig:
    kw = dict(
        vocab_size=64, embed_dim=32, num_heads=4, head_dim=8,
        mlp_dim=64, num_layers=2, max_len=64,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def make_batch_fn(vocab: int, seq_len: int):
    def make_batch(rng: np.random.Generator, batch_size: int) -> Dict[str, np.ndarray]:
        src = rng.integers(2, vocab, size=(batch_size, seq_len))
        tgt = src[:, ::-1]  # reversal task
        tgt_in = np.concatenate(
            [np.full((batch_size, 1), BOS_ID, np.int64), tgt[:, :-1]], axis=1
        )
        return {
            "src": src.astype(np.int32),
            "tgt_in": tgt_in.astype(np.int32),
            "tgt_out": tgt.astype(np.int32),
        }

    return make_batch


def make_task(
    cfg: Optional[TransformerConfig] = None,
    seq_len: int = 128,
    batch_size: int = 32,
    targets: Optional[Dict[str, float]] = None,
    attn_fn: Optional[Any] = None,
) -> TrainTask:
    cfg = cfg or base_config()
    seq_len = min(seq_len, cfg.max_len)
    model = T5(cfg, attn_fn=attn_fn)

    def init(rng):
        # full batch shape: an SP attn_fn's shard_map needs the batch dim
        # divisible by the data axis even at trace time (same as bert.py)
        z = jnp.zeros((batch_size, seq_len), jnp.int32)
        return model.init(rng, z, z)["params"]

    def loss_fn(params, batch, rng) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        logits, aux = apply_with_aux(
            model, cfg, params, batch["src"], batch["tgt_in"]
        )
        per_tok = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["tgt_out"]
        )
        w = (batch["tgt_out"] != PAD_ID).astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(w), 1.0)
        loss = jnp.sum(per_tok * w) / denom
        acc = jnp.sum(
            (jnp.argmax(logits, -1) == batch["tgt_out"]).astype(jnp.float32) * w
        ) / denom
        metrics = {"token_accuracy": acc}
        if cfg.num_experts > 0:
            metrics["moe_aux"] = aux
            loss = loss + cfg.moe_aux_weight * aux
        return loss, metrics

    return TrainTask(
        name="t5-seq2seq",
        init=init,
        loss_fn=loss_fn,
        make_batch=make_batch_fn(cfg.vocab_size, seq_len),
        batch_size=batch_size,
        targets=targets or {},
    )


def init_decode_cache(cfg: TransformerConfig, batch_size: int):
    """A clean decoder KV cache for incremental T5 decoding; buffer
    length = ``cfg.decode_cache_len or cfg.max_len`` (see
    ``transformer.clean_cache`` for the dirty-init-cache discipline)."""
    from tfk8s_tpu.models.transformer import clean_cache

    return clean_cache(
        T5(cfg, decode_mode=True),
        jnp.zeros((batch_size, 1), jnp.int32),
        jnp.zeros((batch_size, 1), jnp.int32),
    )


def _validate_decode_cfg(cfg: TransformerConfig, num_tokens: int, verb: str):
    import dataclasses as _dc

    if num_tokens < 1:
        raise ValueError(f"{verb} needs num_tokens >= 1")
    if num_tokens > cfg.max_len:
        raise ValueError(
            f"num_tokens {num_tokens} exceeds max_len={cfg.max_len}"
        )
    if cfg.decode_cache_len is not None and cfg.decode_cache_len < num_tokens:
        raise ValueError(
            f"decode_cache_len={cfg.decode_cache_len} < {num_tokens}"
        )
    if cfg.decode_cache_len is None:
        cfg = _dc.replace(cfg, decode_cache_len=num_tokens)
    return cfg


def generate(
    cfg: TransformerConfig,
    params,
    src: jax.Array,  # [b, src_len] int32
    num_tokens: int,
    rng: Optional[jax.Array] = None,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_id: Optional[int] = None,
    pad_id: int = PAD_ID,
) -> jax.Array:
    """Seq2seq decoding — greedy or sampled, serving parity with the
    causal-LM family (``gpt.generate``): ONE full encoder pass, then a
    jitted ``lax.scan`` of single-token decoder steps with the
    self-attention KV cache (cross-attention re-reads the encoder output
    each step — see DecoderLayer). Starts from BOS and returns the
    ``[b, num_tokens]`` decoded target, cache buffers right-sized to the
    request (``decode_cache_len``).

    ``rng=None`` (or ``temperature=0``) is greedy argmax. Otherwise
    tokens draw from ``softmax(gpt.filter_logits(logits / temperature,
    top_k, top_p))`` — the SAME filter the GPT family serves with, so
    top-k/top-p semantics cannot drift between the families — with a key
    folded from ``rng`` by step index. ``eos_id`` gives stop-token
    semantics: after a row emits EOS its remaining positions are
    ``pad_id`` (the enc-dec scan has no early exit — T5 target lengths
    cluster tightly, so the while-loop machinery isn't worth its cost
    here)."""
    from tfk8s_tpu.models.gpt import filter_logits

    b, _src_len = src.shape
    cfg = _validate_decode_cfg(cfg, num_tokens, "generate")
    model = T5(cfg, decode_mode=True)
    enc, enc_mask = model.apply({"params": params}, src, method=T5.encode)
    cache = init_decode_cache(cfg, b)
    bos = jnp.full((b,), BOS_ID, src.dtype)
    greedy = rng is None or temperature == 0.0

    def pick(logits, i):
        lf = logits.astype(jnp.float32)
        if greedy:
            return jnp.argmax(lf, axis=-1)
        lf = filter_logits(lf / max(temperature, 1e-6), top_k, top_p)
        return jax.random.categorical(jax.random.fold_in(rng, i), lf, axis=-1)

    def step(carry, i):
        cache, tok, done = carry
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            tok[:, None], enc, enc_mask,
            pos_offset=i,
            method=T5.decode,
            mutable=["cache"],
        )
        nxt = pick(logits[:, 0], i).astype(src.dtype)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.asarray(pad_id, src.dtype), nxt)
            done = jnp.logical_or(done, nxt == eos_id)
        return (mut["cache"], nxt, done), nxt

    (_, _, _), outs = jax.lax.scan(
        step, (cache, bos, jnp.zeros((b,), bool)), jnp.arange(num_tokens)
    )
    return jnp.swapaxes(outs, 0, 1)


def greedy_generate(
    cfg: TransformerConfig,
    params,
    src: jax.Array,  # [b, src_len] int32
    num_tokens: int,
) -> jax.Array:
    """Greedy decoding — ``generate`` with no rng (kept as the
    stable name the serving surface documented first)."""
    return generate(cfg, params, src, num_tokens)


def beam_generate(
    cfg: TransformerConfig,
    params,
    src: jax.Array,  # [b, src_len] int32
    num_tokens: int,
    num_beams: int = 4,
    return_all: bool = False,
):
    """Beam-search seq2seq decoding with the KV cache, fully jittable —
    the enc-dec counterpart of ``gpt.beam_generate`` (same bookkeeping:
    per-step top-k over cumulative log-probs, cache re-gathered by
    parent beam with ``jnp.take`` so reordering stays on device). The
    encoder runs ONCE at batch ``b``; encoder output and mask are tiled
    to ``b*num_beams`` rows alongside the cache. Fixed-length sequences
    (no EOS short-circuit), ``num_beams=1`` reproduces greedy exactly.
    Returns the best continuation ``[b, num_tokens]``, or with
    ``return_all`` the tuple ``(sequences [b, k, num_tokens], scores
    [b, k])`` sorted best-first."""
    b, _src_len = src.shape
    k, V = num_beams, cfg.vocab_size
    if not 1 <= k <= V:
        # fail with the knob's NAME, not a downstream top_k shape error
        raise ValueError(
            f"num_beams must be in [1, vocab_size={V}], got {num_beams}"
        )
    cfg = _validate_decode_cfg(cfg, num_tokens, "beam search")
    model = T5(cfg, decode_mode=True)
    enc, enc_mask = model.apply({"params": params}, src, method=T5.encode)

    # first step at batch b from BOS: top-k first tokens seed the beams
    cache = init_decode_cache(cfg, b)
    logits0, mut = model.apply(
        {"params": params, "cache": cache},
        jnp.full((b, 1), BOS_ID, src.dtype), enc, enc_mask,
        pos_offset=jnp.zeros((), jnp.int32),
        method=T5.decode,
        mutable=["cache"],
    )
    logp0 = jax.nn.log_softmax(logits0[:, 0].astype(jnp.float32), axis=-1)
    scores, tok0 = jax.lax.top_k(logp0, k)  # [b, k] each

    tile = lambda x: (
        jnp.repeat(x, k, axis=0) if getattr(x, "ndim", 0) >= 2 else x
    )
    cache = jax.tree_util.tree_map(tile, mut["cache"])  # [b*k, ...] rows
    enc_t, mask_t = tile(enc), tile(enc_mask)
    seqs = jnp.zeros((b * k, num_tokens), src.dtype)
    seqs = seqs.at[:, 0].set(tok0.reshape(b * k).astype(src.dtype))
    row_base = jnp.arange(b)[:, None] * k  # [b, 1]

    def step(carry, i):
        # generates token i+1 given token i (column i of seqs)
        cache, scores, seqs = carry
        tok = seqs[:, i].astype(src.dtype)
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            tok[:, None], enc_t, mask_t,
            pos_offset=i + 1,
            method=T5.decode,
            mutable=["cache"],
        )
        logp = jax.nn.log_softmax(logits[:, 0].astype(jnp.float32), axis=-1)
        cand = (scores.reshape(b * k)[:, None] + logp).reshape(b, k * V)
        new_scores, flat = jax.lax.top_k(cand, k)  # [b, k]
        parent = (row_base + flat // V).reshape(b * k)  # absolute rows
        new_tok = (flat % V).reshape(b * k).astype(src.dtype)
        gather = lambda x: (
            jnp.take(x, parent, axis=0) if getattr(x, "ndim", 0) >= 2 else x
        )
        cache = jax.tree_util.tree_map(gather, mut["cache"])
        seqs = jnp.take(seqs, parent, axis=0).at[:, i + 1].set(new_tok)
        return (cache, new_scores, seqs), ()

    (cache, scores, seqs), _ = jax.lax.scan(
        step, (cache, scores, seqs), jnp.arange(num_tokens - 1)
    )
    seqs = seqs.reshape(b, k, num_tokens)
    if return_all:
        return seqs, scores  # top_k keeps beams sorted best-first
    return seqs[:, 0]


def task_for_mesh(
    mesh,
    cfg: Optional[TransformerConfig] = None,
    **task_kw,
) -> TrainTask:
    """Pick the attention impl for the mesh/config via the shared
    ``transformer.select_attn_fn`` policy. T5's enc-dec attention carries
    [batch, lk] key-padding masks throughout, and EVERY branch of the
    shared policy is now mask-capable — including the ring kernel, which
    rotates the mask block with k/v (parallel/ring_attention.py) — so T5
    long-context rides Ulysses while the sequence degree divides the
    per-device head count and ring attention beyond it, like the other
    families."""
    from tfk8s_tpu.models.transformer import select_attn_fn

    cfg = cfg or base_config()
    seq_len = min(task_kw.get("seq_len", 128), cfg.max_len)
    attn_fn = select_attn_fn(mesh, cfg, seq_len)
    return make_task(cfg=cfg, attn_fn=attn_fn, **task_kw)


def train(env: Dict[str, str], stop: Optional[Any] = None) -> None:
    """TPUJob entrypoint: ``tfk8s_tpu.models.t5:train``. MoE (EP) in the
    encoder is job-configurable via ``TFK8S_NUM_EXPERTS``."""
    env = dict(env)
    env.setdefault("TFK8S_TRAIN_STEPS", "100")
    env.setdefault("TFK8S_LEARNING_RATE", "1e-4")
    seq = int(env.get("TFK8S_SEQ_LEN", "128"))
    batch = int(env.get("TFK8S_BATCH_SIZE", "32"))
    preset = tiny_config if env.get("TFK8S_MODEL_PRESET") == "tiny" else base_config
    cfg = preset(
        num_experts=int(env.get("TFK8S_NUM_EXPERTS", "0")),
        moe_top_k=int(env.get("TFK8S_MOE_TOP_K", "1")),
        attention_impl=env.get("TFK8S_ATTENTION_IMPL", "auto"),
    )
    from tfk8s_tpu.runtime.launcher import ProcessContext, build_mesh, initialize_distributed

    ctx = ProcessContext.from_env(env)
    initialize_distributed(ctx, env)
    mesh = build_mesh(ctx)
    task = task_for_mesh(mesh, cfg=cfg, seq_len=seq, batch_size=batch)
    run_task(task, env, stop, mesh=mesh)
