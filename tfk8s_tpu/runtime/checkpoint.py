"""Checkpoint/resume — the contract gang restart depends on.

The reference names storage as a capability with nothing behind it
(k8s-operator.md:2; SURVEY.md §5 'Checkpoint / resume: ABSENT') —
checkpointing was the training script's problem. Here it is a framework
subsystem because TPU failure semantics demand it: a slice fails as a unit,
the controller restarts the whole gang (trainer/tpujob_controller.py), and
the restarted processes restore the last step instead of step 0.

Orbax is the engine; this wraps it with a small, dependency-tolerant
surface (save-every-N, latest-step discovery, sharding-aware restore).

Paths may be scheme'd URIs (SURVEY.md §5 plans "orbax-style async
checkpoint to GCS" — the GKE deployment has nowhere durable to write
otherwise): ``gs://bucket/path`` and ``file:///...`` pass through to
orbax/tensorstore UNTOUCHED — no ``abspath``/``makedirs`` mangling (the
r3 gap: ``os.path.abspath("gs://b/p")`` destroyed the URI before orbax
ever saw it). For hermetic tests and air-gapped dev, setting
``TFK8S_GCS_FAKE_ROOT=/some/dir`` maps ``gs://bucket/path`` →
``<root>/bucket/path`` — an explicit local fake of the object store, so
the gang-resume contract is testable with gs://-shaped specs and the
exact same URIs work unmapped against real GCS.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional

import jax

from tfk8s_tpu.utils.logging import get_logger

log = get_logger("checkpoint")

try:  # orbax is baked into the image; tolerate its absence anyway
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except Exception:  # noqa: BLE001
    _HAVE_ORBAX = False

# RFC 3986 scheme — distinguishes URIs (gs://, file://, s3://...) from
# plain paths, which keep the historical abspath normalization.
_URI_RE = re.compile(r"^[a-z][a-z0-9+.\-]*://")

# Commit-marker registry (atomic-commit discovery): a step only counts as
# restorable once its marker file exists under ``<dir>/.tfk8s_commits/``,
# and the marker is written strictly AFTER the save durably finished — so
# a kill mid-write (preemption landing inside the drain checkpoint) can
# never corrupt latest-step discovery: the partial step dir simply has no
# marker and restore falls back to the previous committed step. Local
# directories only (the fake-GCS root included); true remote URIs keep
# orbax/tensorstore's own atomicity and discovery.
_COMMITS_DIRNAME = ".tfk8s_commits"


def resolve_directory(directory: str) -> str:
    """Normalize a checkpoint location. Plain paths → absolute; URIs pass
    through untouched, except ``gs://`` when ``TFK8S_GCS_FAKE_ROOT`` maps
    it onto the local fake object store (module docstring)."""
    if not _URI_RE.match(directory):
        return os.path.abspath(directory)
    if directory.startswith("gs://"):
        fake_root = os.environ.get("TFK8S_GCS_FAKE_ROOT", "")
        if fake_root:
            return os.path.join(os.path.abspath(fake_root), directory[len("gs://"):])
    return directory


class Checkpointer:
    """Save/restore a pytree train state under ``directory/step_N``."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = resolve_directory(directory) if directory else directory
        self.max_to_keep = max_to_keep
        self._mgr = None
        # steps whose orbax save was STARTED but whose commit marker is
        # not yet written (the async window); committed once the save is
        # known durable (wait_until_finished / the next save's barrier)
        self._pending: list = []
        self._commit_dir = (
            os.path.join(self.directory, _COMMITS_DIRNAME)
            if self.directory and not _URI_RE.match(self.directory)
            else None
        )
        if _HAVE_ORBAX and directory:
            if not _URI_RE.match(self.directory):
                os.makedirs(self.directory, exist_ok=True)
            # URIs: orbax (CheckpointManagerOptions.create) + tensorstore
            # own creation semantics on the remote store.
            self._mgr = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep, create=True
                ),
            )

    @property
    def enabled(self) -> bool:
        return self._mgr is not None

    # -- commit markers -----------------------------------------------------

    def _write_marker(self, step: int) -> None:
        with open(os.path.join(self._commit_dir, str(int(step))), "w") as f:
            f.write("committed\n")

    def _commit_pending(self) -> None:
        """Write markers for every pending step, then prune markers whose
        step dir orbax retention has deleted (the registry must not grow
        one file per step forever). ONLY call once the saves are known
        durable (after ``wait_until_finished``)."""
        if self._commit_dir is None:
            self._pending.clear()
            return
        if self._pending:
            os.makedirs(self._commit_dir, exist_ok=True)
        for step in self._pending:
            self._write_marker(step)
        self._pending.clear()
        try:
            retained = set(self._mgr.all_steps())
            for n in os.listdir(self._commit_dir):
                if n.isdigit() and int(n) not in retained:
                    os.remove(os.path.join(self._commit_dir, n))
        except OSError:
            pass  # pruning is housekeeping; stale markers are harmless

    def _committed_only(self, steps: list) -> list:
        """Filter a step listing down to COMMITTED steps. A directory with
        no marker registry at all (written by raw orbax, or pre-marker
        code) is trusted as-is — strict gating applies once this class
        has ever committed here."""
        if self._commit_dir is None or not os.path.isdir(self._commit_dir):
            return list(steps)
        try:
            marked = {
                int(n) for n in os.listdir(self._commit_dir) if n.isdigit()
            }
        except OSError:
            return list(steps)
        return [s for s in steps if s in marked]

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        self.save_async(step, state)
        if wait:
            self.wait_until_finished()

    def save_async(self, step: int, state: Any) -> None:
        """Start an async save and return while it drains on orbax's
        background thread — the drain path's checkpoint (training has
        already stopped; the overlap buys the reclaim deadline). The
        step's commit marker is written only once the save is known
        durable, so a kill mid-save leaves a partial dir that
        latest-step discovery skips."""
        if not self.enabled:
            return
        if self._pending:
            # orbax serializes async saves anyway; making the barrier
            # explicit lets the PREVIOUS step commit before this one opens
            # its own vulnerability window
            self._mgr.wait_until_finished()
            self._commit_pending()
        if self._commit_dir is not None and not os.path.isdir(self._commit_dir):
            # FIRST save into this directory: activate the strict gate
            # before the step dir starts materializing, grandfathering any
            # pre-marker (raw-orbax/legacy) steps — otherwise a kill mid-
            # first-save leaves a partial dir that a fresh registry-less
            # directory would TRUST instead of skip
            os.makedirs(self._commit_dir, exist_ok=True)
            for s in self._mgr.all_steps():
                self._write_marker(s)
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        self._pending.append(int(step))
        log.info("saving checkpoint step=%d -> %s", step, self.directory)

    def saving_in_progress(self) -> bool:
        """True while an async save is still draining on orbax's background
        thread — ``save(wait=False)`` returns immediately and training
        overlaps the persistence; callers needing durability barrier on
        :meth:`wait_until_finished`."""
        if not self.enabled:
            return False
        fn = getattr(self._mgr, "is_saving_in_progress", None)
        return bool(fn()) if fn is not None else False

    def wait_until_finished(self) -> None:
        if self.enabled:
            self._mgr.wait_until_finished()
            self._commit_pending()

    def maybe_commit(self) -> None:
        """Commit pending markers iff the async save has finished draining
        — never blocks. Cheap enough for the step loop: without it a
        periodic ``save(wait=False)`` stays uncommitted until the NEXT
        save's barrier, so a cold kill inside the following window would
        discard a fully durable checkpoint and double the replay."""
        if self.enabled and self._pending and not self.saving_in_progress():
            self._commit_pending()

    def all_steps(self) -> list:
        """Every retained COMMITTED checkpoint step, ascending (cadence
        assertions and retention inspection)."""
        if not self.enabled:
            return []
        if hasattr(self._mgr, "reload"):
            self._mgr.reload()
        return sorted(self._committed_only(self._mgr.all_steps()))

    def latest_step(self) -> Optional[int]:
        if not self.enabled:
            return None
        # CheckpointManager caches its step listing at construction; an
        # evaluator polling for checkpoints written by ANOTHER process
        # (runtime.train.run_eval) needs a re-read to ever see them.
        if hasattr(self._mgr, "reload"):
            self._mgr.reload()
        elif not getattr(self, "_warned_no_reload", False):
            self._warned_no_reload = True
            log.warning(
                "orbax CheckpointManager has no reload(); cross-process "
                "pollers will only see checkpoints that existed at open time"
            )
        # commit-marker gate: a partial step dir left by a kill mid-save
        # (or a save still in its async window) must never be the resume
        # point — discovery returns the newest COMMITTED step
        steps = self._committed_only(self._mgr.all_steps())
        return max(steps) if steps else None

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        """Restore into the shape/sharding of ``state_like`` (an abstract or
        concrete example tree). Returns the restored tree."""
        if not self.enabled:
            raise RuntimeError("checkpointing is disabled (no directory/orbax)")
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape")
            else x,
            state_like,
        )
        restored = self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))
        log.info("restored checkpoint step=%d from %s", step, self.directory)
        return restored

    def close(self) -> None:
        if self._mgr is not None:
            self._mgr.wait_until_finished()
            self._commit_pending()
            self._mgr.close()
