"""Host-side page accounting for the block-paged KV cache.

The device half (models/gpt.decode_step_packed / prefill_into_slots) is a pure
function over a preallocated page pool and per-slot page tables; THIS
module owns which page holds what:

- :class:`PageAllocator` hands out fixed-size pages from the pool,
  reserves a request's worst-case page budget at admission (so a live
  request can always grow its page table mid-generation — out-of-pages
  can stall ADMISSION, never corrupt a row that already started), and
  recycles pages when requests retire.
- The **prefix cache**: page-aligned prompt prefixes are content-hashed
  per page (a digest CHAIN, so a page's identity includes everything
  before it) and kept after release. A new request whose prompt starts
  with a cached chain reuses those pages copy-on-write: shared pages are
  never written again — a reused prefix always ends on a page boundary
  and the remainder (at least the prompt's final token, which must be
  re-run to produce the first output logits) lands in freshly allocated
  pages, so divergence allocates instead of mutating. Idle cached pages
  are reclaimed LRU-first when the free list runs dry.

Page 0 is the TRASH page: never allocated, the write target of inactive
decode slots (zero-filled page tables). Everything here is plain Python
under the executor's lock — no jax, unit-testable in microseconds
(tests/test_paged_kv.py).
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

#: reserved write target for inactive slots; never handed out
TRASH_PAGE = 0


def prefix_digest_chain(tokens: Sequence[int], page_size: int,
                        upto: int) -> List[str]:
    """Chained content digests for the first ``upto`` full pages of
    ``tokens``: page ``k``'s digest folds in every page before it, so a
    digest identifies a whole page-aligned PREFIX, not one page in
    isolation. Module-level because three layers key off the same chain:
    the prefix cache (here), KV handoff integrity (runtime/handoff.py),
    and prefix-affinity routing (gateway/affinity.py) — the gateway must
    hash a prompt exactly the way the replica's cache will."""
    digests, h = [], b""
    for k in range(upto):
        page = [int(t) for t in tokens[k * page_size:(k + 1) * page_size]]
        h = hashlib.sha256(h + repr(page).encode()).digest()
        digests.append(h.hex())
    return digests


class OutOfPages(Exception):
    """The pool cannot cover a new request's worst-case page budget.
    Admission-time only: the caller keeps the request queued and retries
    after retirements free pages."""


@dataclass
class RestoreTicket:
    """In-flight KV-tier restore (:meth:`PageAllocator.restore_begin`):
    drawn-but-unpublished pages plus the ref-pinned resident head of the
    chain being restored. Must be resolved by ``restore_commit`` or
    ``restore_abort`` before the admission pass continues."""

    digests: List[str]
    start: int
    pages: List[int]
    pinned: List[int]


@dataclass
class SlotLease:
    """One admitted request's page holdings: ``pages`` in table order
    (cached prefix first), plus the unallocated remainder of its
    reserved budget."""

    pages: List[int] = field(default_factory=list)
    #: leading entries of ``pages`` reused from the prefix cache —
    #: shared, read-only; the executor never writes positions below
    #: ``cached_pages * page_size``
    cached_pages: int = 0
    #: pages this lease may still draw on demand (reserved at admission)
    reserved: int = 0


class PageAllocator:
    """Fixed pool of ``num_pages`` pages of ``page_size`` tokens.

    Contract (tests/test_paged_kv.py):

    - :meth:`admit` either returns a lease whose reservation covers the
      request's WORST-CASE length (prompt + generation budget) or raises
      :class:`OutOfPages` — a live lease's :meth:`extend` therefore
      always succeeds;
    - pages released by a retiring lease are reusable immediately;
      cache-registered pages stay resident (evictable LRU) so later
      requests with the same prompt prefix skip their prefill;
    - a cached page is shared by refcount and never freed while any
      lease holds it.
    """

    def __init__(self, num_pages: int, page_size: int,
                 prefix_cache: bool = True):
        if num_pages < 2:
            raise ValueError(
                f"need >= 2 pages (trash + 1 usable), got {num_pages}"
            )
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = num_pages
        self.page_size = page_size
        self.prefix_cache_enabled = prefix_cache
        self._free: deque = deque(range(1, num_pages))
        self._ref: Dict[int, int] = {}
        self._reserved_total = 0
        # digest-chain key -> page id, LRU order (oldest first); a cached
        # page with refcount 0 is idle storage, evictable on demand
        self._cache: "OrderedDict[str, int]" = OrderedDict()
        self._page_key: Dict[int, str] = {}
        # fault containment (ISSUE 13): pages held by a FAULTED row are
        # never returned to the free list until verified — a poisoned
        # page must not carry corrupt K/V into a future admission.
        # _quarantined = unreferenced pages awaiting verification;
        # _tainted = poisoned pages still shared with a live lease
        # (diverted into _quarantined at their final release)
        self._quarantined: set = set()
        self._tainted: set = set()
        self.prefix_hits = 0
        self.prefix_misses = 0
        #: idle cached pages reclaimed by :meth:`_evict_idle` (the device
        #: tier's eviction accounting — ISSUE 17 bugfix: before the KV
        #: economy these drops were invisible)
        self.evictions = 0
        #: observer invoked BEFORE an idle cached page is dropped, with
        #: ``(digest_key, page_id)`` — the page is still cache-resident
        #: during the call so the host tier (runtime/kvtier) can demote
        #: the whole chain it belongs to. The callback MUST NOT mutate
        #: this allocator (it runs mid-eviction); reads are fine.
        self.on_evict: Optional[Callable[[str, int], None]] = None

    # -- accounting ---------------------------------------------------------

    @property
    def free_pages(self) -> int:
        """Pages immediately on the free list (excludes evictable cache)."""
        return len(self._free)

    @property
    def used_pages(self) -> int:
        """Pages held by leases OR idle in the prefix cache."""
        return self.num_pages - 1 - len(self._free)

    def available(self) -> int:
        """Pages a NEW admission may still claim: free + evictable cached
        idle pages, minus what live leases have reserved but not drawn."""
        idle = sum(1 for p in self._cache.values() if not self._ref.get(p))
        return len(self._free) + idle - self._reserved_total

    # -- prefix cache -------------------------------------------------------

    def _page_digests(self, tokens: Sequence[int], upto: int) -> List[str]:
        """Chained content digests for the first ``upto`` full pages.
        Tokens are normalized to plain ints so a numpy prompt and a list
        prompt with the same content hash identically."""
        return prefix_digest_chain(tokens, self.page_size, upto)

    def match_prefix(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Longest cached page chain covering a PROPER prefix of
        ``tokens`` (at most ``len(tokens) - 1`` — the final prompt token
        is always re-run so the request has first-output logits).
        Returns ``(page_ids, tokens_covered)`` WITHOUT acquiring them —
        :meth:`admit` does the refcounting."""
        if not self.prefix_cache_enabled:
            return [], 0
        k_max = max(len(tokens) - 1, 0) // self.page_size
        matched: List[int] = []
        for key in self._page_digests(tokens, k_max):
            pid = self._cache.get(key)
            if pid is None:
                break
            matched.append(pid)
        return matched, len(matched) * self.page_size

    def cached_chain(self, digests: Sequence[str]) -> List[int]:
        """Resident page ids for the longest cached prefix of a digest
        chain, WITHOUT acquiring them. The KV-tier read path: demotion
        walks it to find what is still exportable, restore walks it to
        find where the device tier ends."""
        pages: List[int] = []
        for key in digests:
            pid = self._cache.get(key)
            if pid is None:
                break
            pages.append(pid)
        return pages

    def cached_keys(self, limit: int = 0) -> List[str]:
        """Digest keys currently in the prefix cache, LRU-oldest first
        (the gateway cache directory's per-replica report; ``limit`` > 0
        keeps only the most-recent tail)."""
        keys = list(self._cache.keys())
        return keys[-limit:] if limit > 0 else keys

    def restore_begin(self, digests: Sequence[str],
                      start: int) -> Optional["RestoreTicket"]:
        """Phase 1 of adopting externally sourced prefix pages (host/peer
        tier restore, runtime/kvtier): draw one page per chain position
        ``start..len(digests)-1``, WITHOUT publishing them. The caller
        scatters the restored K/V into ``ticket.pages``, then
        :meth:`restore_commit` publishes them under their digests (or
        :meth:`restore_abort` returns them untouched). The two-phase
        shape is load-bearing: a dry free list evicts idle cached pages
        through the normal :meth:`_evict_idle` path — whose demotion
        callback may EXPORT any published chain — so pages holding
        not-yet-scattered garbage must stay invisible to the cache until
        their bytes are real. The chain's resident head is ref-pinned
        for the ticket's lifetime so the eviction scan cannot break the
        chain being restored; drawn pages need no pin (eviction only
        sees the cache). Returns ``None`` — side-effect-free — when live
        leases own the whole pool.

        Accounting-neutral once committed: every drawn page becomes an
        idle cached (evictable) page, so :meth:`available` and the
        infallible-:meth:`extend` contract hold. A restore SHUFFLES
        residency (displaced chains demote to host first); it never
        destroys it."""
        need = len(digests) - start
        if need <= 0:
            return None
        pinned: List[int] = []
        for key in digests[:start]:
            pid = self._cache.get(key)
            if pid is not None:
                # touch the head: the whole chain ends up contiguous at
                # the MRU end, aging (and demoting) as one unit
                self._cache.move_to_end(key)
                self._ref[pid] = self._ref.get(pid, 0) + 1
                pinned.append(pid)
        pages: List[int] = []
        while len(pages) < need:
            if not self._free:
                if not any(
                    not self._ref.get(p) for p in self._cache.values()
                ):
                    self._free.extend(pages)
                    self._unpin(pinned)
                    return None
                self._evict_idle()
            pages.append(self._free.popleft())
        return RestoreTicket(
            digests=list(digests), start=start, pages=pages, pinned=pinned
        )

    def restore_commit(self, ticket: "RestoreTicket") -> None:
        """Phase 2: the K/V landed — publish the drawn pages under their
        digests (idle cached, exactly as if a request had prefilled and
        released them) and unpin the head."""
        for key, pid in zip(ticket.digests[ticket.start:], ticket.pages):
            self._cache[key] = pid
            self._page_key[pid] = key
        self._unpin(ticket.pinned)
        ticket.pages = []
        ticket.pinned = []

    def restore_abort(self, ticket: "RestoreTicket") -> None:
        """The scatter failed: return the drawn pages to the free list
        unpublished and unpin the head. No trace remains."""
        self._free.extend(ticket.pages)
        self._unpin(ticket.pinned)
        ticket.pages = []
        ticket.pinned = []

    def _unpin(self, pinned: List[int]) -> None:
        for pid in pinned:
            n = self._ref.get(pid, 0) - 1
            if n > 0:
                self._ref[pid] = n
            else:
                self._ref.pop(pid, None)

    def discard_cached(self, keys: Sequence[str]) -> None:
        """Unpublish idle cache entries (a failed restore rolls back the
        pages it drew; pages shared with a live lease just lose their
        cache identity and free at final release)."""
        for key in keys:
            pid = self._cache.pop(key, None)
            if pid is None:
                continue
            self._page_key.pop(pid, None)
            if not self._ref.get(pid):
                self._free.append(pid)

    def register_prefix(self, tokens: Sequence[int], lease: SlotLease) -> None:
        """Publish the lease's full-page prompt prefixes into the cache
        (called once the prompt's K/V are actually resident — after
        prefill). First writer wins: a concurrent identical prompt that
        registered first keeps its pages; ours simply stay private."""
        if not self.prefix_cache_enabled:
            return
        k_max = max(len(tokens) - 1, 0) // self.page_size
        for k, key in enumerate(self._page_digests(tokens, k_max)):
            if k >= len(lease.pages):
                break
            pid = lease.pages[k]
            cur = self._cache.get(key)
            if cur is not None:
                if cur == pid:
                    self._cache.move_to_end(key)
                continue
            if pid in self._page_key:  # already published under its key
                continue
            self._cache[key] = pid
            self._page_key[pid] = key

    # -- lease lifecycle ----------------------------------------------------

    def admit(self, tokens: Sequence[int], gen_budget: int) -> SlotLease:
        """Reserve the worst-case page budget for ``tokens`` plus
        ``gen_budget`` generated tokens, reusing a cached prefix when one
        matches. Raises :class:`OutOfPages` without side effects when the
        pool cannot cover it.

        ``need_pages`` below — ``ceil((len + max(gen, 1)) / page_size)``
        — is the ONE footprint formula in the system: the attention
        gather's per-row extent is ``pages_per_slot() = ceil(max_len /
        page_size)`` of the same shape (the Pallas seam comment in
        models/transformer.py), and the scheduler's preemption spill
        (runtime/server._spill_locked) re-admits a spilled row through
        this exact method with its REMAINING budget, so spill/restore can
        never free fewer pages than a fresh admission would need."""
        need_pages = -(-(len(tokens) + max(gen_budget, 1)) // self.page_size)
        cached, cached_tokens = self.match_prefix(tokens)
        need_new = need_pages - len(cached)
        # IDLE cached pages this admission is about to acquire stop being
        # evictable the moment it refs them — charge them against
        # available() too, or a prefix-hit admission could over-commit
        # the pool and a later extend() (contractually infallible) would
        # fail mid-generation and poison every in-flight request
        idle_acquired = sum(1 for pid in cached if not self._ref.get(pid))
        if need_new + idle_acquired > self.available():
            raise OutOfPages(
                f"{need_new} pages needed (+{idle_acquired} idle cached "
                f"acquired), {self.available()} available "
                f"({self.num_pages - 1} pool)"
            )
        if cached:
            self.prefix_hits += 1
            for pid in cached:
                self._ref[pid] = self._ref.get(pid, 0) + 1
                key = self._page_key.get(pid)
                if key is not None:
                    self._cache.move_to_end(key)
        elif self.prefix_cache_enabled:
            self.prefix_misses += 1
        self._reserved_total += need_new
        return SlotLease(
            pages=list(cached), cached_pages=len(cached), reserved=need_new
        )

    def extend(self, lease: SlotLease) -> int:
        """Draw the lease's next page from its admission-time reservation
        (the page table grows as the generation crosses page boundaries).
        Always succeeds for a lease admitted by :meth:`admit`."""
        if lease.reserved <= 0:
            raise OutOfPages("lease reservation exhausted — admission bug")
        if not self._free:
            self._evict_idle()
        pid = self._free.popleft()
        lease.reserved -= 1
        self._reserved_total -= 1
        self._ref[pid] = 1
        lease.pages.append(pid)
        return pid

    def release(self, lease: SlotLease) -> None:
        """Retire a lease: drop every page reference and return the
        unused reservation. Unreferenced pages return to the free list
        unless the prefix cache holds them (those stay resident, LRU-
        evictable, so the next same-prefix request hits)."""
        self._reserved_total -= lease.reserved
        lease.reserved = 0
        for pid in lease.pages:
            n = self._ref.get(pid, 0) - 1
            if n > 0:
                self._ref[pid] = n
                continue
            self._ref.pop(pid, None)
            if pid in self._tainted:
                self._tainted.discard(pid)
                self._quarantined.add(pid)
                continue
            if pid not in self._page_key:
                self._free.append(pid)
        lease.pages = []
        lease.cached_pages = 0

    # -- KV handoff (disaggregated prefill/decode) --------------------------

    def export_pages(
        self, lease: SlotLease, tokens: Sequence[int]
    ) -> Tuple[List[int], List[str]]:
        """The prefill side of a KV handoff: the lease's page ids covering
        the PROMPT (in table order — what runtime/handoff.py serializes
        together with the digest chain into a self-describing buffer) plus
        the chained digests of the full prompt pages, which double as the
        buffer's integrity check and the gateway's affinity key. The
        trailing partial page (if the prompt isn't page-aligned) is
        exported too — its live rows are prompt K/V; its tail rows are
        junk the importer's decode never reads (attention is masked to
        positions <= the current one, exactly as on this replica)."""
        ps = self.page_size
        n_prompt = -(-len(tokens) // ps)
        assert len(lease.pages) >= n_prompt, (
            f"lease holds {len(lease.pages)} page(s), prompt needs "
            f"{n_prompt} — export before prefill drew the lease"
        )
        digests = prefix_digest_chain(tokens, ps, len(tokens) // ps)
        return list(lease.pages[:n_prompt]), digests

    def import_pages(self, tokens: Sequence[int], gen_budget: int) -> SlotLease:
        """The decode side of a KV handoff: admit the row exactly like
        :meth:`admit` (worst-case reservation, prefix-cache reuse — a
        repeated session history that is already cached locally is NOT
        re-copied), then draw the remaining prompt pages immediately so
        the imported K/V has somewhere to land BEFORE the row's first
        decode step. The caller copies buffer pages
        ``[lease.cached_pages, ceil(len(tokens)/page_size))`` into
        ``lease.pages[cached_pages:]``. Raises :class:`OutOfPages`
        without side effects when the pool cannot cover the row."""
        lease = self.admit(tokens, gen_budget)
        n_prompt = -(-len(tokens) // self.page_size)
        while len(lease.pages) < n_prompt:
            self.extend(lease)
        return lease

    # -- fault quarantine ---------------------------------------------------

    @property
    def quarantined_pages(self) -> int:
        """Pages held out of circulation pending verification (includes
        tainted pages still pinned by a live lease)."""
        return len(self._quarantined) + len(self._tainted)

    def quarantine(self, lease: SlotLease) -> int:
        """Retire a FAULTED lease: return its unused reservation, but
        hold every page it touched OUT of the free list (and unpublish
        them from the prefix cache) until :meth:`verify_quarantined`
        clears them. A page still shared with another live lease stays
        readable for that lease (its content predates the fault) but is
        tainted — it quarantines at its final release instead of going
        free. Returns the number of pages quarantined or tainted."""
        self._reserved_total -= lease.reserved
        lease.reserved = 0
        n_held = 0
        for pid in lease.pages:
            key = self._page_key.pop(pid, None)
            if key is not None:
                self._cache.pop(key, None)
            n = self._ref.get(pid, 0) - 1
            if n > 0:
                self._ref[pid] = n
                if pid not in self._tainted:
                    self._tainted.add(pid)
                    n_held += 1
                continue
            self._ref.pop(pid, None)
            if pid not in self._quarantined:
                self._quarantined.add(pid)
                n_held += 1
        lease.pages = []
        lease.cached_pages = 0
        return n_held

    def verify_quarantined(self) -> int:
        """Release verified quarantined pages back to the free list (the
        pool's K/V pages are fully overwritten by prefill before any row
        reads them, so verification is an explicit operator/executor
        decision, never implicit). Tainted pages still pinned by live
        leases stay tainted. Returns the number of pages returned."""
        n = len(self._quarantined)
        while self._quarantined:
            self._free.append(self._quarantined.pop())
        return n

    def _evict_idle(self) -> None:
        """Reclaim the LRU idle cached page into the free list. Called
        only when the free list is dry but ``available()`` promised
        capacity, so an idle page must exist."""
        for key, pid in self._cache.items():
            if not self._ref.get(pid):
                if self.on_evict is not None:
                    # page still resident: the host tier can export the
                    # chain this digest belongs to before it disappears
                    self.on_evict(key, pid)
                del self._cache[key]
                del self._page_key[pid]
                self._free.append(pid)
                self.evictions += 1
                return
        raise OutOfPages("no idle cached page to evict — accounting bug")


__all__ = [
    "OutOfPages",
    "PageAllocator",
    "RestoreTicket",
    "SlotLease",
    "TRASH_PAGE",
    "prefix_digest_chain",
]
