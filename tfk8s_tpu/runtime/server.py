"""In-process model server: the data plane of the TPUServe subsystem.

The kubelet launches this like any trainer entrypoint (:func:`serve`),
closing the gap the ROADMAP names — ``models/transformer.py`` ships
``clean_cache``/``prefill_cache`` incremental-decode machinery that no
runtime exercised. The server:

1. loads the checkpoint named by the spec (``seed:<n>`` initializes
   deterministic params hermetically; a path/URI restores a real
   checkpoint), THEN reports Ready — the controller's readiness gate;
2. runs a **dynamic micro-batching executor** (:class:`ModelServer`):
   requests land in a bounded queue; the batcher closes a batch at
   ``max_batch_size`` or ``batch_timeout`` — whichever first (Clipper-
   style adaptive batching); requests are grouped by a model-defined
   **bucket key** so incompatible shapes are never padded together; one
   jitted forward serves the whole batch (KV-cache ``gpt.generate`` for
   generative tasks, a plain padded forward for classifiers); responses
   fan back with per-request queue/execute/total latency histograms;
3. sheds load: past ``queue_limit`` a submit raises the typed
   :class:`Overloaded` (the 429 equivalent) instead of queuing
   unboundedly;
4. reports load (queue depth, windowed QPS, mean batch occupancy) through
   ``runtime/progress.py`` → kubelet flush → ``pod.status.training`` —
   the same channel training throughput rides — which is what the
   controller's autoscaler consumes.

Transport: replicas register in an in-process table keyed by pod
(``namespace/pod-name``) and :class:`ServeClient` dispatches into them
after discovering Ready replicas through the apiserver — the hermetic
analogue of a Service endpoint list. This is the seam where an HTTP/gRPC
front end would slot in for a multi-host deployment; the batching
executor, readiness gate, and drain protocol are transport-independent.

Drain protocol (what makes rolling updates lose zero requests): pod
deletion signals the entrypoint's stop event; the server first
UNREGISTERS (new submits see :class:`Draining` and the client retries on
another replica), then finishes every queued request before the thread
exits.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from tfk8s_tpu.runtime import progress as _progress
from tfk8s_tpu.utils.logging import Metrics, get_logger

log = get_logger("serve")


class ServeError(Exception):
    """Base class for serving-path errors."""


class Overloaded(ServeError):
    """Bounded-queue backpressure: the request was shed, not queued — the
    typed 429 equivalent. Carries the observed depth and the limit so a
    client/load-balancer can back off intelligently."""

    def __init__(self, queue_depth: int, queue_limit: int):
        self.queue_depth = queue_depth
        self.queue_limit = queue_limit
        super().__init__(
            f"request queue full ({queue_depth}/{queue_limit}); retry later"
        )


class Draining(ServeError):
    """The replica is shutting down (rolling update / scale-down): it no
    longer ACCEPTS requests but will finish the ones it holds. Clients
    retry on another replica."""


class RequestFailed(ServeError):
    """The model raised while executing the batch this request rode."""


# ---------------------------------------------------------------------------
# Served models
# ---------------------------------------------------------------------------


class ServedModel:
    """One loadable model family. ``bucket_of`` partitions payloads into
    batchable groups (payloads in one bucket MUST be stackable after the
    model's own padding); ``forward`` serves one bucket's batch."""

    #: version string of the loaded weights (the checkpoint ref)
    version: str = ""

    def load(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def bucket_of(self, payload: Any) -> Any:  # pragma: no cover - interface
        raise NotImplementedError

    def forward(self, payloads: List[Any]) -> List[Any]:  # pragma: no cover
        raise NotImplementedError


class EchoModel(ServedModel):
    """Hermetic control-plane test model: no accelerator, no compile.
    Payloads are scalars/arrays; the response echoes ``payload`` plus the
    model version. ``delay_ms`` emulates per-BATCH model latency so
    batching measurably beats sequential dispatch and autoscaler tests
    can build real queue depth."""

    def __init__(self, checkpoint: str = "", delay_ms: float = 0.0):
        self.version = checkpoint or "echo"
        self.delay_ms = delay_ms
        self._loaded = False

    def load(self) -> None:
        self._loaded = True

    def bucket_of(self, payload: Any) -> Any:
        shape = getattr(payload, "shape", None)
        return ("echo", tuple(shape) if shape is not None else type(payload).__name__)

    def forward(self, payloads: List[Any]) -> List[Any]:
        if not self._loaded:
            raise RequestFailed("model not loaded")
        if self.delay_ms:
            time.sleep(self.delay_ms / 1000.0)
        return [{"echo": p, "version": self.version} for p in payloads]


def _params_from_checkpoint(checkpoint: str, init_fn: Callable[[int], Any]) -> Any:
    """Resolve a checkpoint ref to params: ``seed:<n>`` initializes
    deterministically (the hermetic path every test and the bench use);
    anything else restores the latest step from a checkpoint directory
    (runtime/checkpoint.py)."""
    if checkpoint.startswith("seed:"):
        return init_fn(int(checkpoint[len("seed:"):] or "0"))
    from tfk8s_tpu.runtime import checkpoint as ckpt

    mgr = ckpt.CheckpointManager(checkpoint)
    if mgr.latest_step() is None:
        raise ServeError(f"checkpoint {checkpoint!r} has no saved step")
    return mgr.restore({"params": init_fn(0)})["params"]


class MlpClassifier(ServedModel):
    """Classifier serving path: ONE jitted forward over a batch padded to
    ``max_batch_size`` rows (a single compile per feature shape — batch
    occupancy varies per dispatch, the padded shape does not)."""

    def __init__(self, checkpoint: str, max_batch_size: int, hidden: int = 64):
        self.version = checkpoint
        self.max_batch_size = max_batch_size
        self.hidden = hidden
        self._apply = None
        self._params = None

    def load(self) -> None:
        import jax
        import jax.numpy as jnp

        from tfk8s_tpu.models.mlp import IMAGE_DIM, MLP
        from tfk8s_tpu.parallel.sharding import unbox

        model = MLP(hidden=self.hidden)

        def init_fn(seed: int):
            return unbox(
                model.init(jax.random.key(seed), jnp.zeros((1, IMAGE_DIM)))["params"]
            )

        self._params = _params_from_checkpoint(self.version, init_fn)
        self._apply = jax.jit(
            lambda params, x: jnp.argmax(model.apply({"params": params}, x), axis=-1)
        )

    def bucket_of(self, payload: Any) -> Any:
        import numpy as np

        arr = np.asarray(payload)
        if arr.ndim != 1:
            raise TypeError(f"mlp payload must be a 1-D feature vector, got {arr.shape}")
        return ("mlp", arr.shape)

    def forward(self, payloads: List[Any]) -> List[Any]:
        import numpy as np

        x = np.stack([np.asarray(p, dtype=np.float32) for p in payloads])
        n = len(payloads)
        if n < self.max_batch_size:  # pad rows; one compile per feature shape
            x = np.concatenate(
                [x, np.zeros((self.max_batch_size - n, x.shape[1]), np.float32)]
            )
        out = np.asarray(self._apply(self._params, x))
        return [{"label": int(out[i]), "version": self.version} for i in range(n)]


class GptGenerator(ServedModel):
    """Generative serving path: batched-prefill + KV-cache decode
    (``models/gpt.generate`` — the ``prefill_cache``/``clean_cache``
    machinery finally driven by a runtime). Prompts bucket by EXACT
    length: decode mode refuses padding masks by design (padded K/V
    would silently corrupt the cache), so same-length prompts are the
    only safe batch. The batch dim pads to ``max_batch_size`` (row 0
    repeated) so each prompt-length bucket compiles once."""

    def __init__(self, checkpoint: str, max_batch_size: int, gen_tokens: int = 16,
                 tiny: bool = True):
        self.version = checkpoint
        self.max_batch_size = max_batch_size
        self.gen_tokens = gen_tokens
        self.tiny = tiny
        self._params = None
        self._cfg = None
        self._runs: Dict[int, Any] = {}  # prompt_len -> jitted generate

    def load(self) -> None:
        import jax

        from tfk8s_tpu.models import gpt
        from tfk8s_tpu.parallel.sharding import unbox

        self._cfg = gpt.tiny_config() if self.tiny else gpt.base_config()

        def init_fn(seed: int):
            task = gpt.make_task(cfg=self._cfg, seq_len=8, batch_size=1)
            return unbox(task.init(jax.random.key(seed)))

        self._params = _params_from_checkpoint(self.version, init_fn)

    def bucket_of(self, payload: Any) -> Any:
        import numpy as np

        arr = np.asarray(payload)
        if arr.ndim != 1 or arr.dtype.kind not in "iu":
            raise TypeError(
                f"gpt payload must be a 1-D int token array, got "
                f"{arr.dtype}{arr.shape}"
            )
        if arr.shape[0] + self.gen_tokens > self._cfg.max_len:
            raise TypeError(
                f"prompt of {arr.shape[0]} + {self.gen_tokens} generated "
                f"tokens exceeds max_len={self._cfg.max_len}"
            )
        return ("gpt", int(arr.shape[0]))

    def _run_for(self, plen: int):
        run = self._runs.get(plen)
        if run is None:
            import dataclasses as _dc

            import jax

            from tfk8s_tpu.models import gpt

            # right-size the KV cache to this bucket (prompt + generation)
            cfg = _dc.replace(self._cfg, decode_cache_len=plen + self.gen_tokens)
            run = jax.jit(
                lambda params, prompt: gpt.generate(
                    cfg, params, prompt, num_tokens=self.gen_tokens
                )
            )
            self._runs[plen] = run
        return run

    def forward(self, payloads: List[Any]) -> List[Any]:
        import numpy as np

        prompt = np.stack([np.asarray(p, dtype=np.int32) for p in payloads])
        n, plen = prompt.shape
        if n < self.max_batch_size:  # pad batch dim: one compile per bucket
            prompt = np.concatenate(
                [prompt, np.repeat(prompt[:1], self.max_batch_size - n, axis=0)]
            )
        out = np.asarray(self._run_for(plen)(self._params, prompt))
        return [
            {"tokens": out[i].tolist(), "version": self.version} for i in range(n)
        ]


def make_model(task: str, checkpoint: str, batching_max: int,
               env: Optional[Dict[str, str]] = None) -> ServedModel:
    """Served-model factory, by spec.task."""
    env = env or {}
    if task == "echo":
        return EchoModel(
            checkpoint,
            delay_ms=float(env.get("TFK8S_SERVE_ECHO_DELAY_MS", "0")),
        )
    if task == "mlp":
        return MlpClassifier(
            checkpoint, batching_max,
            hidden=int(env.get("TFK8S_SERVE_MLP_HIDDEN", "64")),
        )
    if task in ("gpt", "t5"):
        # t5 rides the same decoder-only generate path for now; the
        # enc-dec serving split is the documented follow-on (README)
        return GptGenerator(
            checkpoint, batching_max,
            gen_tokens=int(env.get("TFK8S_SERVE_GEN_TOKENS", "16")),
            tiny=env.get("TFK8S_SERVE_GPT_SIZE", "tiny") == "tiny",
        )
    raise ServeError(f"unknown serve task {task!r} (known: echo, mlp, gpt, t5)")


# ---------------------------------------------------------------------------
# Metrics registry hook (the data.images pattern: the operator process
# wires its registry in; standalone use falls back to a private one)
# ---------------------------------------------------------------------------

_metrics_lock = threading.Lock()
_metrics: Optional[Metrics] = None


def set_metrics(metrics: Metrics) -> None:
    global _metrics
    with _metrics_lock:
        _metrics = metrics


def get_metrics() -> Metrics:
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            _metrics = Metrics()
        return _metrics


# ---------------------------------------------------------------------------
# The dynamic micro-batching executor
# ---------------------------------------------------------------------------


@dataclass
class _Request:
    payload: Any
    bucket: Any
    enqueue_t: float
    done: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: Optional[BaseException] = None
    # stamped at dispatch so queue/execute split exactly once per request
    dequeue_t: float = 0.0


class ModelServer:
    """Bounded-queue dynamic batcher around one :class:`ServedModel`.

    Contract (unit-tested in tests/test_serving_executor.py):

    - a batch closes at ``max_batch_size`` OR ``batch_timeout_s`` after
      the batch OPENED (first request dequeued), whichever first;
    - only requests whose model bucket matches the batch head ride the
      batch — padding/bucketing never mixes incompatible shapes;
    - a submit past ``queue_limit`` sheds with :class:`Overloaded`; after
      :meth:`drain` began, with :class:`Draining`;
    - the queue/execute/total latency histograms observe every SERVED
      request exactly once (shed requests only count in
      ``tfk8s_serving_requests_total{outcome="rejected"}``).
    """

    def __init__(
        self,
        model: ServedModel,
        max_batch_size: int = 8,
        batch_timeout_s: float = 0.01,
        queue_limit: int = 128,
        metrics: Optional[Metrics] = None,
        labels: Optional[Dict[str, str]] = None,
    ):
        self.model = model
        self.max_batch_size = max(1, int(max_batch_size))
        self.batch_timeout_s = max(0.0, float(batch_timeout_s))
        self.queue_limit = max(self.max_batch_size, int(queue_limit))
        self.metrics = metrics if metrics is not None else get_metrics()
        self.labels = dict(labels or {})
        self._cond = threading.Condition()
        self._q: deque = deque()
        self._draining = False
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        # occupancy/throughput accounting (report_progress reads these)
        self.served_total = 0
        self.batches_total = 0
        self.rejected_total = 0
        self._qps_last = (time.monotonic(), 0)
        for name, help_text in (
            ("tfk8s_serving_requests_total",
             "Serving requests by outcome (ok / rejected / error)."),
            ("tfk8s_serving_batches_total", "Batches executed by the server."),
            ("tfk8s_serving_queue_seconds",
             "Per-request time from submit to batch dispatch."),
            ("tfk8s_serving_execute_seconds",
             "Per-request model execution time (its batch's wall time)."),
            ("tfk8s_serving_request_seconds",
             "Per-request total latency, submit to response."),
            ("tfk8s_serving_queue_depth", "Pending requests in the bounded queue."),
            ("tfk8s_serving_batch_occupancy",
             "Mean requests per executed batch since start."),
        ):
            self.metrics.describe(name, help_text)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "ModelServer":
        self._thread = threading.Thread(
            target=self._loop, name="serve-batcher", daemon=True
        )
        self._thread.start()
        return self

    def drain(self, timeout: float = 30.0) -> bool:
        """Stop accepting, finish everything queued, stop the batcher.
        Returns True when the queue fully drained inside ``timeout``."""
        deadline = time.monotonic() + timeout
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        while time.monotonic() < deadline:
            with self._cond:
                if not self._q:
                    break
            time.sleep(0.005)
        with self._cond:
            drained = not self._q
            self._stopped = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        return drained

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._q)

    @property
    def mean_batch_occupancy(self) -> float:
        return self.served_total / self.batches_total if self.batches_total else 0.0

    # -- client side --------------------------------------------------------

    def submit(self, payload: Any, timeout: Optional[float] = 30.0) -> Any:
        """Blocking request: returns the model's response for ``payload``,
        or raises Overloaded / Draining / RequestFailed / TimeoutError."""
        bucket = self.model.bucket_of(payload)  # TypeError propagates: bad payload
        req = _Request(payload=payload, bucket=bucket, enqueue_t=time.perf_counter())
        with self._cond:
            if self._draining or self._stopped:
                raise Draining("replica is draining; retry another replica")
            if len(self._q) >= self.queue_limit:
                self.rejected_total += 1
                self.metrics.inc(
                    "tfk8s_serving_requests_total", 1.0,
                    {**self.labels, "outcome": "rejected"},
                )
                raise Overloaded(len(self._q), self.queue_limit)
            self._q.append(req)
            self.metrics.set_gauge(
                "tfk8s_serving_queue_depth", float(len(self._q)), self.labels
            )
            self._cond.notify_all()
        if not req.done.wait(timeout):
            # best-effort cancellation: a request still QUEUED is removed
            # (the batcher never burns a forward on a caller that gave
            # up, and it is counted timeout, not ok); one already riding
            # a dispatched batch completes server-side — bounded waste.
            with self._cond:
                try:
                    self._q.remove(req)
                    self.metrics.inc(
                        "tfk8s_serving_requests_total", 1.0,
                        {**self.labels, "outcome": "timeout"},
                    )
                    self.metrics.set_gauge(
                        "tfk8s_serving_queue_depth", float(len(self._q)),
                        self.labels,
                    )
                except ValueError:
                    pass  # already dequeued into a batch
            raise TimeoutError(f"request not served within {timeout}s")
        if req.error is not None:
            raise RequestFailed(str(req.error)) from req.error
        return req.result

    # -- the batcher --------------------------------------------------------

    def _take_matching(self, bucket: Any, want: int) -> List[_Request]:
        """Pop up to ``want`` queued requests of ``bucket`` (FIFO among
        matches; non-matching requests keep their positions). Caller holds
        the lock."""
        taken: List[_Request] = []
        if want <= 0:
            return taken
        kept: deque = deque()
        while self._q:
            r = self._q.popleft()
            if len(taken) < want and r.bucket == bucket:
                taken.append(r)
            else:
                kept.append(r)
        self._q = kept
        return taken

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._q and not self._stopped:
                    self._cond.wait(0.5)
                if self._stopped and not self._q:
                    return
                head = self._q.popleft()
                batch = [head]
                deadline = time.monotonic() + self.batch_timeout_s
                # fill from what's already queued, then wait out the
                # remaining timeout for stragglers — size OR time closes it
                batch += self._take_matching(
                    head.bucket, self.max_batch_size - len(batch)
                )
                while len(batch) < self.max_batch_size:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or self._stopped or self._draining:
                        break
                    self._cond.wait(remaining)
                    batch += self._take_matching(
                        head.bucket, self.max_batch_size - len(batch)
                    )
                self.metrics.set_gauge(
                    "tfk8s_serving_queue_depth", float(len(self._q)), self.labels
                )
            self._execute(batch)

    def _execute(self, batch: List[_Request]) -> None:
        t0 = time.perf_counter()
        for r in batch:
            r.dequeue_t = t0
        try:
            results = self.model.forward([r.payload for r in batch])
            if len(results) != len(batch):  # a model bug, not a request bug
                raise RequestFailed(
                    f"model returned {len(results)} results for a batch of "
                    f"{len(batch)}"
                )
        except BaseException as e:  # noqa: BLE001 — fan the failure out
            t1 = time.perf_counter()
            for r in batch:
                r.error = e
                r.done.set()
            self.metrics.inc(
                "tfk8s_serving_requests_total", float(len(batch)),
                {**self.labels, "outcome": "error"},
            )
            log.warning("batch of %d failed: %s", len(batch), e)
            return
        t1 = time.perf_counter()
        self.batches_total += 1
        self.served_total += len(batch)
        self.metrics.inc("tfk8s_serving_batches_total", 1.0, self.labels)
        self.metrics.inc(
            "tfk8s_serving_requests_total", float(len(batch)),
            {**self.labels, "outcome": "ok"},
        )
        self.metrics.set_gauge(
            "tfk8s_serving_batch_occupancy", self.mean_batch_occupancy, self.labels
        )
        exec_s = t1 - t0
        for r, res in zip(batch, results):
            # exactly-once histogram contract: one observation per served
            # request per family, all recorded here and nowhere else
            self.metrics.observe(
                "tfk8s_serving_queue_seconds", r.dequeue_t - r.enqueue_t, self.labels
            )
            self.metrics.observe("tfk8s_serving_execute_seconds", exec_s, self.labels)
            self.metrics.observe(
                "tfk8s_serving_request_seconds", t1 - r.enqueue_t, self.labels
            )
            r.result = res
            r.done.set()

    # -- load reporting (progress → pod status → autoscaler) ----------------

    def report_progress(self) -> Dict[str, float]:
        now = time.monotonic()
        last_t, last_served = self._qps_last
        dt = now - last_t
        qps = (self.served_total - last_served) / dt if dt > 0 else 0.0
        self._qps_last = (now, self.served_total)
        values = {
            "serving_ready": 1.0,
            "serving_queue_depth": float(self.queue_depth),
            "serving_qps": qps,
            "serving_batch_occupancy": self.mean_batch_occupancy,
            "serving_requests": float(self.served_total),
        }
        _progress.report(**values)
        return values


# ---------------------------------------------------------------------------
# Replica registry + entrypoint (the kubelet-facing half)
# ---------------------------------------------------------------------------

_registry_lock = threading.Lock()
_REPLICAS: Dict[str, ModelServer] = {}


def register_replica(key: str, server: ModelServer) -> None:
    with _registry_lock:
        _REPLICAS[key] = server


def unregister_replica(key: str) -> None:
    with _registry_lock:
        _REPLICAS.pop(key, None)


def lookup_replica(key: str) -> Optional[ModelServer]:
    with _registry_lock:
        return _REPLICAS.get(key)


# How often the serving entrypoint refreshes its progress report. The
# kubelet flushes progress into pod status every LOG_FLUSH_SECONDS on its
# own clock; reporting faster than it flushes costs nothing.
PROGRESS_PERIOD_S = 0.2


def replica_is_ready(pod) -> bool:
    """THE replica-readiness predicate, shared by the serve controller's
    rollout gating and ServeClient's routing (one definition — the two
    must never disagree or the zero-failed-requests rollout contract
    breaks): live, RUNNING, and the server reported ``serving_ready``
    AFTER loading the checkpoint (published into pod status by the
    kubelet flush — the hermetic readiness probe)."""
    from tfk8s_tpu.api.types import PodPhase

    return (
        pod.metadata.deletion_timestamp is None
        and pod.status.phase == PodPhase.RUNNING
        and pod.status.training.get("serving_ready") == 1.0
    )


def serve(env: Dict[str, str], stop: threading.Event) -> None:
    """The TPUServe pod entrypoint (rendered by trainer/serve_controller).
    Load → register → Ready → report load until stopped → drain."""
    task = env.get("TFK8S_SERVE_TASK", "echo")
    checkpoint = env.get("TFK8S_SERVE_CHECKPOINT", "")
    max_batch = int(env.get("TFK8S_SERVE_MAX_BATCH", "8"))
    timeout_ms = float(env.get("TFK8S_SERVE_BATCH_TIMEOUT_MS", "10"))
    queue_limit = int(env.get("TFK8S_SERVE_QUEUE_LIMIT", "128"))
    ns = env.get("TFK8S_NAMESPACE", "default")
    pod = env.get("TFK8S_POD_NAME", "")
    serve_name = env.get("TFK8S_SERVE_NAME", "")
    key = f"{ns}/{pod}"

    model = make_model(task, checkpoint, max_batch, env)
    model.load()  # Ready is honest: the weights are resident before it
    server = ModelServer(
        model,
        max_batch_size=max_batch,
        batch_timeout_s=timeout_ms / 1000.0,
        queue_limit=queue_limit,
        metrics=get_metrics(),
        labels={"serve": serve_name, "pod": pod},
    ).start()
    register_replica(key, server)
    server.report_progress()
    log.info("%s: serving %s (%s) ready; version=%s", key, task, checkpoint,
             model.version)
    reclaimed = False
    try:
        while not stop.wait(PROGRESS_PERIOD_S):
            # a reclaim notice (runtime/kubelet.py PodStopSignal) is an
            # immediate graceful exit for a serving replica: there is no
            # step to finish — unregister now so the client routes away,
            # drain the accepted queue, and exit Drained so the
            # controller replaces rather than failure-counts the pod
            if getattr(stop, "drain_requested", False):
                reclaimed = True
                log.info("%s: reclaim notice; draining replica", key)
                break
            server.report_progress()
    finally:
        # drain order matters: unregister FIRST so the client stops
        # picking this replica, then finish what it already holds —
        # a rolling update never fails an accepted request
        unregister_replica(key)
        drained = server.drain(
            timeout=float(env.get("TFK8S_SERVE_DRAIN_TIMEOUT_S", "30"))
        )
        log.info("%s: drained=%s after %d requests in %d batches",
                 key, drained, server.served_total, server.batches_total)
    if reclaimed:
        from tfk8s_tpu.runtime.registry import PodDrained

        raise PodDrained(f"{key}: replica drained on reclaim notice")


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class ServeClient:
    """Round-robin client over a TPUServe's Ready replicas. Discovery is
    a pod list through the clientset (label selector, the endpoints-list
    analogue); dispatch goes through the in-process replica registry.
    Draining/vanished replicas are retried transparently on another
    replica (the zero-failed-requests rollout contract); Overloaded is
    surfaced to the caller — backpressure is the point."""

    def __init__(self, clientset, name: str, namespace: str = "default",
                 cache_ttl_s: float = 0.25):
        self._cs = clientset
        self.name = name
        self.namespace = namespace
        self._rr = 0
        self._cache: Tuple[float, List[str]] = (0.0, [])
        self._cache_ttl = cache_ttl_s
        self._lock = threading.Lock()

    def ready_replica_keys(self, refresh: bool = False) -> List[str]:
        from tfk8s_tpu.trainer import labels as L

        with self._lock:
            ts, cached = self._cache
            if not refresh and cached and time.monotonic() - ts < self._cache_ttl:
                return list(cached)
        pods, _rv = self._cs.pods(self.namespace).list(
            label_selector=L.serve_selector(self.name)
        )
        keys = sorted(p.metadata.key for p in pods if replica_is_ready(p))
        with self._lock:
            self._cache = (time.monotonic(), keys)
        return keys

    def request(self, payload: Any, timeout: float = 30.0) -> Any:
        deadline = time.monotonic() + timeout
        refresh = False
        backoff = 0.02
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"no replica of {self.namespace}/{self.name} served the "
                    f"request within {timeout}s"
                )
            keys = self.ready_replica_keys(refresh=refresh)
            refresh = False
            targets = [k for k in keys if lookup_replica(k) is not None]
            if not targets:
                # exponential backoff while no replica is routable: N
                # blocked callers re-listing every few ms would stampede
                # the shared rate-limited client during a rollout gap
                time.sleep(min(backoff, max(remaining, 0.0)))
                backoff = min(backoff * 2, 0.5)
                refresh = True
                continue
            backoff = 0.02
            with self._lock:
                self._rr += 1
                key = targets[self._rr % len(targets)]
            server = lookup_replica(key)
            if server is None:
                refresh = True
                continue
            try:
                return server.submit(payload, timeout=remaining)
            except Draining:
                # replica is rolling out from under us — retry elsewhere
                refresh = True
                continue


def template_hash(wire_fragment: Any) -> str:
    """Stable short hash of a wire-form spec fragment — the pod-template
    version identity rolling updates key off."""
    import json

    blob = json.dumps(wire_fragment, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:10]


__all__ = [
    "Draining",
    "EchoModel",
    "GptGenerator",
    "MlpClassifier",
    "ModelServer",
    "Overloaded",
    "RequestFailed",
    "ServeClient",
    "ServeError",
    "ServedModel",
    "make_model",
    "register_replica",
    "replica_is_ready",
    "serve",
    "set_metrics",
    "template_hash",
    "unregister_replica",
]
