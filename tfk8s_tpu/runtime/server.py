"""In-process model server: the data plane of the TPUServe subsystem.

The kubelet launches this like any trainer entrypoint (:func:`serve`),
closing the gap the ROADMAP names — ``models/transformer.py`` ships
``clean_cache``/``prefill_cache`` incremental-decode machinery that no
runtime exercised. The server:

1. loads the checkpoint named by the spec (``seed:<n>`` initializes
   deterministic params hermetically; a path/URI restores a real
   checkpoint), THEN reports Ready — the controller's readiness gate;
2. runs a **dynamic micro-batching executor** (:class:`ModelServer`):
   requests land in a bounded queue; the batcher closes a batch at
   ``max_batch_size`` or ``batch_timeout`` — whichever first (Clipper-
   style adaptive batching); requests are grouped by a model-defined
   **bucket key** so incompatible shapes are never padded together; one
   jitted forward serves the whole batch (KV-cache ``gpt.generate`` for
   generative tasks, a plain padded forward for classifiers); responses
   fan back with per-request queue/execute/total latency histograms;
3. sheds load: past ``queue_limit`` a submit raises the typed
   :class:`Overloaded` (the 429 equivalent) instead of queuing
   unboundedly;
4. reports load (queue depth, windowed QPS, mean batch occupancy) through
   ``runtime/progress.py`` → kubelet flush → ``pod.status.training`` —
   the same channel training throughput rides — which is what the
   controller's autoscaler consumes.

Transport: replicas register in an in-process table keyed by pod
(``namespace/pod-name``) and :class:`ServeClient` dispatches into them
after discovering Ready replicas through the apiserver — the hermetic
analogue of a Service endpoint list. This is the seam where an HTTP/gRPC
front end would slot in for a multi-host deployment; the batching
executor, readiness gate, and drain protocol are transport-independent.

Drain protocol (what makes rolling updates lose zero requests): pod
deletion signals the entrypoint's stop event; the server first
UNREGISTERS (new submits see :class:`Draining` and the client retries on
another replica), then finishes every queued request before the thread
exits.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from tfk8s_tpu.obs import trace as _trace
from tfk8s_tpu.runtime import progress as _progress
from tfk8s_tpu.runtime.handoff import HandoffError, KVHandoffBuffer
from tfk8s_tpu.utils.logging import Metrics, get_logger

log = get_logger("serve")

# Per-token timeline events attached to a traced request's serve span
# are strided down to this many samples — a 4k-token generation must
# not balloon its span (the full TPOT distribution is in the histogram;
# the span carries the shape).
MAX_TOKEN_EVENTS = 32


def _trace_id_of(traceparent: str) -> str:
    parsed = _trace.parse_traceparent(traceparent)
    return parsed[0] if parsed else ""


class ServeError(Exception):
    """Base class for serving-path errors."""


class Overloaded(ServeError):
    """Bounded-queue backpressure: the request was shed, not queued — the
    typed 429 equivalent. Carries the observed depth and the limit so a
    client/load-balancer can back off intelligently; ``retry_after_s``
    (when the shedder knows one — the gateway's priority bands do) is the
    hint clients turn into a jittered backoff instead of re-hammering."""

    def __init__(self, queue_depth: int, queue_limit: int,
                 retry_after_s: Optional[float] = None):
        self.queue_depth = queue_depth
        self.queue_limit = queue_limit
        self.retry_after_s = retry_after_s
        super().__init__(
            f"request queue full ({queue_depth}/{queue_limit}); retry later"
        )


class QuotaExceeded(ServeError):
    """The TENANT's admission budget (gateway/admission.py: token-bucket
    QPS or the concurrency cap from its TenantQuota) is exhausted — the
    per-tenant 429. Distinct from :class:`Overloaded`: the cluster may
    have headroom; THIS tenant does not, which is what keeps one abusive
    tenant from starving the rest. ``retry_after_s`` is when the bucket
    accrues the next token."""

    def __init__(self, tenant: str, retry_after_s: float, reason: str = "qps"):
        self.tenant = tenant
        self.retry_after_s = retry_after_s
        self.reason = reason
        super().__init__(
            f"tenant {tenant!r} over {reason} quota; retry in {retry_after_s:.3f}s"
        )


class Draining(ServeError):
    """The replica is shutting down (rolling update / scale-down): it no
    longer ACCEPTS requests but will finish the ones it holds. Clients
    retry on another replica."""


class InvalidRequest(ServeError):
    """The request itself is unservable (e.g. prompt + generation budget
    exceeds the model's max_len) — the typed 400 equivalent. Unlike a
    malformed payload (TypeError: caller bug), this is a CLIENT-visible
    outcome: counted as ``outcome="invalid"`` in the serving request
    counter and never retried by :class:`ServeClient` (no other replica
    could serve it either)."""


class RequestFailed(ServeError):
    """The model raised while executing the batch this request rode."""


class DeadlineExceeded(ServeError, TimeoutError):
    """The caller's deadline elapsed before the request was served — the
    typed 504 equivalent. Subclasses :class:`TimeoutError` so callers
    written against the original ``raise TimeoutError`` contract keep
    working, while the serve paths now only raise the ServeError tree."""


class ReplicaUnavailable(RequestFailed, ConnectionError):
    """The replica died (or dropped the connection) while it held the
    request — a transport-class failure of an accepted-but-unanswered,
    idempotent serve request. Unlike a plain :class:`RequestFailed` (the
    request's own execution raised), NOTHING about the request itself is
    suspect: it is safe to re-dispatch to a survivor, which is exactly
    what the gateway does under its retry budget. Subclasses
    :class:`ConnectionError` so transport-level handlers catch it too."""


class RowFault(RequestFailed):
    """A fault attributable to ONE decode row — poisoned pages, a
    malformed continuation (out-of-vocab token off the device), a
    per-row device fault. Crash containment retires THAT row typed and
    quarantines its pages; sibling rows keep decoding untouched."""


class Preempted(RequestFailed):
    """A low-priority row was evicted mid-decode to free KV pages for a
    stalled higher-priority admission AND its spill could not complete
    (an export/serialize failure) — the row cannot be resumed, so its
    request fails typed and retriable. A SUCCESSFUL preemption never
    surfaces this error: the row's KV spills to a host-side
    :class:`KVHandoffBuffer`, the request re-enters the queue at the
    front of its priority class, and it later completes bit-identical to
    an unpreempted run (the resume path is the KV-handoff import). The
    class exists so spill failures are distinguishable from
    :class:`RowFault` (whose pages are suspect) — a preempted-and-lost
    request's pages were healthy; it is safe to re-dispatch."""


# ---------------------------------------------------------------------------
# Served models
# ---------------------------------------------------------------------------


class ServedModel:
    """One loadable model family. ``bucket_of`` partitions payloads into
    batchable groups (payloads in one bucket MUST be stackable after the
    model's own padding); ``forward`` serves one bucket's batch."""

    #: version string of the loaded weights (the checkpoint ref)
    version: str = ""

    def load(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def bucket_of(self, payload: Any) -> Any:  # pragma: no cover - interface
        raise NotImplementedError

    def forward(self, payloads: List[Any]) -> List[Any]:  # pragma: no cover
        raise NotImplementedError


class EchoModel(ServedModel):
    """Hermetic control-plane test model: no accelerator, no compile.
    Payloads are scalars/arrays; the response echoes ``payload`` plus the
    model version. ``delay_ms`` emulates per-BATCH model latency so
    batching measurably beats sequential dispatch and autoscaler tests
    can build real queue depth."""

    def __init__(self, checkpoint: str = "", delay_ms: float = 0.0):
        self.version = checkpoint or "echo"
        self.delay_ms = delay_ms
        self._loaded = False

    def load(self) -> None:
        self._loaded = True

    def bucket_of(self, payload: Any) -> Any:
        shape = getattr(payload, "shape", None)
        return ("echo", tuple(shape) if shape is not None else type(payload).__name__)

    def forward(self, payloads: List[Any]) -> List[Any]:
        if not self._loaded:
            raise RequestFailed("model not loaded")
        if self.delay_ms:
            time.sleep(self.delay_ms / 1000.0)
        return [{"echo": p, "version": self.version} for p in payloads]


def _params_from_checkpoint(checkpoint: str, init_fn: Callable[[int], Any]) -> Any:
    """Resolve a checkpoint ref to params: ``seed:<n>`` initializes
    deterministically (the hermetic path every test and the bench use);
    anything else restores the latest step from a checkpoint directory
    (runtime/checkpoint.py)."""
    if checkpoint.startswith("seed:"):
        return init_fn(int(checkpoint[len("seed:"):] or "0"))
    from tfk8s_tpu.runtime import checkpoint as ckpt

    mgr = ckpt.CheckpointManager(checkpoint)
    if mgr.latest_step() is None:
        raise ServeError(f"checkpoint {checkpoint!r} has no saved step")
    return mgr.restore({"params": init_fn(0)})["params"]


class MlpClassifier(ServedModel):
    """Classifier serving path: ONE jitted forward over a batch padded to
    ``max_batch_size`` rows (a single compile per feature shape — batch
    occupancy varies per dispatch, the padded shape does not)."""

    def __init__(self, checkpoint: str, max_batch_size: int, hidden: int = 64):
        self.version = checkpoint
        self.max_batch_size = max_batch_size
        self.hidden = hidden
        self._apply = None
        self._params = None

    def load(self) -> None:
        import jax
        import jax.numpy as jnp

        from tfk8s_tpu.models.mlp import IMAGE_DIM, MLP
        from tfk8s_tpu.parallel.sharding import unbox

        model = MLP(hidden=self.hidden)

        def init_fn(seed: int):
            return unbox(
                model.init(jax.random.key(seed), jnp.zeros((1, IMAGE_DIM)))["params"]
            )

        self._params = _params_from_checkpoint(self.version, init_fn)
        self._apply = jax.jit(
            lambda params, x: jnp.argmax(model.apply({"params": params}, x), axis=-1)
        )

    def bucket_of(self, payload: Any) -> Any:
        import numpy as np

        arr = np.asarray(payload)
        if arr.ndim != 1:
            raise TypeError(f"mlp payload must be a 1-D feature vector, got {arr.shape}")
        return ("mlp", arr.shape)

    def forward(self, payloads: List[Any]) -> List[Any]:
        import numpy as np

        x = np.stack([np.asarray(p, dtype=np.float32) for p in payloads])
        n = len(payloads)
        if n < self.max_batch_size:  # pad rows; one compile per feature shape
            x = np.concatenate(
                [x, np.zeros((self.max_batch_size - n, x.shape[1]), np.float32)]
            )
        out = np.asarray(self._apply(self._params, x))
        return [{"label": int(out[i]), "version": self.version} for i in range(n)]


# (temperature, top_k, top_p, seed) — the normalized per-request
# sampling tuple threaded from validate() through the packed device
# step. temperature <= 0 pins the row to the greedy argmax path.
_SamplingTuple = Tuple[float, int, float, int]


def _parse_sampling(raw: Any) -> Optional[_SamplingTuple]:
    """Normalize a payload's ``sampling`` block into ``(temperature,
    top_k, top_p, seed)`` via :class:`api.types.SamplingParams` — the
    one wire schema for the block, so defaults/casings/ranges cannot
    drift between the API surface and this parser. Raises
    :class:`InvalidRequest` on malformed blocks and out-of-range knobs —
    the block rides the wire payload, so every failure here is
    client-visible."""
    from tfk8s_tpu.api.types import SamplingParams

    if raw is None:
        return None
    try:
        params = SamplingParams.from_payload(raw)
    except ValueError as e:
        raise InvalidRequest(str(e)) from None
    if params.temperature == 0.0:
        return None  # greedy: identical to no sampling block at all
    return params.as_tuple()


def _gpt_config_of(size: str):
    """Served GPT shape by name: ``tiny`` (test scale), ``mid`` (the
    serving-bench scale whose decode step is FLOP-bound even on a CPU
    host), ``base`` (GPT-2-small)."""
    from tfk8s_tpu.models import gpt

    shapes = {
        "tiny": gpt.tiny_config,
        "mid": gpt.mid_config,
        "base": gpt.base_config,
    }
    if size not in shapes:
        raise ServeError(
            f"unknown TFK8S_SERVE_GPT_SIZE {size!r} (known: tiny, mid, base)"
        )
    return shapes[size]()


class GptGenerator(ServedModel):
    """Generative serving path: batched-prefill + KV-cache decode
    (``models/gpt.generate`` — the ``prefill_cache``/``clean_cache``
    machinery finally driven by a runtime). Prompts bucket by EXACT
    length: decode mode refuses padding masks by design (padded K/V
    would silently corrupt the cache), so same-length prompts are the
    only safe batch. The batch dim pads to ``max_batch_size`` (row 0
    repeated) so each prompt-length bucket compiles once."""

    def __init__(self, checkpoint: str, max_batch_size: int, gen_tokens: int = 16,
                 size: str = "tiny"):
        self.version = checkpoint
        self.max_batch_size = max_batch_size
        self.gen_tokens = gen_tokens
        self.size = size
        self._params = None
        self._cfg = None
        self._runs: Dict[int, Any] = {}  # prompt_len -> jitted generate

    def load(self) -> None:
        import jax

        from tfk8s_tpu.models import gpt
        from tfk8s_tpu.parallel.sharding import unbox

        self._cfg = _gpt_config_of(self.size)

        def init_fn(seed: int):
            task = gpt.make_task(cfg=self._cfg, seq_len=8, batch_size=1)
            return unbox(task.init(jax.random.key(seed)))

        self._params = _params_from_checkpoint(self.version, init_fn)

    def bucket_of(self, payload: Any) -> Any:
        import numpy as np

        arr = np.asarray(payload)
        if arr.ndim != 1 or arr.dtype.kind not in "iu":
            raise TypeError(
                f"gpt payload must be a 1-D int token array, got "
                f"{arr.dtype}{arr.shape}"
            )
        if arr.shape[0] + self.gen_tokens > self._cfg.max_len:
            # client-visible typed rejection, NOT a malformed payload:
            # the executor counts it outcome="invalid" (was a bare
            # TypeError that read as a caller bug)
            raise InvalidRequest(
                f"prompt of {arr.shape[0]} + {self.gen_tokens} generated "
                f"tokens exceeds max_len={self._cfg.max_len}"
            )
        return ("gpt", int(arr.shape[0]))

    def _run_for(self, plen: int):
        run = self._runs.get(plen)
        if run is None:
            import dataclasses as _dc

            import jax

            from tfk8s_tpu.models import gpt

            # right-size the KV cache to this bucket (prompt + generation)
            cfg = _dc.replace(self._cfg, decode_cache_len=plen + self.gen_tokens)
            run = jax.jit(
                lambda params, prompt: gpt.generate(
                    cfg, params, prompt, num_tokens=self.gen_tokens
                )
            )
            self._runs[plen] = run
        return run

    def forward(self, payloads: List[Any]) -> List[Any]:
        import numpy as np

        prompt = np.stack([np.asarray(p, dtype=np.int32) for p in payloads])
        n, plen = prompt.shape
        if n < self.max_batch_size:  # pad batch dim: one compile per bucket
            prompt = np.concatenate(
                [prompt, np.repeat(prompt[:1], self.max_batch_size - n, axis=0)]
            )
        out = np.asarray(self._run_for(plen)(self._params, prompt))
        return [
            {"tokens": out[i].tolist(), "version": self.version} for i in range(n)
        ]


class PagedGptDecoder:
    """Model half of the continuous-batching decode loop: GPT params plus
    the jitted packed entry points the loop dispatches —
    ``gpt.decode_step_packed`` (one token for every live slot against
    the block-paged KV cache) and ``gpt.prefill_step_packed`` (one chunk
    round of prompt slices, batched across an admission burst). Because
    the cache is paged, prompts of EVERY length ride the same three
    compiled shapes (all warmed at load); the per-prompt-length compile
    cache of :class:`GptGenerator` is gone."""

    def __init__(self, checkpoint: str, slots: int, page_size: int,
                 max_pages: int, gen_tokens: int = 16, size: str = "tiny",
                 prefill_chunk: int = 32, eos_id: Optional[int] = None,
                 cfg: Any = None, params: Any = None):
        self.version = checkpoint
        self.slots = max(1, int(slots))
        self.page_size = max(1, int(page_size))
        self.max_pages = max(2, int(max_pages))
        self.gen_tokens = gen_tokens
        self.size = size
        self.prefill_chunk = max(1, int(prefill_chunk))
        self.eos_id = eos_id
        # explicit base-config / params overrides: the speculative
        # engine shapes its draft to the target's vocab/max_len, and the
        # bench injects briefly-trained params so draft acceptance is
        # genuine — both without touching the checkpoint machinery
        self._cfg_base = cfg
        self._params_override = params
        self._params = None
        self._cfg = None
        self._pages = None
        self._decode_fn = None
        self._prefill_fn = None
        # sampled / speculative-verify variants compile lazily on first
        # use — a greedy-FIFO replica never pays for them
        self._decode_samp_fn = None
        self._prefill_samp_fn = None
        self._verify_fn = None
        self._verify_samp_fn = None

    def load(self) -> None:
        import dataclasses as _dc

        import jax

        from tfk8s_tpu.models import gpt
        from tfk8s_tpu.parallel.sharding import unbox

        base = (
            self._cfg_base if self._cfg_base is not None
            else _gpt_config_of(self.size)
        )
        cfg = _dc.replace(
            base, kv_page_size=self.page_size, kv_max_pages=self.max_pages
        )
        self._cfg = cfg

        def init_fn(seed: int):
            task = gpt.make_task(cfg=base, seq_len=8, batch_size=1)
            return unbox(task.init(jax.random.key(seed)))

        self._params = (
            self._params_override if self._params_override is not None
            else _params_from_checkpoint(self.version, init_fn)
        )
        self._pages = gpt.clean_pages(cfg)
        # The serving hot path runs the PACKED entry points: greedy pick
        # + position advance fused on device, all per-row step state in
        # one int32 array (one transfer per rebuild), and an admission
        # burst's prompt slices sharing one batched prefill dispatch.
        # Two deliberate dispatch-cost choices, both measured on the
        # 1-core CI box: params are CLOSED OVER (weights are fixed for a
        # replica's lifetime — rollouts replace the pod; passing them
        # re-flattens a ~40-leaf pytree every call, ~60us/step), and NO
        # donate_argnums on the pool (donation measured 2.5x SLOWER per
        # step than the pool copy at serving scale — 0.80 vs 0.32
        # ms/step; revisit for real-TPU deployments where the pool is
        # GBs and aliasing is free).
        params = self._params
        self._decode_fn = jax.jit(
            lambda pages, state: gpt.decode_step_packed(
                cfg, params, pages, state
            )
        )
        self._prefill_fn = jax.jit(
            lambda pages, batch: gpt.prefill_step_packed(
                cfg, params, pages, batch
            )
        )
        # sampled variants thread the per-row knob pair (samp_f =
        # temperature/top_p f32, samp_i = top_k/seed i32); rows with
        # temperature 0 stay argmax inside the SAME dispatch, so a mixed
        # greedy/sampled batch costs one program, and the verify step is
        # speculative decoding's one-dispatch scoring of k draft tokens
        self._decode_samp_fn = jax.jit(
            lambda pages, state, sf, si: gpt.decode_step_packed(
                cfg, params, pages, state, sampling=(sf, si)
            )
        )
        self._prefill_samp_fn = jax.jit(
            lambda pages, batch, sf, si: gpt.prefill_step_packed(
                cfg, params, pages, batch, sampling=(sf, si)
            )
        )
        self._verify_fn = jax.jit(
            lambda pages, state, drafts: gpt.verify_step_packed(
                cfg, params, pages, state, drafts
            )
        )
        self._verify_samp_fn = jax.jit(
            lambda pages, state, drafts, sf, si: gpt.verify_step_packed(
                cfg, params, pages, state, drafts, sampling=(sf, si)
            )
        )
        # KV handoff seam (ISSUE 14): gather/scatter the whole KV tree
        # in ONE XLA program per transfer. The eager per-leaf versions
        # paid a dispatch (and a full pool copy on import) per leaf —
        # measured ~30x slower on the 1-core box, enough to put a
        # handoff import on par with ~15 decode steps of loop stall.
        # export_kv/import_kv pad the index to the fixed pages_per_slot
        # extent (ISSUE 15), so BOTH compile exactly once — preemption
        # victims carry arbitrary page counts, and a per-count compile
        # would stall the whole decode loop mid-spill.
        self._export_fn = jax.jit(
            lambda pages, idx: [
                leaf[idx] for leaf in jax.tree_util.tree_leaves(pages)
            ]
        )

        def _scatter_kv(pages, srcs, idx):
            leaves, treedef = jax.tree_util.tree_flatten(pages)
            return jax.tree_util.tree_unflatten(
                treedef, [l.at[idx].set(s) for l, s in zip(leaves, srcs)]
            )

        self._import_fn = jax.jit(_scatter_kv)
        # Precompile all three serving shapes NOW (decode [slots], burst
        # prefill [slots, C], trickle prefill [1, C]) against the trash
        # page, so Ready means COMPILED — the first admission burst never
        # stalls behind XLA. The junk K/V land in page 0, which no live
        # row ever reads.
        import numpy as np

        mpp = cfg.pages_per_slot()
        c = self.prefill_chunk
        np.asarray(self.prefill_batch(np.zeros((1, c + 1 + mpp), np.int32)))
        np.asarray(
            self.prefill_batch(np.zeros((self.slots, c + 1 + mpp), np.int32))
        )
        nxt, state = self.decode(np.zeros((self.slots, 2 + mpp), np.int32))
        np.asarray(nxt)
        self._pages = gpt.clean_pages(cfg)  # drop the warmup junk

    @property
    def pages_per_slot(self) -> int:
        return self._cfg.pages_per_slot()

    @property
    def max_len(self) -> int:
        return self._cfg.max_len

    @property
    def vocab_size(self) -> int:
        """Bound on legal token ids — the decode loop's per-row sanity
        check: an out-of-range token off the device means THAT row's
        state is corrupt (poisoned pages / per-row device fault), which
        crash containment retires typed instead of failing the world."""
        return self._cfg.vocab_size

    def validate(self, payload: Any):
        """Normalize a payload into ``(tokens int32 [plen], gen_budget,
        sampling)``. Payloads are a 1-D int token array, or a dict
        ``{"tokens": ..., "gen_tokens": n, "sampling": {...}}`` for a
        per-request generation budget and sampling knobs
        (temperature / top_k / top_p / seed — see
        :func:`_parse_sampling`; ``sampling`` is None for greedy).
        Raises TypeError on malformed payloads and
        :class:`InvalidRequest` on unservable ones (over-long,
        non-positive budget, out-of-range knobs)."""
        import numpy as np

        gen = self.gen_tokens
        sampling = None
        if isinstance(payload, dict):
            if "tokens" not in payload:
                raise TypeError("gpt payload dict needs a 'tokens' key")
            try:
                gen = int(payload.get("gen_tokens", gen))
            except (TypeError, ValueError):
                # malformed payload, kept inside the documented submit
                # contract (a raw ValueError would escape it uncounted)
                raise TypeError(
                    f"gen_tokens must be an int, got "
                    f"{payload.get('gen_tokens')!r}"
                ) from None
            sampling = _parse_sampling(payload.get("sampling"))
            payload = payload["tokens"]
        arr = np.asarray(payload)
        if arr.ndim != 1 or arr.dtype.kind not in "iu" or arr.shape[0] < 1:
            raise TypeError(
                f"gpt payload must be a non-empty 1-D int token array, got "
                f"{arr.dtype}{arr.shape}"
            )
        if gen < 1:
            raise InvalidRequest(f"gen_tokens must be >= 1, got {gen}")
        if arr.shape[0] + gen > self._cfg.max_len:
            raise InvalidRequest(
                f"prompt of {arr.shape[0]} + {gen} generated tokens "
                f"exceeds max_len={self._cfg.max_len}"
            )
        return arr.astype(np.int32), gen, sampling

    # -- device dispatch (loop-thread only) ---------------------------------

    def prefill_batch(self, batch, samp=None):
        """One chunk round for every admitted request: ``batch`` is the
        packed ``[slots, C + 1 + pages_per_slot]`` int32 rows
        (gpt.prefill_step_packed), passed as NUMPY — the jit's internal
        C++ transfer path measured ~3.5x cheaper than an explicit
        device_put here. Returns the picks ``[slots, C]`` as numpy
        (synced). ``samp`` is the per-row ``(samp_f, samp_i)`` knob pair
        when any admitted row samples; None keeps the original greedy
        program."""
        import numpy as np

        if samp is None:
            picks, self._pages = self._prefill_fn(self._pages, batch)
        else:
            picks, self._pages = self._prefill_samp_fn(
                self._pages, batch, samp[0], samp[1]
            )
        return np.asarray(picks)

    def decode(self, state, samp=None):
        """One fused decode step over the DEVICE-RESIDENT packed state
        (numpy accepted on rebuild iterations); returns
        ``(emitted_tokens, new_state)`` with new_state still on device —
        the caller syncs emitted once per step and feeds new_state
        straight back while no row changes. ``samp`` as in
        :meth:`prefill_batch`; greedy rows inside a sampled batch stay
        bit-identical to the plain program's argmax."""
        if samp is None:
            nxt, new_state, self._pages = self._decode_fn(self._pages, state)
        else:
            nxt, new_state, self._pages = self._decode_samp_fn(
                self._pages, state, samp[0], samp[1]
            )
        return nxt, new_state

    def verify(self, state, drafts, samp=None):
        """Speculative-decode scoring: one packed chunk dispatch runs the
        target over each row's last token + ``k`` draft proposals and
        returns the target's own pick at every position as numpy
        ``[slots, k + 1]`` (gpt.verify_step_packed). The caller accepts
        the longest agreeing prefix; emitted streams stay token-identical
        to plain decoding at the same seeds regardless of the draft."""
        import numpy as np

        if samp is None:
            picks, self._pages = self._verify_fn(self._pages, state, drafts)
        else:
            picks, self._pages = self._verify_samp_fn(
                self._pages, state, drafts, samp[0], samp[1]
            )
        return np.asarray(picks)

    # -- KV handoff seam (runtime/handoff.py) --------------------------------

    def export_kv(self, page_ids):
        """Copy the K/V rows of ``page_ids`` out of the page pool as
        numpy leaves (tree order). The pool leaves are FLAT along the
        token axis — page ``pid`` is rows ``[pid*ps, (pid+1)*ps)`` — so
        each exported leaf is the buffer's contiguous
        ``[n_pages*ps, heads, head_dim]`` block. All leaves gather in
        one jitted program, then sync to host; a device-to-device
        transport reads the same row ranges without the host hop.

        The gather index is padded to the fixed ``pages_per_slot``
        extent with trash-page rows (sliced off after the host sync), so
        every export — disagg handoff or preemption spill — runs the
        SAME compiled program regardless of the row's page count. Same
        full-extent trade as the dense paged-attention gather
        (models/transformer.py, PALLAS SEAM): pay bounded junk traffic
        for a shape-stable one-program hot path."""
        import numpy as np

        ps = self.page_size
        n = len(page_ids)
        padded = list(page_ids) + [0] * max(self.pages_per_slot - n, 0)
        idx = np.concatenate(
            [np.arange(p * ps, (p + 1) * ps) for p in padded]
        )
        return [
            np.asarray(leaf)[: n * ps]
            for leaf in self._export_fn(self._pages, idx)
        ]

    def import_kv(self, kv_leaves, page_ids) -> None:
        """Land exported K/V rows into THIS replica's pool at
        ``page_ids`` (same order as :meth:`export_kv` wrote them). The
        write is a scatter into rows no live slot's page table points
        at, so sibling rows are untouched by construction."""
        import jax
        import numpy as np

        ps = self.page_size
        leaves, treedef = jax.tree_util.tree_flatten(self._pages)
        if len(kv_leaves) != len(leaves):
            raise HandoffError(
                f"buffer carries {len(kv_leaves)} kv leaves, model has "
                f"{len(leaves)} — incompatible model config"
            )
        n_rows = len(page_ids) * ps
        for i, (leaf, src) in enumerate(zip(leaves, kv_leaves)):
            if (
                tuple(src.shape[1:]) != tuple(leaf.shape[1:])
                or src.shape[0] != n_rows
            ):
                raise HandoffError(
                    f"kv leaf {i} is {tuple(src.shape)}, pool expects "
                    f"[{n_rows}, {', '.join(map(str, leaf.shape[1:]))}]"
                )
        # pad the scatter to the fixed pages_per_slot extent — the extra
        # rows land in trash page 0, which no live row ever reads — so
        # every import (handoff or preemption restore) shares ONE
        # compiled program (see export_kv)
        pad = max(self.pages_per_slot - len(page_ids), 0)
        padded = list(page_ids) + [0] * pad
        idx = np.concatenate(
            [np.arange(p * ps, (p + 1) * ps) for p in padded]
        )
        srcs = [
            np.concatenate(
                [src, np.zeros((pad * ps,) + src.shape[1:], src.dtype)]
            ) if pad else np.asarray(src)
            for src in kv_leaves
        ]
        self._pages = self._import_fn(self._pages, srcs, idx)


@dataclass(eq=False)  # identity semantics: deque.remove / slots.index
class _GenRequest:
    tokens: Any           # np.int32 [plen]
    gen_budget: int
    enqueue_t: float
    done: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: Optional[BaseException] = None
    dequeue_t: float = 0.0       # admission into a slot
    first_token_t: float = 0.0   # prefill produced the first output token
    out: List[int] = field(default_factory=list)
    # request-scoped observability (empty traceparent = untraced; the
    # timeline below is only collected for traced requests)
    traceparent: str = ""
    tenant: str = ""
    priority: int = 0
    wall_start: float = 0.0      # time.time() at submit, anchors the
    # perf_counter timeline onto the wall clock spans use
    cached_pages: int = 0        # prefix-cache pages reused at admission
    prefill_chunks: int = 0      # chunk rounds this request rode
    token_times: List[float] = field(default_factory=list)
    # disaggregated serving (runtime/handoff.py): a prefill-pool request
    # stops after its first output token and exports the warm KV
    # (prefill_only + decode_budget -> exported buffer rides the
    # result); a decode-pool request arrives WITH a buffer (handoff) and
    # skips prefill entirely
    prefill_only: bool = False
    decode_budget: int = 0
    handoff: Optional[KVHandoffBuffer] = None
    exported: Optional[KVHandoffBuffer] = None
    # per-request sampling knobs (temperature, top_k, top_p, seed) — None
    # means greedy, the bit-identical argmax path. The seed + the
    # absolute-position PRNG fold is what makes a sampled stream survive
    # a preempt/spill/restore cycle unchanged.
    sampling: Optional[_SamplingTuple] = None
    # scheduler accounting: how many times this request was preempted,
    # and the ORIGINAL prompt length (captured at the first spill —
    # spills absorb emitted tokens into ``tokens``, so the resident
    # stream must be rebuilt from the immutable prompt every time)
    preempt_count: int = 0
    prompt_len: int = 0
    # KV economy (runtime/kvtier): the gateway's cache-directory hint —
    # a replica key believed to hold this prompt's prefix warm. Consumed
    # (at most once) by the admission-time peer fetch; empty = no hint.
    kv_peer: str = ""

    def wall(self, t: float) -> float:
        """Map a perf_counter stamp onto the wall clock."""
        return self.wall_start + (t - self.enqueue_t)


@dataclass(eq=False)
class _Slot:
    req: _GenRequest
    lease: Any                   # paging.SlotLease
    idx: int = 0                 # fixed row in the slot bank / step state
    position: int = 0            # absolute write position of the NEXT token
    last_token: int = 0
    # speculative decode: the tokens emitted by this row's LAST round
    # (the draft engine's catch-up chunk; position of chunk[0] is
    # position - len(chunk) + 1). None/empty means the draft has nothing
    # to catch up on and the row sits out speculative rounds.
    spec_chunk: Optional[List[int]] = None


class DecodeLoopExecutor:
    """ORCA-style continuous batching for generative serving: a
    persistent decode loop over a fixed bank of ``slots``, admitting and
    retiring requests at TOKEN granularity against the block-paged KV
    cache (models/gpt.decode_step_packed + runtime/paging.PageAllocator).

    Each iteration the loop (1) retires rows that hit their eos or
    generation budget — their pages free immediately and their slot is
    reusable THIS iteration, (2) admits queued requests into free slots
    while the page pool covers their worst-case budget (FIFO; an
    admission the pool cannot cover stalls, it never corrupts live
    rows), (3) chunk-prefills admissions (page-aligned shared prompt
    prefixes skip straight to cached pages — copy-on-write reuse), and
    (4) runs ONE decode step for every live row. A short request
    admitted behind a long-running one therefore completes mid-batch
    instead of waiting out the batch (the slot-per-batch
    :class:`GptGenerator` behavior this replaces).

    Client surface (submit / drain / queue_depth / report_progress) and
    the requests/queue/execute/total metric families match
    :class:`ModelServer`, so the controller, autoscaler, registry and
    ServeClient work unchanged. New per-token families:
    ``tfk8s_serving_tokens_total``, ``tfk8s_serving_tpot_seconds``
    (per-request mean time per output token),
    ``tfk8s_serving_slot_occupancy`` / ``tfk8s_serving_page_occupancy``
    gauges, and ``tfk8s_serving_prefix_cache_hits_total``.
    """

    def __init__(
        self,
        model: PagedGptDecoder,
        queue_limit: int = 128,
        metrics: Optional[Metrics] = None,
        labels: Optional[Dict[str, str]] = None,
        prefix_cache: bool = True,
        sched_policy: str = "fifo",
        preemption: bool = True,
        aging_s: float = 5.0,
        speculative: Any = None,
        kv_host_bytes: int = 0,
        kv_peer_fetch: bool = False,
        kv_transport: Any = None,
        kv_peer_resolve: Any = None,
    ):
        from tfk8s_tpu.runtime.kvtier import HostKVCache
        from tfk8s_tpu.runtime.paging import PageAllocator
        from tfk8s_tpu.runtime.sched import make_scheduler

        self.model = model
        # vocab bound for the per-row malformed-continuation check; a
        # decoder that declares none (test doubles) skips the upper
        # bound — negative tokens are malformed regardless
        self._vocab_bound = getattr(model, "vocab_size", None)
        self.queue_limit = max(1, int(queue_limit))
        self.metrics = metrics if metrics is not None else get_metrics()
        self.labels = dict(labels or {})
        if model.max_pages - 1 < model.pages_per_slot:
            # a max_len request could NEVER admit — it would sit queued
            # until its submit timeout, forever; refuse loudly at startup
            raise ServeError(
                f"max_pages={model.max_pages} cannot hold one max_len "
                f"request ({model.pages_per_slot} pages of "
                f"{model.page_size} tokens + the trash page)"
            )
        self.allocator = PageAllocator(
            model.max_pages, model.page_size, prefix_cache=prefix_cache
        )
        # KV economy (runtime/kvtier): the device tier's eviction hook
        # always runs — eviction accounting is a bugfix, not a feature
        # flag — but demotion to host only happens with a host budget
        self.allocator.on_evict = self._kv_on_device_evict
        self._kv_host = (
            HostKVCache(
                int(kv_host_bytes), on_evict=self._kv_on_host_evict
            ) if kv_host_bytes and int(kv_host_bytes) > 0 else None
        )
        self._kv_peer_fetch = bool(kv_peer_fetch)
        self._kv_transport = kv_transport
        self._kv_resolve = kv_peer_resolve
        # digest -> (full-page prompt ints, chain length): what the
        # demotion path needs to rebuild a chain's tokens when one of
        # its pages evicts (register_prefix only keeps digests)
        self._kv_chains: Dict[str, Tuple[List[int], int]] = {}
        self.kv_peer_serves = 0
        self._kv_restore_ms: deque = deque(maxlen=256)
        self._cond = threading.Condition()
        # admission order is a pluggable policy (runtime/sched): FIFO is
        # the PR-7 behavior bit-identical; "priority" adds the per-class
        # weighted pick + page-spill preemption
        self._q = make_scheduler(sched_policy, aging_s=aging_s)
        self._preemption = bool(preemption) and sched_policy == "priority"
        # speculative decode engine (runtime/sched/speculative) — None
        # runs plain one-token steps; set via serve() env or tests
        self._spec = speculative
        self._known_priorities: set = set()
        self.preempted_total = 0
        self.restored_total = 0
        self._slots: List[Optional[_Slot]] = [None] * model.slots
        self._live = 0
        self._draining = False
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        self.served_total = 0
        self.batches_total = 0   # decode iterations
        self.rejected_total = 0
        self.tokens_total = 0
        self._occupancy_sum = 0
        self._qps_last = (time.monotonic(), 0)
        # device-resident packed step state ([slots, 2 + pages_per_slot]
        # int32 — gpt.decode_step_packed): rebuilt from the slot mirrors
        # only when admission, retirement or page-table growth changes a
        # row — steady-state decode feeds the previous step's output
        # state straight back
        self._d_state = None
        self._d_samp = None  # per-row sampling knobs, rebuilt with it
        self._state_dirty = True
        # fault containment (ISSUE 13): a non-None fault means a GLOBAL
        # failure (device unusable) — the loop is dead, submits refuse
        # with retriable ReplicaUnavailable, report_progress goes
        # non-Ready and the serve controller replaces the pod
        self._fault: Optional[BaseException] = None
        # chaos hooks (tests/chaos.py): poisoned prompt keys whose next
        # decoded token is corrupted to an out-of-vocab id (the hermetic
        # per-row device fault), and an injected submit latency (gray)
        self._chaos_poison: set = set()
        self._chaos_delay_s = 0.0
        for name, help_text in (
            ("tfk8s_serving_rows_quarantined_total",
             "Decode rows retired by per-row fault containment; their "
             "pages are quarantined until verified."),
            ("tfk8s_serving_tokens_total",
             "Generated tokens, counted per decode iteration."),
            ("tfk8s_serving_tpot_seconds",
             "Per-request mean time per output token (decode phase), "
             "by tenant and priority class."),
            ("tfk8s_serving_ttft_seconds",
             "Per-request time to first token (submit to first output), "
             "by tenant and priority class."),
            ("tfk8s_serving_slot_occupancy",
             "Live decode slots / slot capacity of the decode loop."),
            ("tfk8s_serving_page_occupancy",
             "KV pages held (leases + prefix cache) / usable pool."),
            ("tfk8s_serving_prefix_cache_hits_total",
             "Admissions that reused cached prompt-prefix pages."),
            ("tfk8s_serving_prefix_cache_misses_total",
             "Admissions that found no cached prompt prefix and "
             "prefilled from scratch."),
            ("tfk8s_disagg_exports_total",
             "Prefill-pool requests whose warm KV was exported as a "
             "handoff buffer."),
            ("tfk8s_disagg_imports_total",
             "Handoff buffers imported directly into decode slots "
             "(no local prefill)."),
            ("tfk8s_sched_preemptions_total",
             "Rows evicted mid-decode by the priority scheduler, by "
             "reason (page_pressure = spilled and requeued; "
             "spill_failed = export failed, request failed typed)."),
            ("tfk8s_sched_queue_depth",
             "Queued requests per priority class (priority label)."),
            ("tfk8s_sched_spec_accept_ratio",
             "Speculative decode: accepted draft tokens / proposed, "
             "cumulative."),
            ("tfk8s_serving_prefix_cache_evictions_total",
             "Cached prefixes dropped by LRU pressure, by tier "
             "(device = page pool, host = host-RAM KV cache)."),
            ("tfk8s_serving_kv_host_ops_total",
             "Host-tier KV cache traffic: demote (device eviction "
             "parked the chain), restore (a later prompt re-imported "
             "it), restore_failed (corrupt/mismatched entry dropped, "
             "plain prefill ran)."),
            ("tfk8s_serving_kv_peer_fetches_total",
             "Peer-tier prefix pulls, by outcome (ok = warm pages "
             "imported; fallback = any HandoffError, plain prefill "
             "ran)."),
        ):
            self.metrics.describe(name, help_text)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "DecodeLoopExecutor":
        self._thread = threading.Thread(
            target=self._loop, name="decode-loop", daemon=True
        )
        self._thread.start()
        return self

    def drain(self, timeout: float = 30.0) -> bool:
        """Stop accepting, finish every queued AND live request, stop the
        loop. Returns True when everything drained inside ``timeout``."""
        deadline = time.monotonic() + timeout
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        while time.monotonic() < deadline:
            with self._cond:
                if not self._q and not self._live:
                    break
            time.sleep(0.005)
        with self._cond:
            drained = not self._q and not self._live
            self._stopped = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        return drained

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._q)

    @property
    def live_slots(self) -> int:
        with self._cond:
            return self._live

    @property
    def mean_batch_occupancy(self) -> float:
        """Mean LIVE slots per decode iteration — the continuous-batching
        analogue of requests-per-batch."""
        return (
            self._occupancy_sum / self.batches_total
            if self.batches_total else 0.0
        )

    # -- client side --------------------------------------------------------

    def submit(self, payload: Any, timeout: Optional[float] = 30.0,
               traceparent: Optional[str] = None, tenant: str = "",
               priority: int = 0, kv_peer: str = "") -> Any:
        """Blocking request; raises Overloaded / Draining / InvalidRequest
        / RequestFailed / DeadlineExceeded — the :class:`ModelServer`
        contract. Returns ``{"tokens": [...], "version": ...}`` with the
        generated continuation (ending at eos or the budget). A
        ``traceparent`` makes the request TRACED: the loop collects its
        per-token timeline and retires it as a ``serve.request`` span
        under that parent; tenant/priority label its TTFT/TPOT."""
        try:
            parts = self.model.validate(payload)
        except InvalidRequest:
            self.metrics.inc(
                "tfk8s_serving_requests_total", 1.0,
                {**self.labels, "outcome": "invalid"},
            )
            raise
        # test doubles may still speak the historical 2-tuple contract
        tokens, gen = parts[0], parts[1]
        sampling = parts[2] if len(parts) > 2 else None
        if self._chaos_delay_s:
            time.sleep(self._chaos_delay_s)  # gray replica: alive but slow
        req = _GenRequest(
            tokens=tokens, gen_budget=gen, enqueue_t=time.perf_counter(),
            traceparent=traceparent or "", tenant=tenant,
            priority=int(priority), wall_start=time.time(),
            sampling=sampling, kv_peer=kv_peer or "",
        )
        return self._enqueue_and_wait(req, timeout)

    def submit_prefill(self, payload: Any, timeout: Optional[float] = 30.0,
                       traceparent: Optional[str] = None, tenant: str = "",
                       priority: int = 0, kv_peer: str = "") -> Any:
        """Prefill-pool entry point (disaggregated serving): run chunked
        prefill to completion, pick the FIRST output token, export the
        warm KV, and retire — same typed contract as :meth:`submit`, but
        the result additionally carries ``{"handoff":
        KVHandoffBuffer}`` for the gateway to move to a decode replica.
        The request's real generation budget rides the buffer
        (``decode_budget``); THIS replica only ever holds the row for
        one output token."""
        try:
            parts = self.model.validate(payload)
        except InvalidRequest:
            self.metrics.inc(
                "tfk8s_serving_requests_total", 1.0,
                {**self.labels, "outcome": "invalid"},
            )
            raise
        tokens, gen = parts[0], parts[1]
        sampling = parts[2] if len(parts) > 2 else None
        if self._chaos_delay_s:
            time.sleep(self._chaos_delay_s)
        req = _GenRequest(
            tokens=tokens, gen_budget=1, enqueue_t=time.perf_counter(),
            traceparent=traceparent or "", tenant=tenant,
            priority=int(priority), wall_start=time.time(),
            prefill_only=True, decode_budget=gen, sampling=sampling,
            kv_peer=kv_peer or "",
        )
        return self._enqueue_and_wait(req, timeout)

    def submit_handoff(self, buf: KVHandoffBuffer,
                       timeout: Optional[float] = 30.0,
                       traceparent: Optional[str] = None, tenant: str = "",
                       priority: int = 0, sampling: Any = None) -> Any:
        """Decode-pool entry point (disaggregated serving): admit a row
        whose prefill already happened elsewhere. The buffer's K/V pages
        land in freshly drawn local pages (prefix-cached pages are NOT
        re-copied), the slot seeds at position ``len(tokens)`` with the
        prefill replica's pick, and decoding continues bit-identically
        to a local prefill. Raises :class:`HandoffError` on a buffer
        this replica cannot import (wrong page size / model version /
        integrity failure); otherwise the :meth:`submit` contract.
        ``sampling`` re-applies the request's original sampling knobs on
        the decode side (the buffer carries tokens/KV only) — the same
        seed + absolute-position fold makes the continued stream
        bit-identical to a single-replica sampled run."""
        import numpy as np

        buf.verify()
        if buf.page_size != self.model.page_size:
            raise HandoffError(
                f"buffer page_size={buf.page_size}, replica runs "
                f"{self.model.page_size}"
            )
        if buf.version != self.model.version:
            raise HandoffError(
                f"buffer prefilled under {buf.version!r}, replica serves "
                f"{self.model.version!r} — params differ, refusing a "
                f"non-bit-identical import"
            )
        tokens = np.asarray(buf.tokens, np.int32)
        gen = int(buf.gen_budget)
        if gen < 1:
            raise InvalidRequest(f"gen_budget must be >= 1, got {gen}")
        if len(tokens) + gen > self.model.max_len:
            raise InvalidRequest(
                f"prompt of {len(tokens)} + {gen} generated tokens "
                f"exceeds max_len={self.model.max_len}"
            )
        if self._chaos_delay_s:
            time.sleep(self._chaos_delay_s)
        req = _GenRequest(
            tokens=tokens, gen_budget=gen, enqueue_t=time.perf_counter(),
            traceparent=traceparent or "", tenant=tenant,
            priority=int(priority), wall_start=time.time(),
            handoff=buf,
            sampling=sampling if isinstance(sampling, tuple)
            else _parse_sampling(sampling),
        )
        return self._enqueue_and_wait(req, timeout)

    def _enqueue_and_wait(self, req: _GenRequest,
                          timeout: Optional[float]) -> Any:
        """The shared back half of every submit flavor: bounded-queue
        admission, deadline wait, typed re-raise."""
        with self._cond:
            if self._fault is not None:
                raise ReplicaUnavailable(f"replica failed: {self._fault}")
            if self._draining or self._stopped:
                raise Draining("replica is draining; retry another replica")
            if len(self._q) >= self.queue_limit:
                self.rejected_total += 1
                self.metrics.inc(
                    "tfk8s_serving_requests_total", 1.0,
                    {**self.labels, "outcome": "rejected"},
                )
                raise Overloaded(len(self._q), self.queue_limit)
            self._q.append(req)
            self.metrics.set_gauge(
                "tfk8s_serving_queue_depth", float(len(self._q)), self.labels
            )
            self._sched_gauges_locked()
            self._cond.notify_all()
        if not req.done.wait(timeout):
            timed_out = False
            with self._cond:
                try:
                    self._q.remove(req)
                    timed_out = True
                    self.metrics.inc(
                        "tfk8s_serving_requests_total", 1.0,
                        {**self.labels, "outcome": "timeout"},
                    )
                    self.metrics.set_gauge(
                        "tfk8s_serving_queue_depth", float(len(self._q)),
                        self.labels,
                    )
                    self._sched_gauges_locked()
                except ValueError:
                    pass  # already admitted into a slot; it will finish
            if timed_out and req.traceparent:
                _trace.get_tracer().record_span(
                    "serve.request", req.wall_start, time.time(),
                    traceparent=req.traceparent, status="error",
                    attributes={"outcome": "timeout",
                                "tenant": req.tenant,
                                "priority": req.priority},
                )
            raise DeadlineExceeded(f"request not served within {timeout}s")
        if req.error is not None:
            if isinstance(req.error, ServeError):
                # already typed (RowFault, ReplicaUnavailable, ...):
                # surface AS IS — retriability must survive the hop
                raise req.error
            raise RequestFailed(str(req.error)) from req.error
        return req.result

    # -- the decode loop ----------------------------------------------------

    def _sched_gauges_locked(self) -> None:
        """Per-priority-class queue depth gauges. Classes seen once keep
        reporting (at zero) so a drained class doesn't leave a stale
        last value on the scrape."""
        depths = self._q.class_depths()
        self._known_priorities.update(depths)
        for p in self._known_priorities:
            self.metrics.set_gauge(
                "tfk8s_sched_queue_depth", float(depths.get(p, 0)),
                {**self.labels, "priority": str(p)},
            )

    def _admit_locked(self) -> List[_Slot]:
        """Move queued requests into free slots while the page pool covers
        them. Order is the scheduler's pick — FIFO by default (a stalled
        head blocks later admissions so a stream of small requests can't
        starve a big one), or the aged priority-weighted pick. Under the
        priority policy, a pick that stalls on pages may PREEMPT a
        lower-priority live row: its KV spills to a host-side buffer
        (the handoff serialize path), its request re-enters at the front
        of its class, and admission retries with the freed pages. Caller
        holds the lock."""
        from tfk8s_tpu.runtime.paging import OutOfPages

        admitted: List[_Slot] = []
        while self._live < len(self._slots):
            req = self._q.peek()
            if req is None:
                break
            try:
                if req.handoff is not None:
                    # handoff rows (disagg import OR preemption restore)
                    # draw their prompt pages NOW so the imported K/V
                    # has somewhere to land before step 1; the buffer's
                    # gen_budget is the REMAINING budget after any
                    # already-emitted tokens
                    lease = self.allocator.import_pages(
                        req.tokens, req.handoff.gen_budget
                    )
                else:
                    # KV economy: climb the tiers (host restore, then a
                    # directory-hinted peer fetch) BEFORE admit, so a
                    # warm prefix lands as an ordinary device hit; a
                    # no-op with the tiers off
                    self._kv_promote_locked(req)
                    lease = self.allocator.admit(req.tokens, req.gen_budget)
            except OutOfPages:
                if self._preemption and self._maybe_preempt_locked(req):
                    continue  # pages freed (or victim failed); retry
                break  # admission stalls; retirements will free pages
            self._q.pop(req)
            if lease.cached_pages:
                self.metrics.inc(
                    "tfk8s_serving_prefix_cache_hits_total", 1.0, self.labels
                )
            elif self.allocator.prefix_cache_enabled:
                self.metrics.inc(
                    "tfk8s_serving_prefix_cache_misses_total", 1.0,
                    self.labels,
                )
            req.cached_pages = lease.cached_pages
            req.dequeue_t = time.perf_counter()
            idx = self._slots.index(None)
            slot = _Slot(req=req, lease=lease, idx=idx)
            self._slots[idx] = slot
            self._live += 1
            admitted.append(slot)
        if admitted:
            self.metrics.set_gauge(
                "tfk8s_serving_queue_depth", float(len(self._q)), self.labels
            )
            self._sched_gauges_locked()
        return admitted

    def _maybe_preempt_locked(self, req: _GenRequest) -> bool:
        """A higher-priority admission stalled on pages: evict the
        lowest-priority live row strictly below the stalled request's
        class (youngest first — least sunk cost), spilling its KV to a
        host buffer and requeueing it at the front of its class. Returns
        True when a victim was evicted (the admission loop retries),
        False when no eligible victim exists (the admission stalls, the
        pre-preemption behavior). A spill failure fails the VICTIM typed
        (:class:`Preempted`) with its pages quarantined — still True:
        the slot is free either way. Caller holds the lock (this runs on
        the loop thread inside the admission pass, so no step is in
        flight while rows move)."""
        from tfk8s_tpu.runtime.sched.scheduler import pick_victim

        victim = pick_victim(self._slots, int(req.priority))
        if victim is None:
            return False
        try:
            self._spill_locked(victim)
        except BaseException as e:  # noqa: BLE001 — contain to the victim
            vreq = victim.req
            self.allocator.quarantine(victim.lease)
            self._slots[victim.idx] = None
            self._live -= 1
            self._state_dirty = True
            self.preempted_total += 1
            self.metrics.inc(
                "tfk8s_sched_preemptions_total", 1.0,
                {**self.labels, "reason": "spill_failed"},
            )
            self.metrics.inc(
                "tfk8s_serving_requests_total", 1.0,
                {**self.labels, "outcome": "error"},
            )
            log.warning("preemption spill failed, victim request lost: %s", e)
            vreq.error = Preempted(f"KV spill failed mid-preemption: {e}")
            vreq.done.set()
            return True
        self.preempted_total += 1
        self.metrics.inc(
            "tfk8s_sched_preemptions_total", 1.0,
            {**self.labels, "reason": "page_pressure"},
        )
        return True

    def _spill_locked(self, victim: _Slot) -> None:
        """Serialize a live row's whole KV state into a
        :class:`KVHandoffBuffer` riding its own request, free its pages
        and slot, and requeue it at the front of its priority class. The
        restore is the existing handoff-import admission path, so a
        resumed row continues BIT-IDENTICAL to an unpreempted run: the
        resident tokens (prompt + all-but-last emitted) become the
        buffer's prompt, the last emitted token seeds the decode, and
        the buffer's gen_budget is the remaining budget. The resident
        stream is rebuilt from the ORIGINAL prompt every time —
        ``req.tokens`` absorbs emitted tokens on restore, so a second
        spill concatenating ``req.tokens + out[:-1]`` would duplicate
        them (wrong positions, digest chain, and KV extent)."""
        req = victim.req
        if req.preempt_count == 0:
            # req.tokens is still the pristine prompt only BEFORE the
            # first spill rewrites it below
            req.prompt_len = len(req.tokens)
        resident = [int(t) for t in req.tokens[:req.prompt_len]] + [
            int(t) for t in req.out[:-1]
        ]
        last = int(req.out[-1])
        page_ids, digests = self.allocator.export_pages(victim.lease, resident)
        buf = KVHandoffBuffer(
            version=self.model.version, page_size=self.model.page_size,
            tokens=resident, last_token=last,
            # remaining budget: len(req.out) already emitted, and the
            # buffer's last_token re-enters req.out on restore
            gen_budget=req.gen_budget - len(req.out) + 1,
            digests=digests,
            kv=self.model.export_kv(page_ids),
        )
        import numpy as np

        req.out.pop()  # re-enters as buf.last_token on restore
        req.handoff = buf
        req.tokens = np.asarray(resident, np.int32)
        req.preempt_count += 1
        self.allocator.release(victim.lease)
        self._slots[victim.idx] = None
        self._live -= 1
        self._state_dirty = True
        self._q.requeue_front(req)
        self._sched_gauges_locked()

    # -- KV economy (runtime/kvtier) ----------------------------------------

    def _kv_on_device_evict(self, key: str, pid: int) -> None:
        """``PageAllocator.on_evict``: the device tier is dropping an
        idle cached page. Always accounts the eviction (the ISSUE-17
        bugfix — these drops used to be invisible); with a host budget,
        demotes the longest still-resident chain through the evicting
        page before it disappears. Runs inside ``_evict_idle`` under the
        executor lock — reads the allocator, never mutates it."""
        self.metrics.inc(
            "tfk8s_serving_prefix_cache_evictions_total", 1.0,
            {**self.labels, "tier": "device"},
        )
        if self._kv_host is None:
            return
        info = self._kv_chains.get(key)
        if info is None:
            return
        from tfk8s_tpu.runtime.paging import prefix_digest_chain

        toks, m = info
        ps = self.model.page_size
        digests = prefix_digest_chain(toks, ps, m)
        pages = self.allocator.cached_chain(digests)
        r = len(pages)
        if r == 0 or self._kv_host.has(digests[r - 1]):
            # the chain's head already evicted (a later page of an
            # already-demoted chain), or the host holds it — either way
            # there is nothing new to park
            return
        try:
            wire = KVHandoffBuffer.prefix(
                version=self.model.version, page_size=ps,
                tokens=toks[:r * ps], digests=digests[:r],
                kv=self.model.export_kv(pages),
            ).to_bytes()
        except HandoffError as e:
            log.warning("kv host demotion failed, chain dropped: %s", e)
            return
        if self._kv_host.put(digests[r - 1], wire, akey=digests[0]):
            self.metrics.inc(
                "tfk8s_serving_kv_host_ops_total", 1.0,
                {**self.labels, "op": "demote"},
            )

    def _kv_on_host_evict(self, key: str, nbytes: int) -> None:
        """Host-tier LRU overflow: the byte budget pushed a chain out of
        its last tier. Same eviction counter, ``tier="host"``."""
        self.metrics.inc(
            "tfk8s_serving_prefix_cache_evictions_total", 1.0,
            {**self.labels, "tier": "host"},
        )

    def _kv_note_chain(self, tokens: Any) -> None:
        """Remember the tokens behind a registered prefix chain so the
        demotion path can rebuild (and re-hash) the chain when one of
        its pages evicts — ``register_prefix`` itself only keeps
        digests. Bounded: entries for chains no tier still holds are
        pruned once the map outgrows the pool."""
        if self._kv_host is None:
            return
        from tfk8s_tpu.runtime.paging import prefix_digest_chain

        ps = self.model.page_size
        m = max(len(tokens) - 1, 0) // ps  # register_prefix's k_max
        if m == 0:
            return
        toks = [int(t) for t in tokens[:m * ps]]
        for d in prefix_digest_chain(toks, ps, m):
            prev = self._kv_chains.get(d)
            if prev is None or prev[1] < m:
                self._kv_chains[d] = (toks, m)
        if len(self._kv_chains) > 16 * self.allocator.num_pages:
            held = set(self.allocator.cached_keys())
            self._kv_chains = {
                d: v for d, v in self._kv_chains.items()
                if d in held or self._kv_host.has(d)
            }

    def _kv_promote_locked(self, req: _GenRequest) -> None:
        """Admission-time tier climb: before a request admits, pull its
        prefix UP the tiers — host restore first (local, cheap), then a
        directory-hinted peer fetch — so :meth:`PageAllocator.admit`
        sees a plain device hit. Every failure shape degrades to plain
        prefill; this method never raises. Caller holds the lock (loop
        thread, admission pass — no step in flight)."""
        want_peer = bool(self._kv_peer_fetch and req.kv_peer)
        if self._kv_host is None and not want_peer:
            return
        from tfk8s_tpu.runtime.paging import prefix_digest_chain

        peer_hint, req.kv_peer = req.kv_peer, ""  # one attempt, ever
        tokens = req.tokens
        ps = self.model.page_size
        k_max = max(len(tokens) - 1, 0) // ps
        if k_max == 0:
            return
        digests = prefix_digest_chain(tokens, ps, k_max)
        d = len(self.allocator.cached_chain(digests))
        if d >= k_max:
            return  # full device hit already — nothing to climb for
        if self._kv_host is not None:
            for j in range(k_max, d, -1):
                t0 = time.perf_counter()
                try:
                    # get() raises on a checksum mismatch (host-RAM
                    # corruption) — same fallback as a failed adopt
                    wire = self._kv_host.get(digests[j - 1])
                    if wire is None:
                        continue
                    self._kv_adopt_locked(
                        KVHandoffBuffer.from_bytes(wire), digests, d, j
                    )
                except HandoffError as e:
                    # corrupt or unlandable entry: drop it (never offer
                    # it twice) and fall through to peer/plain prefill
                    self._kv_host.discard(digests[j - 1])
                    self.metrics.inc(
                        "tfk8s_serving_kv_host_ops_total", 1.0,
                        {**self.labels, "op": "restore_failed"},
                    )
                    log.warning("kv host restore failed, prefilling: %s", e)
                    break
                self._kv_host.restores += 1
                self._kv_restore_ms.append(
                    (time.perf_counter() - t0) * 1000.0
                )
                self.metrics.inc(
                    "tfk8s_serving_kv_host_ops_total", 1.0,
                    {**self.labels, "op": "restore"},
                )
                self._kv_note_chain(tokens)
                return
        if want_peer:
            from tfk8s_tpu.runtime import kvtier

            try:
                buf = kvtier.fetch_prefix(
                    self._kv_resolve or lookup_replica, peer_hint,
                    tokens, transport=self._kv_transport,
                )
                j = min(len(buf.tokens) // ps, k_max)
                if j <= d:
                    raise HandoffError(
                        "peer prefix no longer than the local one"
                    )
                self._kv_adopt_locked(buf, digests, d, j)
            except HandoffError as e:
                self.metrics.inc(
                    "tfk8s_serving_kv_peer_fetches_total", 1.0,
                    {**self.labels, "outcome": "fallback"},
                )
                log.info("kv peer fetch from %s fell back to prefill: %s",
                         peer_hint, e)
            else:
                self.metrics.inc(
                    "tfk8s_serving_kv_peer_fetches_total", 1.0,
                    {**self.labels, "outcome": "ok"},
                )
                self._kv_note_chain(tokens)

    def _kv_adopt_locked(self, buf: KVHandoffBuffer, digests: List[str],
                         start: int, upto: int) -> None:
        """Warm-insert a verified prefix buffer into the idle device
        cache: draw pages for chain positions ``start..upto-1``, scatter
        the buffer's K/V rows into them, publish them under their
        digests — the admission that follows sees a plain device hit
        (same pages, same bytes: bit-identity by construction). Raises
        :class:`HandoffError` when the buffer cannot land here."""
        ps = self.model.page_size
        if buf.page_size != ps:
            raise HandoffError(
                f"buffer page_size={buf.page_size}, replica runs {ps}"
            )
        if buf.version != self.model.version:
            raise HandoffError(
                f"buffer from {buf.version!r}, replica serves "
                f"{self.model.version!r} — params differ"
            )
        if len(buf.tokens) < upto * ps:
            raise HandoffError(
                f"buffer covers {len(buf.tokens)} token(s), chain needs "
                f"{upto * ps}"
            )
        ticket = self.allocator.restore_begin(digests[:upto], start)
        if ticket is None:
            raise HandoffError("live leases own the pool — cannot restore")
        try:
            self.model.import_kv(
                [leaf[start * ps:upto * ps] for leaf in buf.kv],
                ticket.pages,
            )
        except BaseException as e:  # noqa: BLE001 — roll back, degrade
            self.allocator.restore_abort(ticket)
            if isinstance(e, HandoffError):
                raise
            raise HandoffError(f"restore scatter failed: {e}") from e
        self.allocator.restore_commit(ticket)

    def export_prefix(self, tokens: Any) -> Optional[KVHandoffBuffer]:
        """Peer-tier export: the longest warm prefix of ``tokens`` this
        replica holds, as a verified prefix buffer — device chain first
        (gathered straight from the pool), host tier second (the parked
        wire bytes deserialize back). ``None`` when neither tier has
        it. Called by PEER replicas through
        :func:`tfk8s_tpu.runtime.kvtier.fetch_prefix`; the gather is
        read-only, so a foreign-thread export never perturbs the loop."""
        from tfk8s_tpu.runtime.paging import prefix_digest_chain

        toks = [int(t) for t in tokens]
        ps = self.model.page_size
        k_max = max(len(toks) - 1, 0) // ps
        if k_max == 0:
            return None
        digests = prefix_digest_chain(toks, ps, k_max)
        # BOUNDED acquire, not ``with``: the caller is another replica's
        # admission path holding ITS loop lock — two replicas hinted at
        # each other must degrade to a fallback prefill, not deadlock
        if not self._cond.acquire(timeout=1.0):
            return None
        try:
            if self._fault is not None or self._stopped:
                return None
            pages = self.allocator.cached_chain(digests)
            if pages:
                r = len(pages)
                try:
                    buf = KVHandoffBuffer.prefix(
                        version=self.model.version, page_size=ps,
                        tokens=toks[:r * ps], digests=digests[:r],
                        kv=self.model.export_kv(pages),
                    )
                except HandoffError:
                    return None
                self.kv_peer_serves += 1
                return buf
            if self._kv_host is not None:
                for j in range(k_max, 0, -1):
                    try:
                        wire = self._kv_host.get(digests[j - 1])
                        if wire is None:
                            continue
                        buf = KVHandoffBuffer.from_bytes(wire)
                    except HandoffError:
                        self._kv_host.discard(digests[j - 1])
                        return None
                    self.kv_peer_serves += 1
                    return buf
        finally:
            self._cond.release()
        return None

    def kv_digest_report(self, limit: int = 512) -> Dict[str, Any]:
        """The cache directory's per-replica digest summary (periodic
        gateway poll — the /debug/routes hit/miss plumbing generalized):
        device-resident cache keys (most-recent tail) plus the affinity
        keys of host-tier entries, with occupancy and hit/miss/eviction
        counts riding along for /debug/routes."""
        with self._cond:
            digests = self.allocator.cached_keys(limit=limit)
            host = None
            if self._kv_host is not None:
                digests.extend(self._kv_host.akeys())
                host = self._kv_host.stats()
            return {
                "digests": digests,
                "host": host,
                "prefix_cache": {
                    "hits": self.allocator.prefix_hits,
                    "misses": self.allocator.prefix_misses,
                    "evictions": self.allocator.evictions,
                },
            }

    def _loop(self) -> None:
        while True:
            with self._cond:
                while True:
                    if self._stopped and not self._q and not self._live:
                        return
                    admitted = self._admit_locked()
                    if admitted or self._live:
                        break
                    self._cond.wait(0.5)
            try:
                if admitted:
                    self._prefill_admitted(admitted)
                if self._live:
                    self._decode_once()
            except BaseException as e:  # noqa: BLE001 — a GLOBAL fault:
                # per-row faults were already contained inside the step
                # (_retire_failed); anything that escapes means the
                # device itself is unusable — fail the world and exit
                # non-Ready so the serve controller replaces the replica
                self._fatal(e)
                return
            self._update_occupancy_gauges()

    def _pages_for(self, slot: _Slot, upto_tokens: int) -> None:
        """Grow the slot's page table to cover ``upto_tokens`` positions
        (drawn from the lease's admission-time reservation)."""
        ps = self.model.page_size
        while len(slot.lease.pages) * ps < upto_tokens:
            self.allocator.extend(slot.lease)

    def _prefill_samp(self, pending, rows: int):
        """Per-row sampling knobs for one prefill round — None when every
        pending row is greedy (keeps the original compiled program on
        the pure-greedy path, bit-identical)."""
        import numpy as np

        if not any(e[0].req.sampling for e in pending):
            return None
        samp_f = np.zeros((rows, 2), np.float32)
        samp_f[:, 1] = 1.0  # top_p disabled by default
        samp_i = np.zeros((rows, 2), np.int32)
        for entry in pending:
            slot = entry[0]
            if slot.req.sampling is None:
                continue
            t, k, p, s = slot.req.sampling
            r = 0 if rows == 1 else slot.idx
            samp_f[r] = (t, p)
            samp_i[r] = (k, s)
        return samp_f, samp_i

    def _slot_samp(self):
        """Per-row sampling knobs for the decode/verify dispatch, aligned
        to the slot bank — None when every live row is greedy."""
        import numpy as np

        if not any(
            s is not None and s.req.sampling for s in self._slots
        ):
            return None
        n = len(self._slots)
        samp_f = np.zeros((n, 2), np.float32)
        samp_f[:, 1] = 1.0
        samp_i = np.zeros((n, 2), np.int32)
        for i, slot in enumerate(self._slots):
            if slot is None or slot.req.sampling is None:
                continue
            t, k, p, s = slot.req.sampling
            samp_f[i] = (t, p)
            samp_i[i] = (k, s)
        return samp_f, samp_i

    def _prefill_admitted(self, admitted: List[_Slot]) -> None:
        """Batched chunked prefill: every admitted request's NEXT prompt
        slice rides one ``[slots, C]`` dispatch (gpt.prefill_step_packed)
        — an admission burst costs one dispatch per chunk round, not one
        per request. A cached prefix skips its pages entirely (prefill
        starts at the first uncovered position); a finishing row's first
        output token is its pick at the last real prompt position."""
        import numpy as np

        # Handoff rows (disaggregated serving) skip prefill entirely:
        # their K/V arrives in the buffer and lands by page copy. A
        # buffer that fails import indicts THAT row only — retire it
        # typed with its pages quarantined (they may hold a partial
        # foreign write), siblings untouched.
        imports = [s for s in admitted if s.req.handoff is not None]
        admitted = [s for s in admitted if s.req.handoff is None]
        for slot in imports:
            try:
                self._import_handoff(slot)
            except HandoffError as e:
                self._retire_failed(
                    slot, RowFault(f"handoff import failed: {e}")
                )

        n, mpp = len(self._slots), self.model.pages_per_slot
        chunk_len, ps = self.model.prefill_chunk, self.model.page_size
        # Draw the WHOLE lease up front (admission already reserved it,
        # so this denies nobody anything): the page table then never
        # grows mid-decode and the packed step state stays clean —
        # rebuilds only on admission/retirement.
        for slot in admitted:
            self._pages_for(
                slot, len(slot.req.tokens) + max(slot.req.gen_budget, 1)
            )
        # (slot, next chunk base); cached pages are already covered
        pending = [
            [slot, slot.lease.cached_pages * ps] for slot in admitted
        ]
        while pending:
            # a SINGLE pending request (the steady-state trickle: one
            # retirement frees one slot) rides a [1, C] dispatch — a
            # full [slots, C] round would burn slots× the compute for
            # one row; admission bursts batch at full width. Two
            # compiled prefill shapes total.
            rows = 1 if len(pending) == 1 else n
            batch = np.zeros((rows, chunk_len + 1 + mpp), np.int32)
            finishing: List[Tuple[_Slot, int, int]] = []
            for entry in pending:
                slot, base = entry
                slot.req.prefill_chunks += 1
                tokens, plen = slot.req.tokens, len(slot.req.tokens)
                end = min(base + chunk_len, plen)
                self._pages_for(slot, end)
                r = 0 if rows == 1 else slot.idx
                row = batch[r]
                row[: end - base] = tokens[base:end]
                row[chunk_len] = base
                row[chunk_len + 1: chunk_len + 1 + len(slot.lease.pages)] = (
                    slot.lease.pages
                )
                if end >= plen:
                    finishing.append((slot, r, plen - 1 - base))
                entry[1] = end
            samp = self._prefill_samp(pending, rows)
            # keep the 1-arg call when every row is greedy: test doubles
            # (and the draft mirror) override prefill_batch(batch)
            picks = (
                self.model.prefill_batch(batch) if samp is None
                else self.model.prefill_batch(batch, samp)
            )
            if self._spec is not None:
                # mirror the dispatch into the draft pool: same packed
                # rows, same page ids — the draft's prompt K/V must be
                # resident before its first proposal round
                self._spec.prefill_batch(batch)
            now = time.perf_counter()
            for slot, r, pick_idx in finishing:
                req = slot.req
                first_tok = int(picks[r, pick_idx])
                self.allocator.register_prefix(req.tokens, slot.lease)
                self._kv_note_chain(req.tokens)
                slot.position = len(req.tokens)
                slot.last_token = first_tok
                if self._spec is not None:
                    slot.spec_chunk = [first_tok]
                req.out.append(first_tok)
                req.first_token_t = now
                self.tokens_total += 1
                self.metrics.inc(
                    "tfk8s_serving_tokens_total", 1.0, self.labels
                )
                if req.prefill_only:
                    # export BEFORE retire frees the lease's pages: the
                    # decode pool gets the warm K/V plus the pick
                    page_ids, digests = self.allocator.export_pages(
                        slot.lease, req.tokens
                    )
                    req.exported = KVHandoffBuffer(
                        version=self.model.version, page_size=ps,
                        tokens=[int(t) for t in req.tokens],
                        last_token=first_tok,
                        gen_budget=req.decode_budget,
                        digests=digests,
                        kv=self.model.export_kv(page_ids),
                    )
                    self.metrics.inc(
                        "tfk8s_disagg_exports_total", 1.0, self.labels
                    )
                if len(req.out) >= req.gen_budget or (
                    self.model.eos_id is not None
                    and first_tok == self.model.eos_id
                ):
                    self._retire(slot)
            pending = [e for e in pending if e[1] < len(e[0].req.tokens)]
        self._state_dirty = True  # admitted rows changed under the state

    def _import_handoff(self, slot: _Slot) -> None:
        """Admit a prefilled-elsewhere row: copy the buffer's K/V into
        the locally drawn prompt pages (prefix-cached pages are already
        resident — only the uncovered tail copies), seed the slot at the
        prompt's end with the prefill replica's pick, and let the next
        decode step continue bit-identically to a local prefill."""
        req = slot.req
        buf = req.handoff
        ps = self.model.page_size
        plen = len(req.tokens)
        # whole lease up front, like the prefill path: the page table
        # never grows mid-decode. The BUFFER's gen_budget bounds the draw
        # — for a preemption restore it is the REMAINING budget, which is
        # exactly what import_pages reserved.
        self._pages_for(slot, plen + max(buf.gen_budget, 1))
        n_prompt = -(-plen // ps)
        dst = slot.lease.pages[slot.lease.cached_pages:n_prompt]
        if dst:
            row0 = slot.lease.cached_pages * ps
            self.model.import_kv(
                [leaf[row0:n_prompt * ps] for leaf in buf.kv], dst
            )
        self.allocator.register_prefix(req.tokens, slot.lease)
        self._kv_note_chain(req.tokens)
        slot.position = plen
        slot.last_token = buf.last_token
        if self._spec is not None:
            # the draft never saw this KV (it arrived as a buffer):
            # rebuild its prompt KV from the tokens, then let the normal
            # catch-up chunk handle the seeded last token
            self._spec.prefill_tokens(
                [int(t) for t in req.tokens], list(slot.lease.pages)
            )
            slot.spec_chunk = [int(buf.last_token)]
        req.out.append(buf.last_token)
        if req.preempt_count:
            # a preemption restore on THIS replica: the row already
            # emitted output here, so its original first_token_t stands
            # (TTFT/TPOT stay anchored to the real first token) and the
            # import counts as a scheduler restore, not a disagg handoff
            self.restored_total += 1
            self.metrics.inc(
                "tfk8s_sched_restores_total", 1.0, self.labels
            )
        else:
            # the first token was generated (and counted in the token
            # metrics) on the PREFILL replica; importing it emits nothing
            req.first_token_t = time.perf_counter()
            self.metrics.inc("tfk8s_disagg_imports_total", 1.0, self.labels)
        if len(req.out) >= req.gen_budget or (
            self.model.eos_id is not None
            and buf.last_token == self.model.eos_id
        ):
            self._retire(slot)

    def _rebuild_state(self) -> None:
        """Re-materialize the packed step state from the slot mirrors —
        only after admission/retirement/page growth; steady-state steps
        feed the previous output state straight back. Kept as NUMPY: the
        jit converts it on its internal C++ path, measured ~3.5x cheaper
        than an explicit device_put."""
        import numpy as np

        n = len(self._slots)
        state = np.zeros((n, 2 + self.model.pages_per_slot), np.int32)
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue  # zeros: trash page, position 0 — inert by design
            state[i, 0] = slot.last_token
            state[i, 1] = slot.position
            state[i, 2: 2 + len(slot.lease.pages)] = slot.lease.pages
        self._d_state = state
        self._d_samp = self._slot_samp()
        self._state_dirty = False

    def _decode_once(self) -> None:
        if self._spec is not None:
            self._decode_spec_once()
            return
        live = []
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            before = len(slot.lease.pages)
            self._pages_for(slot, slot.position + 1)
            if len(slot.lease.pages) != before:
                self._state_dirty = True  # page-table row grew
            live.append(i)
        if self._state_dirty:
            self._rebuild_state()
        # keep the 1-arg call when every row is greedy: test doubles
        # override decode(state) with the original arity
        nxt_dev, state_dev = (
            self.model.decode(self._d_state) if self._d_samp is None
            else self.model.decode(self._d_state, self._d_samp)
        )
        import numpy as np

        nxt = np.asarray(nxt_dev)  # the one per-step device sync
        self._d_state = state_dev
        self.batches_total += 1
        self._occupancy_sum += len(live)
        self.metrics.inc("tfk8s_serving_batches_total", 1.0, self.labels)
        self.metrics.set_gauge(
            "tfk8s_serving_batch_occupancy", self.mean_batch_occupancy,
            self.labels,
        )
        step_t = time.perf_counter()  # one stamp per step, shared by rows
        emitted = 0
        for i in live:
            slot = self._slots[i]
            if slot is None:
                continue  # a chaos crash raced the step and cleared it
            tok = int(nxt[i])
            if self._chaos_poison:
                tok = self._apply_chaos_poison(slot, tok)
            if tok < 0 or (
                self._vocab_bound is not None and tok >= self._vocab_bound
            ):
                # crash containment: a malformed continuation indicts
                # THIS row's state only — retire it typed, quarantine
                # its pages, keep every sibling row decoding
                self._retire_failed(slot, RowFault(
                    f"row {slot.idx} emitted malformed token {tok} "
                    f"(vocab {self._vocab_bound}) at position "
                    f"{slot.position}; row retired, pages quarantined"
                ))
                continue
            emitted += 1
            slot.position += 1
            slot.last_token = tok
            slot.req.out.append(tok)
            if slot.req.traceparent:
                slot.req.token_times.append(step_t)
            if len(slot.req.out) >= slot.req.gen_budget or (
                self.model.eos_id is not None and tok == self.model.eos_id
            ):
                self._retire(slot)
        self.tokens_total += emitted
        if emitted:
            self.metrics.inc(
                "tfk8s_serving_tokens_total", float(emitted), self.labels
            )

    def _decode_spec_once(self) -> None:
        """One SPECULATIVE iteration: the draft proposes ``k`` tokens per
        live row (catch-up chunk + greedy draft steps, all in the draft's
        own page pool), the target verifies every proposal in ONE packed
        chunk dispatch, and each row emits the longest agreeing prefix
        plus the target's correction token — ``1..k+1`` target-identical
        tokens per iteration instead of exactly one.

        Rows within ``k`` positions of the page-table extent
        (``pages_per_slot * page_size``) sit the round out and take a
        plain single step instead: the verify chunk would otherwise
        scatter K/V past the table and XLA's clamped indexing would
        overwrite the row's own last page (the Pallas-seam accounting —
        see models/transformer.py). Those rows are retiring within ``k``
        tokens anyway."""
        import numpy as np

        k = self._spec.k
        limit = self.model.pages_per_slot * self.model.page_size
        if self._state_dirty:
            self._rebuild_state()
        state = np.asarray(self._d_state)
        spec_rows, tail_rows = [], []
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            if slot.spec_chunk and slot.position + k < limit:
                spec_rows.append(i)
            else:
                tail_rows.append(i)
        self.batches_total += 1
        self._occupancy_sum += len(spec_rows) + len(tail_rows)
        self.metrics.inc("tfk8s_serving_batches_total", 1.0, self.labels)
        self.metrics.set_gauge(
            "tfk8s_serving_batch_occupancy", self.mean_batch_occupancy,
            self.labels,
        )
        emitted_n = 0
        if spec_rows:
            spec_set = set(spec_rows)
            sslots = [
                s if i in spec_set else None
                for i, s in enumerate(self._slots)
            ]
            drafts = self._spec.propose(sslots)
            vstate = state.copy()
            for i in tail_rows:
                vstate[i] = 0  # inert: junk writes land in the trash page
            picks = self.model.verify(vstate, drafts, self._d_samp)
            step_t = time.perf_counter()
            for i in spec_rows:
                emitted_n += self._accept_spec_row(
                    i, drafts[i], picks[i], step_t
                )
            self.metrics.set_gauge(
                "tfk8s_sched_spec_accept_ratio", self._spec.accept_ratio,
                self.labels,
            )
        if tail_rows:
            tstate = state.copy()
            for i in spec_rows:
                tstate[i] = 0
            nxt = np.asarray(
                (self.model.decode(tstate) if self._d_samp is None
                 else self.model.decode(tstate, self._d_samp))[0]
            )
            step_t = time.perf_counter()
            for i in tail_rows:
                slot = self._slots[i]
                if slot is None:
                    continue
                emitted_n += self._accept_spec_row(
                    i, np.zeros(0, np.int32), nxt[i:i + 1], step_t
                )
        # positions advanced by a per-row amount: the packed state must
        # re-materialize before the next iteration either way
        self._state_dirty = True
        self.tokens_total += emitted_n
        if emitted_n:
            self.metrics.inc(
                "tfk8s_serving_tokens_total", float(emitted_n), self.labels
            )

    def _accept_spec_row(self, i: int, drafts, picks, step_t: float) -> int:
        """Accept-prefix for one row: longest ``drafts[j] == picks[j]``
        prefix, then the target's own correction token — truncated to
        the remaining budget and (inclusively) to eos. Returns how many
        tokens the row emitted. Empty ``drafts`` (a tail row's plain
        step) degenerates to emitting ``picks[0]``."""
        slot = self._slots[i]
        if slot is None:
            return 0  # a chaos crash raced the step and cleared it
        req = slot.req
        a = 0
        while a < len(drafts) and int(drafts[a]) == int(picks[a]):
            a += 1
        toks = [int(t) for t in drafts[:a]] + [int(picks[a])]
        if len(drafts):
            self._spec.record(proposed=len(drafts), accepted=a)
        remaining = req.gen_budget - len(req.out)
        toks = toks[:remaining]
        if self.model.eos_id is not None and self.model.eos_id in toks:
            toks = toks[: toks.index(self.model.eos_id) + 1]
        if self._chaos_poison and toks:
            # per emitted token, like the plain path's per-step check —
            # an armed key is one-shot, so exactly one token poisons
            toks = [self._apply_chaos_poison(slot, t) for t in toks]
        for tok in toks:
            if tok < 0 or (
                self._vocab_bound is not None and tok >= self._vocab_bound
            ):
                self._retire_failed(slot, RowFault(
                    f"row {slot.idx} emitted malformed token {tok} "
                    f"(vocab {self._vocab_bound}) at position "
                    f"{slot.position}; row retired, pages quarantined"
                ))
                return 0
        slot.position += len(toks)
        slot.last_token = toks[-1]
        slot.spec_chunk = list(toks)
        req.out.extend(toks)
        if req.traceparent:
            req.token_times.extend([step_t] * len(toks))
        if len(req.out) >= req.gen_budget or (
            self.model.eos_id is not None
            and toks[-1] == self.model.eos_id
        ):
            self._retire(slot)
        return len(toks)

    def _retire(self, slot: _Slot) -> None:
        """Complete a finished request and free its pages — the slot is
        reusable on the NEXT admission pass, mid-batch."""
        now = time.perf_counter()
        req = slot.req
        with self._cond:
            self.allocator.release(slot.lease)
            self._slots[self._slots.index(slot)] = None
            self._live -= 1
            self.served_total += 1
            self._state_dirty = True  # the freed row must stop stepping
        # exemplars attach OPTIMISTICALLY here (the tail verdict isn't in
        # yet): slow/error traces — the ones behind interesting buckets —
        # are always kept, so a high-bucket exemplar stays resolvable
        trace_id = _trace_id_of(req.traceparent)
        self.metrics.inc(
            "tfk8s_serving_requests_total", 1.0,
            {**self.labels, "outcome": "ok"},
        )
        self.metrics.observe(
            "tfk8s_serving_queue_seconds", req.dequeue_t - req.enqueue_t,
            self.labels,
        )
        self.metrics.observe(
            "tfk8s_serving_execute_seconds", now - req.dequeue_t, self.labels
        )
        self.metrics.observe(
            "tfk8s_serving_request_seconds", now - req.enqueue_t, self.labels,
            exemplar=trace_id,
        )
        class_labels = {
            **self.labels, "tenant": req.tenant,
            "priority": str(req.priority),
        }
        if req.first_token_t:
            self.metrics.observe(
                "tfk8s_serving_ttft_seconds",
                req.first_token_t - req.enqueue_t, class_labels,
                exemplar=trace_id,
            )
        if len(req.out) > 1:
            self.metrics.observe(
                "tfk8s_serving_tpot_seconds",
                (now - req.first_token_t) / (len(req.out) - 1),
                class_labels, exemplar=trace_id,
            )
        if req.traceparent:
            self._emit_request_span(req, now)
        req.result = {
            "tokens": list(req.out), "version": self.model.version,
            # first-token latency rides the reply so callers (and the
            # bench) get exact per-request TTFT without scraping buckets
            "ttft_s": round(req.first_token_t - req.enqueue_t, 6)
            if req.first_token_t else None,
        }
        if req.exported is not None:
            # prefill-pool retirement: the warm KV rides the result to
            # the gateway, which moves it across the pool seam
            req.result["handoff"] = req.exported
        req.done.set()

    def _retire_reason(self, req: _GenRequest) -> str:
        if (
            self.model.eos_id is not None and req.out
            and req.out[-1] == self.model.eos_id
        ):
            return "eos"
        return "budget"

    def _emit_request_span(
        self, req: _GenRequest, end_t: float, error: Optional[str] = None
    ) -> None:
        """The per-request timeline, attached as one ``serve.request``
        span under the caller's traceparent: admission wait, prefix-cache
        reuse, prefill chunking, TTFT, a strided sample of per-token
        TPOTs, and the retirement reason."""
        reason = "error" if error is not None else self._retire_reason(req)
        events: List[Dict[str, Any]] = []
        if req.dequeue_t:
            events.append({
                "name": "admitted", "ts": req.wall(req.dequeue_t),
                "attributes": {
                    "queue_wait_s": req.dequeue_t - req.enqueue_t,
                    "cached_pages": req.cached_pages,
                },
            })
        if req.first_token_t:
            events.append({
                "name": "first_token", "ts": req.wall(req.first_token_t),
                "attributes": {
                    "ttft_s": req.first_token_t - req.enqueue_t,
                    "prefill_chunks": req.prefill_chunks,
                },
            })
        times = req.token_times
        if times:
            stride = max(1, len(times) // MAX_TOKEN_EVENTS)
            prev = req.first_token_t or times[0]
            for i, t in enumerate(times):
                if i % stride == 0 or i == len(times) - 1:
                    events.append({
                        "name": "token", "ts": req.wall(t),
                        "attributes": {"i": i + 1, "tpot_s": t - prev},
                    })
                prev = t
        events.append({
            "name": "retire", "ts": req.wall(end_t),
            "attributes": {"reason": reason, "tokens": len(req.out)},
        })
        _trace.get_tracer().record_span(
            "serve.request", req.wall_start, req.wall(end_t),
            traceparent=req.traceparent,
            status="error" if error is not None else "ok",
            attributes={
                "outcome": reason,
                "tenant": req.tenant,
                "priority": req.priority,
                "prompt_tokens": len(req.tokens),
                "tokens_out": len(req.out),
                "cached_pages": req.cached_pages,
                "prefill_chunks": req.prefill_chunks,
                **({"error": error} if error is not None else {}),
            },
            events=events,
        )

    def _retire_failed(self, slot: _Slot, exc: ServeError) -> None:
        """Crash containment: retire ONE faulted row without failing the
        world. Its request fails typed (:class:`RowFault`, a
        RequestFailed), its pages are QUARANTINED — never returned to
        the free list (or the prefix cache) until explicitly verified,
        so a poisoned page can't carry corrupt K/V into a future
        admission — and every sibling row keeps decoding (each row's
        paged attention reads only its own page table, so isolation is
        exact; test-pinned bit-identical siblings)."""
        now = time.perf_counter()
        req = slot.req
        with self._cond:
            held = self.allocator.quarantine(slot.lease)
            self._slots[self._slots.index(slot)] = None
            self._live -= 1
            self._state_dirty = True  # the faulted row must stop stepping
        self.metrics.inc(
            "tfk8s_serving_rows_quarantined_total", 1.0, self.labels
        )
        self.metrics.inc(
            "tfk8s_serving_requests_total", 1.0,
            {**self.labels, "outcome": "error"},
        )
        log.warning("decode row fault (%d page(s) quarantined): %s", held, exc)
        if req.traceparent:
            self._emit_request_span(req, now, error=str(exc))
        req.error = exc
        req.done.set()

    def _fail_all(self, e: BaseException) -> None:
        """A device-step failure poisons every in-flight request (the
        ModelServer batch-failure contract, extended to live slots)."""
        with self._cond:
            victims = [s for s in self._slots if s is not None]
            for slot in victims:
                self.allocator.release(slot.lease)
            self._slots = [None] * len(self._slots)
            self._live = 0
            self._state_dirty = True
        if victims:
            self.metrics.inc(
                "tfk8s_serving_requests_total", float(len(victims)),
                {**self.labels, "outcome": "error"},
            )
            log.warning("decode loop failed %d request(s): %s", len(victims), e)
        now = time.perf_counter()
        for slot in victims:
            slot.req.error = e
            if slot.req.traceparent:
                self._emit_request_span(slot.req, now, error=str(e))
            slot.req.done.set()

    def _fail_queued(self, e: BaseException) -> None:
        """Fail every QUEUED (accepted-but-unstarted) request with ``e``
        — the other half of a whole-replica failure; live slots go
        through :meth:`_fail_all`."""
        with self._cond:
            victims = list(self._q)
            self._q.clear()
            self.metrics.set_gauge(
                "tfk8s_serving_queue_depth", 0.0, self.labels
            )
            self._sched_gauges_locked()
        if victims:
            self.metrics.inc(
                "tfk8s_serving_requests_total", float(len(victims)),
                {**self.labels, "outcome": "error"},
            )
        for req in victims:
            req.error = e
            req.done.set()

    def _fatal(self, e: BaseException) -> None:
        """A genuinely GLOBAL fault (device unusable): mark the replica
        faulted — submits now refuse with retriable
        :class:`ReplicaUnavailable`, ``report_progress`` reports
        non-Ready so the entrypoint exits and the serve controller
        replaces the pod — and fail everything the replica holds with
        the same retriable error (the request rode a dying replica;
        nothing about the request itself is suspect, so the gateway
        re-dispatches it to a survivor)."""
        wrapped = (
            e if isinstance(e, ReplicaUnavailable)
            else ReplicaUnavailable(f"replica failed: {e}")
        )
        if not isinstance(e, ReplicaUnavailable):
            wrapped.__cause__ = e
        with self._cond:
            self._fault = e
            self._cond.notify_all()
        log.error("decode loop fatal fault, replica exiting non-Ready: %s", e)
        self._fail_all(wrapped)
        self._fail_queued(wrapped)

    @property
    def fault(self) -> Optional[BaseException]:
        """The global fault that killed the loop, if any (the serve
        entrypoint polls this and exits non-Ready on it)."""
        return self._fault

    # -- chaos hooks (tests/chaos.py; never on the production path) ----------

    def chaos_crash(self, message: str = "chaos: replica host died") -> None:
        """Simulate the replica's HOST dying mid-generation: every held
        request fails retriable-ReplicaUnavailable, new submits refuse
        with the same, and the replica goes non-Ready. The registry
        entry is NOT removed — a dead host can't unregister; discovery
        (gateway health ejection, stale aging) is what stops traffic."""
        self._fatal(ReplicaUnavailable(message))
        with self._cond:
            self._stopped = True
            self._cond.notify_all()

    def chaos_wire_reset(self, message: str = "chaos: wire reset") -> None:
        """Fail every accepted-but-unanswered request ONCE with
        retriable ReplicaUnavailable — the replica stays healthy and
        keeps serving (a dropped connection, not a dead host)."""
        err = ReplicaUnavailable(message)
        self._fail_all(err)
        self._fail_queued(err)

    def chaos_delay(self, seconds: float) -> None:
        """Gray failure: every subsequent submit stalls ``seconds``
        before enqueueing — alive and correct, but slow (the failure
        mode the gateway's latency-EWMA detector must catch)."""
        self._chaos_delay_s = max(0.0, float(seconds))

    def chaos_poison_row(self, tokens: Any) -> None:
        """Arm a per-row fault: the request whose prompt matches
        ``tokens`` emits a malformed (out-of-vocab) token on its next
        decode step — the hermetic simulation of poisoned pages."""
        self._chaos_poison.add(tuple(int(t) for t in tokens))

    def _apply_chaos_poison(self, slot: _Slot, tok: int) -> int:
        key = tuple(int(t) for t in slot.req.tokens)
        if key in self._chaos_poison:
            self._chaos_poison.discard(key)
            return -1
        return tok

    def _update_occupancy_gauges(self) -> None:
        self.metrics.set_gauge(
            "tfk8s_serving_slot_occupancy",
            self._live / max(len(self._slots), 1), self.labels,
        )
        self.metrics.set_gauge(
            "tfk8s_serving_page_occupancy",
            self.allocator.used_pages / max(self.allocator.num_pages - 1, 1),
            self.labels,
        )

    # -- live introspection (/debug/decode) ---------------------------------

    def debug_state(self) -> Dict[str, Any]:
        """Zpages view of the loop RIGHT NOW: per-slot occupancy (row
        position, page count, progress, owner) and page-pool pressure —
        what ``/debug/decode`` renders per replica."""
        with self._cond:
            slots: List[Optional[Dict[str, Any]]] = []
            for slot in self._slots:
                if slot is None:
                    slots.append(None)
                    continue
                req = slot.req
                slots.append({
                    "position": slot.position,
                    "pages": len(slot.lease.pages),
                    "prompt_tokens": len(req.tokens),
                    "tokens_out": len(req.out),
                    "gen_budget": req.gen_budget,
                    "tenant": req.tenant,
                    "priority": req.priority,
                    "trace_id": _trace_id_of(req.traceparent),
                })
            sched: Dict[str, Any] = {
                "policy": getattr(self._q, "policy", "fifo"),
                "queue_by_priority": {
                    str(p): d for p, d in sorted(self._q.class_depths().items())
                },
                "preemptions": self.preempted_total,
                "restores": self.restored_total,
            }
            if self._spec is not None:
                sched["speculative"] = {
                    "k": self._spec.k,
                    "proposed": self._spec.proposed_total,
                    "accepted": self._spec.accepted_total,
                    "accept_ratio": round(self._spec.accept_ratio, 4),
                }
            return {
                "kind": "decode_loop",
                "queue_depth": len(self._q),
                "live_slots": self._live,
                "slot_capacity": len(self._slots),
                "slots": slots,
                "scheduler": sched,
                "pages_used": self.allocator.used_pages,
                "pages_total": self.allocator.num_pages,
                "served_total": self.served_total,
                "tokens_total": self.tokens_total,
                "prefix_cache": {
                    "hits": self.allocator.prefix_hits,
                    "misses": self.allocator.prefix_misses,
                    "hit_ratio": round(
                        self.allocator.prefix_hits
                        / max(
                            self.allocator.prefix_hits
                            + self.allocator.prefix_misses, 1
                        ), 4,
                    ),
                    # ISSUE-17 bugfix: device-tier LRU drops used to be
                    # invisible — occupancy looked fine while hot
                    # prefixes silently churned
                    "evictions_device": self.allocator.evictions,
                },
                # host-tier occupancy beside the hit/miss counters
                # (null when the serve has no KVTierPolicy)
                "kv_host": (
                    {
                        **self._kv_host.stats(),
                        "restore_ms_mean": round(
                            sum(self._kv_restore_ms)
                            / len(self._kv_restore_ms), 3,
                        ) if self._kv_restore_ms else 0.0,
                    } if self._kv_host is not None else None
                ),
                "kv_peer_serves": self.kv_peer_serves,
            }

    # -- load reporting (progress → pod status → autoscaler) ----------------

    def report_progress(self) -> Dict[str, float]:
        now = time.monotonic()
        last_t, last_served = self._qps_last
        dt = now - last_t
        qps = (self.served_total - last_served) / dt if dt > 0 else 0.0
        self._qps_last = (now, self.served_total)
        values = {
            "serving_ready": 0.0 if self._fault is not None else 1.0,
            "serving_queue_depth": float(self.queue_depth),
            "serving_qps": qps,
            "serving_batch_occupancy": self.mean_batch_occupancy,
            "serving_requests": float(self.served_total),
            "serving_tokens": float(self.tokens_total),
            "serving_live_slots": float(self.live_slots),
        }
        _progress.report(**values)
        return values


def make_model(task: str, checkpoint: str, batching_max: int,
               env: Optional[Dict[str, str]] = None) -> ServedModel:
    """Served-model factory, by spec.task."""
    env = env or {}
    if task == "echo":
        return EchoModel(
            checkpoint,
            delay_ms=float(env.get("TFK8S_SERVE_ECHO_DELAY_MS", "0")),
        )
    if task == "mlp":
        return MlpClassifier(
            checkpoint, batching_max,
            hidden=int(env.get("TFK8S_SERVE_MLP_HIDDEN", "64")),
        )
    if task in ("gpt", "t5"):
        # t5 rides the same decoder-only generate path for now; the
        # enc-dec serving split is the documented follow-on (README)
        return GptGenerator(
            checkpoint, batching_max,
            gen_tokens=int(env.get("TFK8S_SERVE_GEN_TOKENS", "16")),
            size=env.get("TFK8S_SERVE_GPT_SIZE", "tiny"),
        )
    raise ServeError(f"unknown serve task {task!r} (known: echo, mlp, gpt, t5)")


# ---------------------------------------------------------------------------
# Metrics registry hook (the data.images pattern: the operator process
# wires its registry in; standalone use falls back to a private one)
# ---------------------------------------------------------------------------

_metrics_lock = threading.Lock()
_metrics: Optional[Metrics] = None


def set_metrics(metrics: Metrics) -> None:
    global _metrics
    with _metrics_lock:
        _metrics = metrics


def get_metrics() -> Metrics:
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            _metrics = Metrics()
        return _metrics


# ---------------------------------------------------------------------------
# The dynamic micro-batching executor
# ---------------------------------------------------------------------------


@dataclass
class _Request:
    payload: Any
    bucket: Any
    enqueue_t: float
    done: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: Optional[BaseException] = None
    # stamped at dispatch so queue/execute split exactly once per request
    dequeue_t: float = 0.0
    # request-scoped observability (empty traceparent = untraced)
    traceparent: str = ""
    tenant: str = ""
    priority: int = 0
    wall_start: float = 0.0


class ModelServer:
    """Bounded-queue dynamic batcher around one :class:`ServedModel`.

    Contract (unit-tested in tests/test_serving_executor.py):

    - a batch closes at ``max_batch_size`` OR ``batch_timeout_s`` after
      the batch OPENED (first request dequeued), whichever first;
    - only requests whose model bucket matches the batch head ride the
      batch — padding/bucketing never mixes incompatible shapes;
    - a submit past ``queue_limit`` sheds with :class:`Overloaded`; after
      :meth:`drain` began, with :class:`Draining`;
    - the queue/execute/total latency histograms observe every SERVED
      request exactly once (shed requests only count in
      ``tfk8s_serving_requests_total{outcome="rejected"}``).
    """

    def __init__(
        self,
        model: ServedModel,
        max_batch_size: int = 8,
        batch_timeout_s: float = 0.01,
        queue_limit: int = 128,
        metrics: Optional[Metrics] = None,
        labels: Optional[Dict[str, str]] = None,
    ):
        self.model = model
        self.max_batch_size = max(1, int(max_batch_size))
        self.batch_timeout_s = max(0.0, float(batch_timeout_s))
        self.queue_limit = max(self.max_batch_size, int(queue_limit))
        self.metrics = metrics if metrics is not None else get_metrics()
        self.labels = dict(labels or {})
        self._cond = threading.Condition()
        self._q: deque = deque()
        self._draining = False
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        # occupancy/throughput accounting (report_progress reads these)
        self.served_total = 0
        self.batches_total = 0
        self.rejected_total = 0
        self._qps_last = (time.monotonic(), 0)
        # fault containment / chaos hooks — the DecodeLoopExecutor
        # surface, mirrored so every replica kind can crash in tests
        self._fault: Optional[BaseException] = None
        self._chaos_delay_s = 0.0
        for name, help_text in (
            ("tfk8s_serving_requests_total",
             "Serving requests by outcome (ok / rejected / error)."),
            ("tfk8s_serving_batches_total", "Batches executed by the server."),
            ("tfk8s_serving_queue_seconds",
             "Per-request time from submit to batch dispatch."),
            ("tfk8s_serving_execute_seconds",
             "Per-request model execution time (its batch's wall time)."),
            ("tfk8s_serving_request_seconds",
             "Per-request total latency, submit to response."),
            ("tfk8s_serving_queue_depth", "Pending requests in the bounded queue."),
            ("tfk8s_serving_batch_occupancy",
             "Mean requests per executed batch since start."),
        ):
            self.metrics.describe(name, help_text)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "ModelServer":
        self._thread = threading.Thread(
            target=self._loop, name="serve-batcher", daemon=True
        )
        self._thread.start()
        return self

    def drain(self, timeout: float = 30.0) -> bool:
        """Stop accepting, finish everything queued, stop the batcher.
        Returns True when the queue fully drained inside ``timeout``."""
        deadline = time.monotonic() + timeout
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        while time.monotonic() < deadline:
            with self._cond:
                if not self._q:
                    break
            time.sleep(0.005)
        with self._cond:
            drained = not self._q
            self._stopped = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        return drained

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._q)

    @property
    def mean_batch_occupancy(self) -> float:
        return self.served_total / self.batches_total if self.batches_total else 0.0

    # -- client side --------------------------------------------------------

    def submit(self, payload: Any, timeout: Optional[float] = 30.0,
               traceparent: Optional[str] = None, tenant: str = "",
               priority: int = 0) -> Any:
        """Blocking request: returns the model's response for ``payload``,
        or raises Overloaded / Draining / InvalidRequest / RequestFailed /
        DeadlineExceeded (a TimeoutError subclass). A ``traceparent``
        makes the request traced: its served interval lands as a
        ``serve.request`` span under that parent."""
        try:
            bucket = self.model.bucket_of(payload)  # TypeError: bad payload
        except InvalidRequest:
            # unservable-by-contract (e.g. over-long prompt): a typed,
            # client-visible outcome with its own label — distinguishable
            # from shed load and from server errors in the histograms
            self.metrics.inc(
                "tfk8s_serving_requests_total", 1.0,
                {**self.labels, "outcome": "invalid"},
            )
            raise
        if self._chaos_delay_s:
            time.sleep(self._chaos_delay_s)  # gray replica: alive but slow
        req = _Request(
            payload=payload, bucket=bucket, enqueue_t=time.perf_counter(),
            traceparent=traceparent or "", tenant=tenant,
            priority=int(priority), wall_start=time.time(),
        )
        with self._cond:
            if self._fault is not None:
                raise ReplicaUnavailable(f"replica failed: {self._fault}")
            if self._draining or self._stopped:
                raise Draining("replica is draining; retry another replica")
            if len(self._q) >= self.queue_limit:
                self.rejected_total += 1
                self.metrics.inc(
                    "tfk8s_serving_requests_total", 1.0,
                    {**self.labels, "outcome": "rejected"},
                )
                raise Overloaded(len(self._q), self.queue_limit)
            self._q.append(req)
            self.metrics.set_gauge(
                "tfk8s_serving_queue_depth", float(len(self._q)), self.labels
            )
            self._cond.notify_all()
        if not req.done.wait(timeout):
            # best-effort cancellation: a request still QUEUED is removed
            # (the batcher never burns a forward on a caller that gave
            # up, and it is counted timeout, not ok); one already riding
            # a dispatched batch completes server-side — bounded waste.
            timed_out = False
            with self._cond:
                try:
                    self._q.remove(req)
                    timed_out = True
                    self.metrics.inc(
                        "tfk8s_serving_requests_total", 1.0,
                        {**self.labels, "outcome": "timeout"},
                    )
                    self.metrics.set_gauge(
                        "tfk8s_serving_queue_depth", float(len(self._q)),
                        self.labels,
                    )
                except ValueError:
                    pass  # already dequeued into a batch
            if timed_out and req.traceparent:
                _trace.get_tracer().record_span(
                    "serve.request", req.wall_start, time.time(),
                    traceparent=req.traceparent, status="error",
                    attributes={"outcome": "timeout",
                                "tenant": req.tenant,
                                "priority": req.priority},
                )
            raise DeadlineExceeded(f"request not served within {timeout}s")
        if req.error is not None:
            if isinstance(req.error, ServeError):
                raise req.error  # typed; retriability survives the hop
            raise RequestFailed(str(req.error)) from req.error
        return req.result

    # -- chaos hooks (tests/chaos.py; never on the production path) ----------

    @property
    def fault(self) -> Optional[BaseException]:
        return self._fault

    def _fail_queued(self, e: BaseException) -> None:
        with self._cond:
            victims = list(self._q)
            self._q.clear()
            self.metrics.set_gauge(
                "tfk8s_serving_queue_depth", 0.0, self.labels
            )
            self._cond.notify_all()
        if victims:
            self.metrics.inc(
                "tfk8s_serving_requests_total", float(len(victims)),
                {**self.labels, "outcome": "error"},
            )
        for req in victims:
            req.error = e
            req.done.set()

    def chaos_crash(self, message: str = "chaos: replica host died") -> None:
        """Host death: queued requests fail retriable-ReplicaUnavailable,
        new submits refuse with the same, report_progress goes
        non-Ready; the registry entry stays (a dead host can't
        unregister — discovery is what stops traffic)."""
        err = ReplicaUnavailable(message)
        with self._cond:
            self._fault = err
            self._stopped = True
            self._cond.notify_all()
        self._fail_queued(err)

    def chaos_wire_reset(self, message: str = "chaos: wire reset") -> None:
        """Fail accepted-but-unanswered (queued) requests once with
        retriable ReplicaUnavailable; the replica keeps serving."""
        self._fail_queued(ReplicaUnavailable(message))

    def chaos_delay(self, seconds: float) -> None:
        """Gray failure: every subsequent submit stalls ``seconds``."""
        self._chaos_delay_s = max(0.0, float(seconds))

    # -- the batcher --------------------------------------------------------

    def _take_matching(self, bucket: Any, want: int) -> List[_Request]:
        """Pop up to ``want`` queued requests of ``bucket`` (FIFO among
        matches; non-matching requests keep their positions). Caller holds
        the lock."""
        taken: List[_Request] = []
        if want <= 0:
            return taken
        kept: deque = deque()
        while self._q:
            r = self._q.popleft()
            if len(taken) < want and r.bucket == bucket:
                taken.append(r)
            else:
                kept.append(r)
        self._q = kept
        return taken

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._q and not self._stopped:
                    self._cond.wait(0.5)
                if self._stopped and not self._q:
                    return
                head = self._q.popleft()
                batch = [head]
                deadline = time.monotonic() + self.batch_timeout_s
                # fill from what's already queued, then wait out the
                # remaining timeout for stragglers — size OR time closes it
                batch += self._take_matching(
                    head.bucket, self.max_batch_size - len(batch)
                )
                while len(batch) < self.max_batch_size:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or self._stopped or self._draining:
                        break
                    self._cond.wait(remaining)
                    batch += self._take_matching(
                        head.bucket, self.max_batch_size - len(batch)
                    )
                self.metrics.set_gauge(
                    "tfk8s_serving_queue_depth", float(len(self._q)), self.labels
                )
            self._execute(batch)

    def _execute(self, batch: List[_Request]) -> None:
        t0 = time.perf_counter()
        for r in batch:
            r.dequeue_t = t0
        try:
            results = self.model.forward([r.payload for r in batch])
            if len(results) != len(batch):  # a model bug, not a request bug
                raise RequestFailed(
                    f"model returned {len(results)} results for a batch of "
                    f"{len(batch)}"
                )
        except BaseException as e:  # noqa: BLE001 — fan the failure out
            t1 = time.perf_counter()
            for r in batch:
                r.error = e
                if r.traceparent:
                    _trace.get_tracer().record_span(
                        "serve.request", r.wall_start,
                        r.wall_start + (t1 - r.enqueue_t),
                        traceparent=r.traceparent, status="error",
                        attributes={"outcome": "error", "error": str(e),
                                    "tenant": r.tenant,
                                    "priority": r.priority},
                    )
                r.done.set()
            self.metrics.inc(
                "tfk8s_serving_requests_total", float(len(batch)),
                {**self.labels, "outcome": "error"},
            )
            log.warning("batch of %d failed: %s", len(batch), e)
            return
        t1 = time.perf_counter()
        self.batches_total += 1
        self.served_total += len(batch)
        self.metrics.inc("tfk8s_serving_batches_total", 1.0, self.labels)
        self.metrics.inc(
            "tfk8s_serving_requests_total", float(len(batch)),
            {**self.labels, "outcome": "ok"},
        )
        self.metrics.set_gauge(
            "tfk8s_serving_batch_occupancy", self.mean_batch_occupancy, self.labels
        )
        exec_s = t1 - t0
        for r, res in zip(batch, results):
            # exactly-once histogram contract: one observation per served
            # request per family, all recorded here and nowhere else
            self.metrics.observe(
                "tfk8s_serving_queue_seconds", r.dequeue_t - r.enqueue_t, self.labels
            )
            self.metrics.observe("tfk8s_serving_execute_seconds", exec_s, self.labels)
            self.metrics.observe(
                "tfk8s_serving_request_seconds", t1 - r.enqueue_t, self.labels,
                exemplar=_trace_id_of(r.traceparent),
            )
            if r.traceparent:
                _trace.get_tracer().record_span(
                    "serve.request", r.wall_start,
                    r.wall_start + (t1 - r.enqueue_t),
                    traceparent=r.traceparent,
                    attributes={
                        "outcome": "ok",
                        "tenant": r.tenant,
                        "priority": r.priority,
                        "batch_size": len(batch),
                    },
                    events=[{
                        "name": "dispatched",
                        "ts": r.wall_start + (r.dequeue_t - r.enqueue_t),
                        "attributes": {
                            "queue_wait_s": r.dequeue_t - r.enqueue_t,
                            "execute_s": exec_s,
                        },
                    }],
                )
            r.result = res
            r.done.set()

    # -- live introspection (/debug/decode) ---------------------------------

    def debug_state(self) -> Dict[str, Any]:
        """Zpages view of the batcher (no slots/pages here — the shape
        ``/debug/decode`` renders for a non-generative replica)."""
        with self._cond:
            return {
                "kind": "batch",
                "queue_depth": len(self._q),
                "served_total": self.served_total,
                "batches_total": self.batches_total,
                "rejected_total": self.rejected_total,
            }

    # -- load reporting (progress → pod status → autoscaler) ----------------

    def report_progress(self) -> Dict[str, float]:
        now = time.monotonic()
        last_t, last_served = self._qps_last
        dt = now - last_t
        qps = (self.served_total - last_served) / dt if dt > 0 else 0.0
        self._qps_last = (now, self.served_total)
        values = {
            "serving_ready": 0.0 if self._fault is not None else 1.0,
            "serving_queue_depth": float(self.queue_depth),
            "serving_qps": qps,
            "serving_batch_occupancy": self.mean_batch_occupancy,
            "serving_requests": float(self.served_total),
        }
        _progress.report(**values)
        return values


# ---------------------------------------------------------------------------
# Replica registry + entrypoint (the kubelet-facing half)
# ---------------------------------------------------------------------------

_registry_lock = threading.Lock()
# ModelServer or DecodeLoopExecutor — one submit/drain/report surface
_REPLICAS: Dict[str, Any] = {}
# callbacks fired (outside the lock) when a replica unregisters — the
# gateway's route tables subscribe so a Draining replica leaves the
# routing set the instant the drain starts, BEFORE the kubelet flush
# would publish it (the wire half of the zero-failed-request contract)
_drain_hooks: List[Callable[[str], None]] = []


def register_replica(key: str, server: Any) -> None:
    with _registry_lock:
        _REPLICAS[key] = server


def add_drain_hook(fn: Callable[[str], None]) -> None:
    with _registry_lock:
        _drain_hooks.append(fn)


def remove_drain_hook(fn: Callable[[str], None]) -> None:
    with _registry_lock:
        if fn in _drain_hooks:
            _drain_hooks.remove(fn)


def unregister_replica(key: str) -> None:
    with _registry_lock:
        _REPLICAS.pop(key, None)
        hooks = list(_drain_hooks)
    for fn in hooks:  # outside the lock: hooks may take their own locks
        try:
            fn(key)
        except Exception:  # noqa: BLE001 - a bad subscriber can't block drain
            log.exception("drain hook failed for %s", key)


def lookup_replica(key: str) -> Optional[Any]:
    with _registry_lock:
        return _REPLICAS.get(key)


def replica_keys() -> List[str]:
    """Every registered replica key (the /debug/decode enumeration)."""
    with _registry_lock:
        return sorted(_REPLICAS)


def chaos_crash_replica(key: str,
                        message: str = "chaos: replica host died") -> bool:
    """Chaos entry (tests/chaos.py kill_replica): crash a registered
    replica WITHOUT unregistering it — the corpse stays in the registry
    and route tables keep offering it until the gateway's health
    machinery ejects it, which is exactly what a crashed host looks like
    from the serving plane. Returns False when ``key`` isn't
    registered."""
    server = lookup_replica(key)
    if server is None:
        return False
    server.chaos_crash(message)
    return True


# How often the serving entrypoint refreshes its progress report. The
# kubelet flushes progress into pod status every LOG_FLUSH_SECONDS on its
# own clock; reporting faster than it flushes costs nothing.
PROGRESS_PERIOD_S = 0.2


def replica_is_ready(pod) -> bool:
    """THE replica-readiness predicate, shared by the serve controller's
    rollout gating and ServeClient's routing (one definition — the two
    must never disagree or the zero-failed-requests rollout contract
    breaks): live, RUNNING, and the server reported ``serving_ready``
    AFTER loading the checkpoint (published into pod status by the
    kubelet flush — the hermetic readiness probe)."""
    from tfk8s_tpu.api.types import PodPhase

    return (
        pod.metadata.deletion_timestamp is None
        and pod.status.phase == PodPhase.RUNNING
        and pod.status.training.get("serving_ready") == 1.0
    )


def serve(env: Dict[str, str], stop: threading.Event) -> None:
    """The TPUServe pod entrypoint (rendered by trainer/serve_controller).
    Load → register → Ready → report load until stopped → drain."""
    task = env.get("TFK8S_SERVE_TASK", "echo")
    checkpoint = env.get("TFK8S_SERVE_CHECKPOINT", "")
    max_batch = int(env.get("TFK8S_SERVE_MAX_BATCH", "8"))
    timeout_ms = float(env.get("TFK8S_SERVE_BATCH_TIMEOUT_MS", "10"))
    queue_limit = int(env.get("TFK8S_SERVE_QUEUE_LIMIT", "128"))
    ns = env.get("TFK8S_NAMESPACE", "default")
    pod = env.get("TFK8S_POD_NAME", "")
    serve_name = env.get("TFK8S_SERVE_NAME", "")
    # disaggregated serving: "prefill" / "decode" pool membership (empty
    # for a single-pool serve). The executor is the SAME either way —
    # the gateway decides which entry point (submit / submit_prefill /
    # submit_handoff) a pool's replicas see; the phase only labels this
    # replica's metrics so each pool's signals aggregate separately.
    phase = env.get("TFK8S_SERVE_PHASE", "")
    labels = {"serve": serve_name, "pod": pod}
    if phase:
        labels["phase"] = phase
    key = f"{ns}/{pod}"

    # generative tasks get the continuous-batching decode loop (token-
    # granularity admission/retirement against the paged KV cache);
    # TFK8S_SERVE_DECODE_LOOP=0 pins the legacy slot-per-batch executor
    # (and is what the bench baseline arm measures against)
    decode_loop = task in ("gpt", "t5") and env.get(
        "TFK8S_SERVE_DECODE_LOOP", "1"
    ) != "0"
    if decode_loop:
        model = PagedGptDecoder(
            checkpoint,
            slots=max_batch,
            page_size=int(env.get("TFK8S_SERVE_PAGE_SIZE", "16")),
            max_pages=int(env.get("TFK8S_SERVE_MAX_PAGES", "256")),
            gen_tokens=int(env.get("TFK8S_SERVE_GEN_TOKENS", "16")),
            size=env.get("TFK8S_SERVE_GPT_SIZE", "tiny"),
            prefill_chunk=int(env.get("TFK8S_SERVE_PREFILL_CHUNK", "32")),
            eos_id=(
                int(env["TFK8S_SERVE_EOS_ID"])
                if env.get("TFK8S_SERVE_EOS_ID") else None
            ),
        )
        model.load()  # Ready is honest: the weights are resident before it
        speculative = None
        if env.get("TFK8S_SERVE_SPEC_DECODE", "0") != "0":
            from tfk8s_tpu.runtime.sched import SpeculativeEngine

            speculative = SpeculativeEngine.build(
                model,
                k=int(env.get("TFK8S_SERVE_SPEC_TOKENS", "4")),
                size=env.get("TFK8S_SERVE_SPEC_DRAFT", "tiny"),
            )
        server = DecodeLoopExecutor(
            model,
            queue_limit=queue_limit,
            metrics=get_metrics(),
            labels=labels,
            prefix_cache=env.get("TFK8S_SERVE_PREFIX_CACHE", "1") != "0",
            sched_policy=env.get("TFK8S_SERVE_SCHED_POLICY", "fifo"),
            preemption=env.get("TFK8S_SERVE_PREEMPTION", "1") != "0",
            aging_s=float(env.get("TFK8S_SERVE_AGING_S", "5.0")),
            speculative=speculative,
            # KV economy (runtime/kvtier): rendered only when the spec
            # carries a KVTierPolicy — both default OFF, which keeps an
            # absent policy bit-identical (no demotions, no peer pulls)
            kv_host_bytes=int(env.get("TFK8S_KV_HOST_BYTES", "0")),
            kv_peer_fetch=env.get("TFK8S_KV_PEER_FETCH", "0") != "0",
        ).start()
    else:
        model = make_model(task, checkpoint, max_batch, env)
        model.load()  # Ready is honest: the weights are resident before it
        server = ModelServer(
            model,
            max_batch_size=max_batch,
            batch_timeout_s=timeout_ms / 1000.0,
            queue_limit=queue_limit,
            metrics=get_metrics(),
            labels=labels,
        ).start()
    register_replica(key, server)
    server.report_progress()
    log.info("%s: serving %s (%s) ready; version=%s", key, task, checkpoint,
             model.version)
    reclaimed = False
    fault: Optional[BaseException] = None
    try:
        while not stop.wait(PROGRESS_PERIOD_S):
            # a GLOBAL fault (device unusable) exits non-Ready WITHOUT
            # the drain protocol: a crashed host can't unregister — the
            # registry keeps the corpse and discovery (gateway health
            # ejection, stale aging) stops traffic; the raised error
            # FAILs the pod so the serve controller replaces it
            fault = getattr(server, "fault", None)
            if fault is not None:
                log.error("%s: replica fault, exiting non-Ready: %s",
                          key, fault)
                break
            # a reclaim notice (runtime/kubelet.py PodStopSignal) is an
            # immediate graceful exit for a serving replica: there is no
            # step to finish — unregister now so the client routes away,
            # drain the accepted queue, and exit Drained so the
            # controller replaces rather than failure-counts the pod
            if getattr(stop, "drain_requested", False):
                reclaimed = True
                log.info("%s: reclaim notice; draining replica", key)
                break
            server.report_progress()
    finally:
        if fault is not None:
            server.report_progress()  # publish serving_ready 0.0
        else:
            # drain order matters: unregister FIRST so the client stops
            # picking this replica, then finish what it already holds —
            # a rolling update never fails an accepted request
            unregister_replica(key)
            drained = server.drain(
                timeout=float(env.get("TFK8S_SERVE_DRAIN_TIMEOUT_S", "30"))
            )
            log.info("%s: drained=%s after %d requests in %d batches",
                     key, drained, server.served_total, server.batches_total)
    if fault is not None:
        raise ServeError(f"{key}: replica fault: {fault}")
    if reclaimed:
        from tfk8s_tpu.runtime.registry import PodDrained

        raise PodDrained(f"{key}: replica drained on reclaim notice")


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class ServeClient:
    """Round-robin client over a TPUServe's Ready replicas. Discovery is
    a pod list through the clientset (label selector, the endpoints-list
    analogue); dispatch goes through the in-process replica registry.
    Draining/vanished replicas are retried transparently on another
    replica (the zero-failed-requests rollout contract). Overloaded is
    backpressure and is HONORED: the client backs off for the shedder's
    ``retry_after_s`` hint (jittered, so a thousand shed callers don't
    re-arrive in lockstep) and retries inside the caller's deadline; only
    when the deadline can't absorb the backoff does it propagate."""

    #: base backoff when a shed carries no retry_after_s hint
    OVERLOAD_BACKOFF_S = 0.05

    def __init__(self, clientset, name: str, namespace: str = "default",
                 cache_ttl_s: float = 0.25):
        self._cs = clientset
        self.name = name
        self.namespace = namespace
        self._rr = 0
        self._cache: Tuple[float, List[str]] = (0.0, [])
        self._cache_ttl = cache_ttl_s
        self._lock = threading.Lock()

    def ready_replica_keys(self, refresh: bool = False) -> List[str]:
        from tfk8s_tpu.trainer import labels as L

        with self._lock:
            ts, cached = self._cache
            if not refresh and cached and time.monotonic() - ts < self._cache_ttl:
                return list(cached)
        pods, _rv = self._cs.pods(self.namespace).list(
            label_selector=L.serve_selector(self.name)
        )
        keys = sorted(p.metadata.key for p in pods if replica_is_ready(p))
        with self._lock:
            self._cache = (time.monotonic(), keys)
        return keys

    def request(self, payload: Any, timeout: float = 30.0,
                traceparent: Optional[str] = None, tenant: str = "",
                priority: int = 0) -> Any:
        deadline = time.monotonic() + timeout
        refresh = False
        backoff = 0.02
        shed_backoff = self.OVERLOAD_BACKOFF_S
        attempt = 0
        # the ambient span (or the one the traceparent continues) carries
        # the retry timeline: a request retried through a Draining replica
        # shows its FULL path, not just the winning attempt
        span = _trace.get_tracer().current_span()
        if traceparent is None and span is not None:
            traceparent = span.traceparent
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise DeadlineExceeded(
                    f"no replica of {self.namespace}/{self.name} served the "
                    f"request within {timeout}s"
                )
            keys = self.ready_replica_keys(refresh=refresh)
            refresh = False
            targets = [k for k in keys if lookup_replica(k) is not None]
            if not targets:
                # exponential backoff while no replica is routable: N
                # blocked callers re-listing every few ms would stampede
                # the shared rate-limited client during a rollout gap
                time.sleep(min(backoff, max(remaining, 0.0)))
                backoff = min(backoff * 2, 0.5)
                refresh = True
                continue
            backoff = 0.02
            with self._lock:
                self._rr += 1
                key = targets[self._rr % len(targets)]
            server = lookup_replica(key)
            if server is None:
                refresh = True
                continue
            attempt += 1
            try:
                return server.submit(
                    payload, timeout=remaining, traceparent=traceparent,
                    tenant=tenant, priority=priority,
                )
            except Draining:
                # replica is rolling out from under us — retry elsewhere
                if span is not None:
                    span.add_event("retry", {
                        "attempt": attempt, "reason": "Draining",
                        "replica": key, "backoff_s": 0.0,
                    })
                refresh = True
                continue
            except ReplicaUnavailable:
                # the replica died holding the request — idempotent serve,
                # safe to re-dispatch to a survivor inside the deadline
                if span is not None:
                    span.add_event("retry", {
                        "attempt": attempt, "reason": "ReplicaUnavailable",
                        "replica": key, "backoff_s": 0.0,
                    })
                refresh = True
                continue
            except Overloaded as exc:
                delay = jittered_backoff(exc.retry_after_s, shed_backoff)
                if delay >= deadline - time.monotonic():
                    # the deadline can't absorb the backoff — surface the
                    # shed rather than burn the wait and time out anyway
                    raise
                if span is not None:
                    span.add_event("retry", {
                        "attempt": attempt, "reason": "Overloaded",
                        "replica": key, "backoff_s": delay,
                    })
                time.sleep(delay)
                shed_backoff = min(shed_backoff * 2, 1.0)
                refresh = True


def jittered_backoff(retry_after_s: Optional[float], fallback_s: float) -> float:
    """Turn a shedder's Retry-After hint (or a client-side fallback) into
    an actual sleep: uniformly jittered over [0.5x, 1.5x] so shed callers
    decorrelate instead of re-arriving in lockstep at the hinted instant."""
    import random

    base = retry_after_s if retry_after_s and retry_after_s > 0 else fallback_s
    return base * (0.5 + random.random())


def template_hash(wire_fragment: Any) -> str:
    """Stable short hash of a wire-form spec fragment — the pod-template
    version identity rolling updates key off."""
    import json

    blob = json.dumps(wire_fragment, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:10]


__all__ = [
    "DeadlineExceeded",
    "DecodeLoopExecutor",
    "Draining",
    "EchoModel",
    "GptGenerator",
    "HandoffError",
    "KVHandoffBuffer",
    "InvalidRequest",
    "MlpClassifier",
    "ModelServer",
    "Overloaded",
    "PagedGptDecoder",
    "Preempted",
    "QuotaExceeded",
    "ReplicaUnavailable",
    "RequestFailed",
    "RowFault",
    "ServeClient",
    "ServeError",
    "ServedModel",
    "add_drain_hook",
    "chaos_crash_replica",
    "jittered_backoff",
    "make_model",
    "register_replica",
    "remove_drain_hook",
    "replica_is_ready",
    "replica_keys",
    "serve",
    "set_metrics",
    "template_hash",
    "unregister_replica",
]
