"""The KV economy (ISSUE 17): tiered prefix-cache residency.

At fleet scale the shared system prompt IS the working set, but before
this subsystem a cached prefix lived and died inside one replica's
device page pool: ``PageAllocator._evict_idle`` dropped idle pages on
the floor, and a replica that missed a hot prefix re-prefilled from
scratch even when a peer held it warm. This package composes three
primitives that already existed — the content-hashed prefix cache
(runtime/paging.py), verified cross-replica page movement
(``KVHandoffBuffer``/``KVTransport``, runtime/handoff.py), and the
scheduler's spill/serialize path — into three residency tiers:

- **device** (tier 0): the page pool itself; unchanged hot path.
- **host** (tier 1, :mod:`.host`): a byte-bounded LRU of serialized
  prefix buffers behind the pool. Eviction demotes instead of drops; a
  later hit restores through the handoff-import path, bit-identical to
  an uninterrupted device hit.
- **peer** (tier 2, :mod:`.peer`): a replica that misses locally pulls
  warm pages from a peer over ``KVTransport``, digest-chain-verified,
  falling back to plain prefill on any ``HandoffError``.

The gateway side (:mod:`.directory`) aggregates per-replica digest
reports so prefix-affinity routing targets *actual* cache contents:
a directory hit overrides the consistent-hash guess, and staleness
bounds mean a wrong entry costs only a fallback prefill.

Everything here is plain Python under the executor's lock — no jax;
the executor owns the device <-> host/peer K/V movement
(``model.export_kv``/``import_kv``).
"""

from tfk8s_tpu.runtime.kvtier.directory import (
    DIRECTORY_STALE_S,
    CacheDirectory,
)
from tfk8s_tpu.runtime.kvtier.host import HostKVCache
from tfk8s_tpu.runtime.kvtier.peer import fetch_prefix

__all__ = [
    "CacheDirectory",
    "DIRECTORY_STALE_S",
    "HostKVCache",
    "fetch_prefix",
]
