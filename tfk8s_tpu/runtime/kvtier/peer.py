"""Peer tier: pull a warm prefix from another replica over KVTransport.

A replica that misses a prefix locally but learns (via the gateway
cache directory) that a peer holds it warm fetches the peer's pages
instead of re-prefilling. The fetch rides the disaggregation seam
end-to-end: the peer exports a ``KVHandoffBuffer.prefix`` buffer, the
transport moves it (``LocalKVTransport`` round-trips the wire bytes,
which re-verifies the digest chain at the destination), and THIS module
re-checks the chain against the *requesting* prompt — a stale or
confused peer returning a self-consistent buffer for the WRONG prefix
is refused just like a tampered one.

Every failure shape — peer ejected, peer holds nothing, transport
corruption, chain mismatch — raises :class:`HandoffError`; the
executor's caller catches it and falls back to plain prefill, so a
peer fetch is never a user-visible failure (ISSUE 17 contract,
test-pinned in tests/test_kv_tier.py).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

from tfk8s_tpu.runtime.handoff import (
    HandoffError,
    KVHandoffBuffer,
    KVTransport,
    LocalKVTransport,
)
from tfk8s_tpu.runtime.paging import prefix_digest_chain


def fetch_prefix(
    resolve: Callable[[str], Any],
    peer_key: str,
    tokens: Sequence[int],
    transport: Optional[KVTransport] = None,
) -> KVHandoffBuffer:
    """Fetch the longest warm prefix of ``tokens`` that ``peer_key``
    holds. Returns a verified prefix buffer whose digest chain matches
    the requesting prompt; raises :class:`HandoffError` otherwise."""
    transport = transport or LocalKVTransport()
    toks = [int(t) for t in tokens]
    peer = resolve(peer_key)
    if peer is None:
        raise HandoffError(
            f"peer {peer_key!r} not resolvable (drained or ejected)"
        )
    exporter = getattr(peer, "export_prefix", None)
    if exporter is None:
        raise HandoffError(
            f"peer {peer_key!r} does not export prefixes (no KV tier)"
        )
    buf = exporter(toks)
    if buf is None:
        raise HandoffError(
            f"peer {peer_key!r} holds no prefix for this prompt"
        )
    # the transport round trip is the integrity gate for the BYTES
    # (from_bytes -> verify at the destination); tampering anywhere on
    # the wire surfaces here as HandoffError
    buf, _nbytes = transport.transfer(buf)
    # ...and the chain re-check is the integrity gate for the IDENTITY:
    # the buffer must be a prefix of OUR prompt, not merely self-
    # consistent with its own tokens
    ps = buf.page_size
    if ps < 1 or len(buf.tokens) % ps != 0:
        raise HandoffError(
            f"peer buffer is not page-aligned: {len(buf.tokens)} token(s) "
            f"@ page_size {ps}"
        )
    n_pages = len(buf.tokens) // ps
    if n_pages == 0 or len(toks) < len(buf.tokens):
        raise HandoffError(
            f"peer buffer covers {len(buf.tokens)} token(s) — not a "
            f"usable prefix of a {len(toks)}-token prompt"
        )
    want = prefix_digest_chain(toks, ps, n_pages)
    if list(buf.digests) != want:
        raise HandoffError(
            "peer buffer digest chain does not match the requesting "
            "prompt — refusing foreign K/V"
        )
    return buf


__all__ = ["fetch_prefix"]
