"""Host tier: a byte-bounded LRU of serialized prefix buffers.

Sits BEHIND the device page pool. When ``PageAllocator._evict_idle``
would drop an idle cached prefix, the executor exports the chain's K/V
(``model.export_kv``) into a ``KVHandoffBuffer.prefix`` buffer and
parks the wire bytes here; a later prompt whose digest chain hits an
entry restores through the handoff-import path (``model.import_kv`` +
``PageAllocator.restore_prefix``) — the same lossless byte round trip
the disaggregation seam uses, so a restored hit is bit-identical to an
uninterrupted device hit (test-pinned in tests/test_kv_tier.py).

Capacity is BYTES (``TFK8S_KV_HOST_BYTES``), not entries: entries are
whole serialized chains of very different sizes, and host RAM is the
budgeted resource. Overflow evicts LRU-oldest first, with its own
eviction accounting (``tier="host"`` on the shared eviction counter —
the executor owns metric emission; this class just counts).

Plain Python, no locking of its own: the owning executor calls every
method under its admission lock.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from tfk8s_tpu.runtime.handoff import HandoffError


class HostKVCache:
    """LRU map: chain-final digest -> serialized prefix buffer bytes.

    Each entry also remembers the chain's FIRST-page digest (its
    affinity key) so the cache directory can advertise host-resident
    prefixes the same way it advertises device-resident ones, and a
    sha256 of the wire bytes taken at demotion time: the buffer's own
    digest chain covers the TOKEN pages (prefix identity), not the K/V
    payload, so without this check host-RAM corruption would restore
    silently wrong K/V and the bit-identity promise would be a lie.
    A ``get`` whose bytes no longer match raises
    :class:`~tfk8s_tpu.runtime.handoff.HandoffError` and drops the
    entry — the caller falls back to plain prefill.
    """

    def __init__(self, capacity_bytes: int,
                 on_evict: Optional[Callable[[str, int], None]] = None):
        if capacity_bytes < 0:
            raise ValueError(
                f"capacity_bytes must be >= 0, got {capacity_bytes}"
            )
        self.capacity_bytes = capacity_bytes
        #: final digest -> (wire bytes, affinity key, sha256-at-demote)
        #: — LRU oldest first
        self._entries: "OrderedDict[str, Tuple[bytes, str, bytes]]" = (
            OrderedDict()
        )
        self._bytes = 0
        self._on_evict = on_evict
        self.demotions = 0
        self.restores = 0
        self.evictions = 0

    # -- occupancy ----------------------------------------------------------

    @property
    def bytes_used(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._entries)

    def has(self, key: str) -> bool:
        """Membership WITHOUT touching LRU order (the demotion path asks
        before exporting; asking must not make an entry look hot)."""
        return key in self._entries

    def akeys(self) -> List[str]:
        """Affinity keys (first-page digests) of every resident entry,
        LRU-oldest first — the host half of the directory report."""
        return [akey for _wire, akey, _sum in self._entries.values()]

    def stats(self) -> Dict[str, int]:
        """Occupancy block for /debug/state and the directory report."""
        return {
            "bytes": self._bytes,
            "capacity_bytes": self.capacity_bytes,
            "cached_prefixes": len(self._entries),
            "demotions": self.demotions,
            "restores": self.restores,
            "evictions": self.evictions,
        }

    # -- demote / restore ---------------------------------------------------

    def put(self, key: str, wire: bytes, akey: str) -> bool:
        """Demote a serialized chain under its final digest. An entry
        larger than the whole budget is refused (it could only live by
        evicting everything, then immediately thrash). Returns whether
        the entry was admitted."""
        if len(wire) > self.capacity_bytes:
            return False
        old = self._entries.pop(key, None)
        if old is not None:
            self._bytes -= len(old[0])
        self._entries[key] = (wire, akey, hashlib.sha256(wire).digest())
        self._bytes += len(wire)
        self.demotions += 1
        while self._bytes > self.capacity_bytes:
            evicted_key, (evicted_wire, _akey, _sum) = self._entries.popitem(
                last=False
            )
            self._bytes -= len(evicted_wire)
            self.evictions += 1
            if self._on_evict is not None:
                self._on_evict(evicted_key, len(evicted_wire))
        return True

    def get(self, key: str) -> Optional[bytes]:
        """Wire bytes for a chain-final digest, refreshing LRU order on
        hit. The entry STAYS resident — the device copy it restores is
        itself evictable, and keeping the host copy makes the next
        demotion of the same chain a no-op. The owning executor bumps
        :attr:`restores` itself, AFTER the restore actually lands (a
        corrupt entry that fails to scatter is not a restore).

        Raises :class:`HandoffError` (and drops the entry) when the
        bytes no longer match their demotion-time checksum."""
        entry = self._entries.get(key)
        if entry is None:
            return None
        wire, _akey, checksum = entry
        if hashlib.sha256(wire).digest() != checksum:
            self.discard(key)
            raise HandoffError(
                f"host K/V entry {key[:12]} corrupted in RAM "
                "(checksum mismatch)"
            )
        self._entries.move_to_end(key)
        return wire

    def discard(self, key: str) -> None:
        """Drop an entry that failed verification on restore — a corrupt
        buffer must not be offered twice."""
        entry = self._entries.pop(key, None)
        if entry is not None:
            self._bytes -= len(entry[0])


__all__ = ["HostKVCache"]
