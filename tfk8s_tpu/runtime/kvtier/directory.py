"""Gateway cache directory: route on actual cache contents.

Prefix-affinity routing (gateway/affinity.py) GUESSES which replica
holds a prefix warm — the consistent hash sends same-prefix traffic to
the same place, so the guess is usually right, but it is blind to what
replicas actually cached (scale-ups remap the ring, evictions drop
entries, disagg imports warm replicas the ring never chose). The
directory closes that loop: each replica periodically reports the
digest keys resident in its device cache plus the affinity keys of its
host-tier entries (the /debug/routes hit/miss plumbing generalized
into a digest-summary report, ``DecodeLoopExecutor.kv_digest_report``),
and the gateway consults :meth:`CacheDirectory.lookup` before the ring
walk — a fresh directory hit overrides the consistent-hash guess.

Staleness is bounded, not prevented: a report older than ``ttl_s`` is
ignored (the replica may have evicted, drained, or died since), and
even a FRESH entry can be wrong by one eviction. That is safe by
construction — the route override only changes WHERE the request
lands; a replica that turns out cold just runs a plain prefill, and a
peer fetch that fails mid-flight degrades the same way. A wrong
directory entry costs a fallback prefill, never a failed request.

Plain data under the gateway's state lock; the injected clock keeps it
deterministic in tests (seeded-determinism lint scope).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

#: default report freshness bound — older reports are routing noise
#: (TPUServe ``kvTier.directoryTtlS`` overrides per serve)
DIRECTORY_STALE_S = 5.0


class _Report:
    __slots__ = ("digests", "host", "prefix_cache", "stamp")

    def __init__(self, digests: frozenset, host: Dict[str, int],
                 prefix_cache: Dict[str, Any], stamp: float):
        self.digests = digests
        self.host = host
        self.prefix_cache = prefix_cache
        self.stamp = stamp


class CacheDirectory:
    """Per-serve aggregate of replica digest reports."""

    def __init__(self, ttl_s: float = DIRECTORY_STALE_S,
                 clock: Callable[[], float] = time.monotonic):
        if ttl_s <= 0:
            raise ValueError(f"ttl_s must be > 0, got {ttl_s}")
        self.ttl_s = ttl_s
        self._clock = clock
        self._replicas: Dict[str, _Report] = {}
        self._last_poll = float("-inf")
        self.hits = 0
        self.misses = 0
        self.stale = 0

    # -- report ingestion ---------------------------------------------------

    def should_poll(self) -> bool:
        """Rate-limit report collection to twice per TTL — fresh enough
        that entries outlive their collection cadence, cheap enough that
        the dispatch path can call this inline."""
        now = self._clock()
        if now - self._last_poll < self.ttl_s / 2.0:
            return False
        self._last_poll = now
        return True

    def report(self, replica: str, report: Optional[Dict[str, Any]]) -> None:
        """Ingest one replica's digest summary (``kv_digest_report``
        shape: ``{"digests": [...], "host": {...}, "prefix_cache":
        {...}}``). ``None`` — replica gone or reporting unsupported —
        forgets it."""
        if not report:
            self._replicas.pop(replica, None)
            return
        self._replicas[replica] = _Report(
            digests=frozenset(report.get("digests", ())),
            host=dict(report.get("host") or {}),
            prefix_cache=dict(report.get("prefix_cache") or {}),
            stamp=self._clock(),
        )

    def forget(self, replica: str) -> None:
        """Drop a replica's entries (ejected/removed — its cache is no
        longer reachable, so advertising it would only buy fallbacks)."""
        self._replicas.pop(replica, None)

    # -- lookup -------------------------------------------------------------

    def lookup(self, akey: str) -> Tuple[Optional[str], str]:
        """Who holds ``akey`` warm? Returns ``(owner, outcome)`` where
        outcome is ``hit`` (fresh owner found), ``stale`` (only expired
        reports claim it), or ``miss``. Ties break to the freshest
        report, then lexicographically — deterministic, so repeated
        same-prefix requests pile onto ONE warm replica instead of
        spraying."""
        now = self._clock()
        best: Optional[str] = None
        best_stamp = float("-inf")
        saw_stale = False
        for replica, rep in self._replicas.items():
            if akey not in rep.digests:
                continue
            if now - rep.stamp > self.ttl_s:
                saw_stale = True
                continue
            if best is None or rep.stamp > best_stamp or (
                rep.stamp == best_stamp and replica < best
            ):
                best, best_stamp = replica, rep.stamp
        if best is not None:
            self.hits += 1
            return best, "hit"
        if saw_stale:
            self.stale += 1
            return None, "stale"
        self.misses += 1
        return None, "miss"

    def owner_of(self, akey: str) -> Optional[str]:
        owner, _outcome = self.lookup(akey)
        return owner

    # -- introspection ------------------------------------------------------

    def describe(self) -> Dict[str, Any]:
        """/debug/routes block: per-replica digest counts, host-tier
        occupancy, report age, plus directory-level lookup counters."""
        now = self._clock()
        replicas = {}
        for replica, rep in sorted(self._replicas.items()):
            replicas[replica] = {
                "digests": len(rep.digests),
                "host": rep.host,
                "prefix_cache": rep.prefix_cache,
                "age_s": round(max(now - rep.stamp, 0.0), 3),
                "fresh": (now - rep.stamp) <= self.ttl_s,
            }
        return {
            "ttl_s": self.ttl_s,
            "replicas": replicas,
            "lookups": {
                "hit": self.hits, "miss": self.misses, "stale": self.stale,
            },
        }


__all__ = ["CacheDirectory", "DIRECTORY_STALE_S"]
