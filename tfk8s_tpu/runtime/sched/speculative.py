"""Speculative decoding engine for the continuous-batching loop.

A small DRAFT model (``tiny_config`` by default) proposes ``k`` tokens
per live row; the serving (TARGET) model verifies all of them in ONE
packed chunk step (``gpt.verify_step_packed``); the executor accepts the
longest draft prefix the target agrees with and appends the target's own
correction token. Because every emitted token is the TARGET's pick at
its position — computed with the same per-row sampling and the same
position-folded PRNG a plain decode step would use — the output stream
is token-identical to non-speculative decoding at the same seeds, no
matter how bad the draft is. Draft quality only sets the speedup: accept
ratio ``a/k`` turns one verify dispatch into ``1..k+1`` emitted tokens.

Paging: the draft runs against its OWN page pool but reuses the TARGET's
page-table VALUES — the draft decoder is built with the target's
``kv_page_size`` / ``kv_max_pages`` (asserted), so ``pages_per_slot()``
matches and every target lease indexes a valid draft page. The executor
already draws a row's whole lease at admission (prefill never grows the
table mid-decode), so speculative rounds need NO page bookkeeping at
all. Draft KV for REJECTED proposals goes stale in the draft pool; the
per-round catch-up chunk re-scatters the true emitted tokens before the
next proposal reads anything, the same overwrite-before-read order the
paged attention itself relies on.

The engine is deliberately dumb about slots: ``propose`` reads the
executor's live ``_Slot`` rows (``spec_chunk`` — the tokens emitted last
round — plus ``position`` and the lease's page table) and returns a
``[slots, k]`` proposal matrix. All accept/retire policy stays in the
executor.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import numpy as np


class SpeculativeEngine:
    """Owns the draft decoder and the draft-side KV discipline.

    ``draft`` is a loaded ``runtime.server.PagedGptDecoder`` whose page
    geometry matches the target's (see :meth:`build`). The draft always
    proposes GREEDILY — sampling only shapes the target's verify picks,
    where correctness lives; a greedy draft maximizes the accepted
    prefix against a mostly-greedy target and keeps proposal cost at one
    argmax per token.
    """

    def __init__(self, draft: Any, k: int = 4) -> None:
        self.draft = draft
        # clamp rather than raise: a bad knob must degrade to k=1
        # (plain-decode throughput), never brick the replica
        self.k = max(1, int(k))
        # running accept accounting the executor folds into
        # tfk8s_sched_spec_accept_ratio
        self.proposed_total = 0
        self.accepted_total = 0

    # -- construction --------------------------------------------------

    @classmethod
    def build(
        cls,
        target: Any,
        k: int = 4,
        size: str = "tiny",
        checkpoint: Optional[str] = None,
        params: Optional[Any] = None,
    ) -> "SpeculativeEngine":
        """Build + load a draft decoder shaped to shadow ``target``: the
        draft keeps its own (small) width/depth but takes the target's
        vocab, max_len, slot count and page geometry so the two models
        agree on token ids, page-table extent and packed array shapes.
        ``params`` injects pre-trained draft params (the bench trains
        the draft on the same hermetic chain as the target so acceptance
        is genuinely high); otherwise ``checkpoint`` (default
        ``"seed:0"``) initializes them."""
        import dataclasses as _dc

        # lazy: server imports this package inside the executor, never
        # at module scope — keep the reverse edge lazy too
        from tfk8s_tpu.runtime.server import PagedGptDecoder, _gpt_config_of

        base = _dc.replace(
            _gpt_config_of(size),
            vocab_size=target.vocab_size,
            max_len=target.max_len,
        )
        draft = PagedGptDecoder(
            checkpoint or "seed:0",
            slots=target.slots,
            page_size=target.page_size,
            max_pages=target.max_pages,
            gen_tokens=1,
            size=size,
            prefill_chunk=target.prefill_chunk,
            cfg=base,
            params=params,
        )
        draft.load()
        assert draft.pages_per_slot == target.pages_per_slot, (
            "draft/target page-table extent desync: "
            f"{draft.pages_per_slot} != {target.pages_per_slot}"
        )
        return cls(draft, k=k)

    # -- draft-side KV mirroring ---------------------------------------

    def prefill_batch(self, batch: np.ndarray) -> None:
        """Mirror a target prefill dispatch into the draft pool: the
        SAME packed batch array (chunk tokens, base position, page
        table) scatters the draft's prompt K/V at the same page ids.
        Picks are discarded — the draft never emits during prefill."""
        self.draft.prefill_batch(batch)

    def prefill_tokens(self, tokens: Sequence[int], pages: Sequence[int]) -> None:
        """Catch the draft up over a FULL resident token list — the
        restore half of preempt/spill and the import half of a KV
        handoff, where the target's KV arrives as a buffer the draft
        never saw. Chunked ``[1, C]`` like the executor's trickle
        path."""
        c = self.draft.prefill_chunk
        mpp = self.draft.pages_per_slot
        plen = len(tokens)
        base = 0
        while base < plen:
            end = min(base + c, plen)
            batch = np.zeros((1, c + 1 + mpp), np.int32)
            batch[0, : end - base] = np.asarray(tokens[base:end], np.int32)
            batch[0, c] = base
            batch[0, c + 1 : c + 1 + len(pages)] = np.asarray(pages, np.int32)
            self.draft.prefill_batch(batch)
            base = end

    # -- proposal ------------------------------------------------------

    def propose(self, slots: List[Any]) -> np.ndarray:
        """One speculative round's draft half: catch the draft up on
        every row's last-round emitted chunk (one packed prefill-shaped
        dispatch — this also produces the first proposal ``d0`` as the
        pick at the chunk's last real token), then chain ``k - 1``
        greedy draft decode steps for the rest. Returns a ``[len(slots),
        k]`` int32 proposal matrix; rows without a live slot (or an
        empty ``spec_chunk``) are zero-filled junk the caller must skip.

        The catch-up chunk embeds row ``r``'s emitted tokens at base
        position ``position - len(chunk) + 1`` — the absolute position
        of the first emitted token — so the draft's KV and logits line
        up with the target's stream exactly, including after an
        all-``k``-accepted round where positions ``P..P+k`` were written
        by the draft's own (now partially stale) proposals."""
        n = len(slots)
        mpp = self.draft.pages_per_slot
        c = self.k + 1  # a round emits at most k accepted + 1 correction
        batch = np.zeros((n, c + 1 + mpp), np.int32)
        lens = np.zeros(n, np.int64)
        for i, slot in enumerate(slots):
            chunk = getattr(slot, "spec_chunk", None) if slot else None
            if not chunk:
                continue
            base = slot.position - len(chunk) + 1
            batch[i, : len(chunk)] = np.asarray(chunk, np.int32)
            batch[i, c] = base
            table = slot.lease.pages
            batch[i, c + 1 : c + 1 + len(table)] = np.asarray(table, np.int32)
            lens[i] = len(chunk)
        picks = self.draft.prefill_batch(batch)  # [n, c] numpy
        state = np.zeros((n, 2 + mpp), np.int32)
        d0 = np.zeros(n, np.int32)
        for i, slot in enumerate(slots):
            if not lens[i]:
                continue
            d0[i] = picks[i, lens[i] - 1]
            state[i, 0] = d0[i]
            state[i, 1] = slot.position + 1
            table = slot.lease.pages
            state[i, 2 : 2 + len(table)] = np.asarray(table, np.int32)
        cols = [d0]
        dev_state: Any = state
        for _ in range(self.k - 1):
            nxt, dev_state = self.draft.decode(dev_state)
            cols.append(np.asarray(nxt, np.int32))
        return np.stack(cols, axis=1)

    # -- accounting ----------------------------------------------------

    def record(self, proposed: int, accepted: int) -> None:
        self.proposed_total += int(proposed)
        self.accepted_total += int(accepted)

    @property
    def accept_ratio(self) -> float:
        if not self.proposed_total:
            return 0.0
        return self.accepted_total / self.proposed_total
