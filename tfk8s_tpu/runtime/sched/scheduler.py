"""Admission-order policy for the decode loop's request queue.

Both schedulers present the same narrow surface the executor drives
(``append`` / ``peek`` / ``pop`` / ``remove`` / ``requeue_front`` /
``__len__`` / ``__iter__`` / ``clear``), so the loop's admission code is
policy-blind. ``FifoScheduler`` is a thin deque wrapper — the PR-7
behavior, bit-identical. ``PriorityScheduler`` keeps one FIFO deque per
priority class and picks the class head with the highest EFFECTIVE
priority::

    score(req) = req.priority + waited_seconds / aging_s

The aging term is the anti-starvation guarantee: a low-priority request
gains one full priority level per ``aging_s`` seconds queued, so under
sustained high-priority load it is eventually scheduled instead of
starving forever. Within a class, order is strictly FIFO (the head of
each class deque is also its oldest, so the head always holds the
class's best score — ``peek`` only ever scans class heads).

Preempted rows re-enter at the FRONT of their class
(``requeue_front``): they already hold partial output and their spilled
KV buffer is cheapest to restore while the prefix cache is still warm.

Clock discipline: waiting time is measured with ``time.perf_counter``
against the request's ``enqueue_t`` stamp (the same clock the executor
stamps) — never the wall clock, which the seeded-determinism lint bans
on this path.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, Iterator, List, Optional


class FifoScheduler:
    """Strict arrival order — the decode loop's original admission
    policy. A stalled head blocks later admissions by design (a stream
    of small requests cannot starve a big one)."""

    policy = "fifo"

    def __init__(self) -> None:
        self._q: deque = deque()

    def append(self, req: Any) -> None:
        self._q.append(req)

    def requeue_front(self, req: Any) -> None:
        self._q.appendleft(req)

    def peek(self) -> Optional[Any]:
        return self._q[0] if self._q else None

    def pop(self, req: Any) -> None:
        """Remove the previously peeked head."""
        self._q.remove(req)

    def remove(self, req: Any) -> None:
        self._q.remove(req)  # deque raises ValueError when absent

    def clear(self) -> None:
        self._q.clear()

    def class_depths(self) -> Dict[int, int]:
        depths: Dict[int, int] = {}
        for req in self._q:
            p = int(getattr(req, "priority", 0))
            depths[p] = depths.get(p, 0) + 1
        return depths

    def __len__(self) -> int:
        return len(self._q)

    def __bool__(self) -> bool:
        return bool(self._q)

    def __iter__(self) -> Iterator[Any]:
        return iter(self._q)


class PriorityScheduler:
    """Per-priority-class FIFO queues with an aged weighted pick.

    ``peek`` returns the request the loop should try to admit NEXT: the
    class head with the highest ``priority + waited/aging_s`` score
    (ties break toward the higher static priority, then the earlier
    arrival — deterministic under equal clocks). ``aging_s`` is the
    number of seconds of queueing worth one static priority level."""

    policy = "priority"

    def __init__(self, aging_s: float = 5.0) -> None:
        self.aging_s = max(float(aging_s), 1e-6)
        self._classes: Dict[int, deque] = {}
        self._count = 0

    def _class(self, req: Any) -> deque:
        p = int(getattr(req, "priority", 0))
        q = self._classes.get(p)
        if q is None:
            q = self._classes[p] = deque()
        return q

    def append(self, req: Any) -> None:
        self._class(req).append(req)
        self._count += 1

    def requeue_front(self, req: Any) -> None:
        self._class(req).appendleft(req)
        self._count += 1

    def peek(self) -> Optional[Any]:
        if not self._count:
            return None
        now = time.perf_counter()
        best, best_key = None, None
        for p, q in self._classes.items():
            if not q:
                continue
            head = q[0]
            waited = max(now - float(getattr(head, "enqueue_t", now)), 0.0)
            score = p + waited / self.aging_s
            # deterministic total order: score, static priority, age
            key = (score, p, waited)
            if best_key is None or key > best_key:
                best, best_key = head, key
        return best

    def pop(self, req: Any) -> None:
        """Remove the previously peeked request."""
        self.remove(req)

    def remove(self, req: Any) -> None:
        p = int(getattr(req, "priority", 0))
        # an absent request raises ValueError from the deque itself —
        # the executor's timeout path depends on that contract
        self._classes.get(p, _EMPTY).remove(req)
        self._count -= 1

    def clear(self) -> None:
        self._classes.clear()
        self._count = 0

    def class_depths(self) -> Dict[int, int]:
        return {p: len(q) for p, q in self._classes.items() if q}

    def __len__(self) -> int:
        return self._count

    def __bool__(self) -> bool:
        return self._count > 0

    def __iter__(self) -> Iterator[Any]:
        # highest class first, FIFO inside — the order drain/fail paths
        # enumerate victims in
        for p in sorted(self._classes, reverse=True):
            for req in self._classes[p]:
                yield req


# shared empty deque: PriorityScheduler.remove of an unknown class must
# raise the same ValueError a deque raises, without a raise site here
_EMPTY: deque = deque()


def make_scheduler(policy: str = "fifo", aging_s: float = 5.0):
    """Scheduler factory the executor calls with its spec knobs. An
    unknown policy falls back to FIFO — admission policy must never be
    able to brick a replica at startup."""
    if policy == "priority":
        return PriorityScheduler(aging_s=aging_s)
    return FifoScheduler()


MAX_PREEMPTS = 4


def pick_victim(
    slots: List[Any], min_priority: int, max_preempts: int = MAX_PREEMPTS
) -> Optional[Any]:
    """Choose the slot to preempt so a stalled admission of priority
    ``min_priority`` can take its pages: the LOWEST-priority live row
    strictly below ``min_priority``; within a class, the row preempted
    the FEWEST times so far, youngest first among those (the least sunk
    cost — an old row is closer to retiring on its own).

    The preempt-count ordering plus the ``max_preempts`` cap are the
    anti-thrash guarantee: every spill costs the victim a full chunked
    re-prefill of its whole resident stream, so under sustained
    high-priority pressure the selection rotates victims instead of
    bouncing one row through spill/restore forever, and a row already
    preempted ``max_preempts`` times becomes ineligible — the admission
    then stalls, exactly the pre-preemption behavior.

    Only rows whose prefill is complete are eligible: a mid-prefill row
    has no coherent KV prefix to spill, and a prefill-only (disagg) row
    is about to export and retire anyway. Returns None when no eligible
    victim exists."""
    best, best_key = None, None
    for slot in slots:
        if slot is None:
            continue
        req = slot.req
        if getattr(req, "prefill_only", False):
            continue
        if slot.position < len(req.tokens) or not req.out:
            continue  # prefill not finished: nothing coherent to spill
        p = int(getattr(req, "priority", 0))
        if p >= min_priority:
            continue
        pc = int(getattr(req, "preempt_count", 0))
        if pc >= max_preempts:
            continue  # thrash guard: this row has paid enough re-prefills
        # lowest class, then least-preempted, then youngest
        key = (-p, -pc, float(req.dequeue_t))
        if best_key is None or key > best_key:
            best, best_key = slot, key
    return best
