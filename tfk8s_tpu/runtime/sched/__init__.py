"""Token scheduler for the continuous-batching decode loop (ISSUE 15).

The decode loop (runtime/server.DecodeLoopExecutor) owns slots, pages
and device dispatch; THIS package owns every per-step policy decision
the loop used to hard-code:

- ``scheduler.py`` — admission order. FIFO (the PR-7 behavior,
  bit-identical) or priority-weighted with anti-starvation aging; the
  priority scheduler is also where a stalled high-priority admission
  asks for a preemption victim.
- ``speculative.py`` — speculative decoding (Leviathan et al.): a small
  draft model proposes ``k`` tokens per row, the serving model verifies
  them in ONE packed chunk step, and the accepted prefix (plus the
  target's own correction token) is emitted. Output is token-identical
  to non-speculative decoding by construction — the draft only decides
  how many target tokens each verify step yields.

The package deliberately imports nothing from ``runtime/server.py``
(the executor imports the scheduler, never the reverse), so the typed
error taxonomy stays rooted in the server module.
"""

from tfk8s_tpu.runtime.sched.scheduler import (
    FifoScheduler,
    PriorityScheduler,
    make_scheduler,
)
from tfk8s_tpu.runtime.sched.speculative import SpeculativeEngine

__all__ = [
    "FifoScheduler",
    "PriorityScheduler",
    "SpeculativeEngine",
    "make_scheduler",
]
