"""Training-progress reporting: trainer → kubelet → pod status → operator
/metrics (VERDICT r2 next #8; SURVEY.md §5 metrics row).

The hermetic node runs each pod's entrypoint on its own kubelet thread,
so progress routes the same way the log tail does (runtime/kubelet.py
_PodLogRouter): the trainer calls :func:`report` from the pod thread,
the kubelet's flush loop snapshots the thread's latest values into
``pod.status.training``, and the operator mirrors them into per-job
gauges/histograms on its /metrics endpoint. Outside a kubelet (bench,
direct run_task) reporting is a cheap dict write nobody reads.

On a real multi-host deployment the same contract rides the identical
path: the trainer process reports, the node agent publishes to pod
status, the operator scrapes — no side channel."""

from __future__ import annotations

import threading
from typing import Dict, Optional

_LOCK = threading.Lock()
_BY_THREAD: Dict[int, Dict[str, float]] = {}


def report(**values: float) -> None:
    """Merge numeric progress values for the CALLING thread (the pod
    entrypoint thread). Keys are metric suffixes, e.g. ``step``,
    ``steps_per_sec``, ``examples_per_sec``, ``step_seconds``."""
    ident = threading.get_ident()
    clean = {k: float(v) for k, v in values.items()}
    with _LOCK:
        _BY_THREAD.setdefault(ident, {}).update(clean)


def snapshot(ident: Optional[int] = None) -> Dict[str, float]:
    """Latest values for ``ident`` (defaults to the calling thread)."""
    if ident is None:
        ident = threading.get_ident()
    with _LOCK:
        return dict(_BY_THREAD.get(ident, {}))


def clear(ident: Optional[int] = None) -> None:
    if ident is None:
        ident = threading.get_ident()
    with _LOCK:
        _BY_THREAD.pop(ident, None)
