"""Worker-side launcher: the data plane's entry contract.

Decodes the env the trainer rendered into each pod (trainer/replicas.py) —
the TPU-native replacement for TF_CONFIG (SURVEY.md §3.3): instead of a TF
runtime reading ``{cluster, job, task_index}`` and starting gRPC servers,
each pod runs ``jax.distributed.initialize`` against the coordinator
service, attaches to its slice's chips, and builds the job's logical mesh.

Hermetic mode (cpu accelerators / single process) skips distributed init
and uses the host's (possibly virtual) devices — the same code path the
tests and the local kubelet exercise, per the fake-backed test philosophy
of SURVEY.md §4.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional

import jax

from tfk8s_tpu.parallel.mesh import MeshConfig
from tfk8s_tpu.utils.logging import get_logger

log = get_logger("launcher")


@dataclasses.dataclass
class ProcessContext:
    """Everything a training process learns from its pod env."""

    job_name: str = "local"
    namespace: str = "default"
    replica_type: str = "Worker"
    replica_index: int = 0
    process_id: int = 0
    num_processes: int = 1
    coordinator_address: str = ""
    accelerator: str = ""
    num_slices: int = 1
    slice_id: str = ""
    host_index: int = 0
    gang_restarts: int = 0
    # Elastic world version (trainer/replicas.py TFK8S_WORLD_VERSION):
    # bumped by the controller on every gang resize; nonzero means this
    # incarnation is a re-formed world and must resume from checkpoint.
    world_version: int = 0
    checkpoint_dir: str = ""
    mesh: Optional[MeshConfig] = None

    @classmethod
    def from_env(cls, env: Optional[Dict[str, str]] = None) -> "ProcessContext":
        e = dict(os.environ) if env is None else env
        mesh = MeshConfig.from_env(e) if "TFK8S_MESH" in e else None
        return cls(
            job_name=e.get("TFK8S_JOB_NAME", "local"),
            namespace=e.get("TFK8S_NAMESPACE", "default"),
            replica_type=e.get("TFK8S_REPLICA_TYPE", "Worker"),
            replica_index=int(e.get("TFK8S_REPLICA_INDEX", "0")),
            process_id=int(e.get("TFK8S_PROCESS_ID", "0")),
            num_processes=int(e.get("TFK8S_NUM_PROCESSES", "1")),
            coordinator_address=e.get("TFK8S_COORDINATOR_ADDRESS", ""),
            accelerator=e.get("TFK8S_ACCELERATOR", ""),
            num_slices=int(e.get("TFK8S_NUM_SLICES", "1")),
            slice_id=e.get("TFK8S_SLICE_ID", ""),
            host_index=int(e.get("TFK8S_HOST_INDEX", "0")),
            gang_restarts=int(e.get("TFK8S_GANG_RESTARTS", "0")),
            world_version=int(e.get("TFK8S_WORLD_VERSION", "0")),
            checkpoint_dir=e.get("TFK8S_CHECKPOINT_DIR", ""),
            mesh=mesh,
        )

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0

    @property
    def resuming(self) -> bool:
        """True on a gang restart OR after an elastic resize — either way
        the process must restore from the last committed checkpoint
        (SURVEY.md §5 checkpoint/resume contract)."""
        return self.gang_restarts > 0 or self.world_version > 0


def force_platform(platform: str, num_devices: Optional[int] = None) -> bool:
    """Best-effort JAX platform switch before first backend use — THE one
    copy of the platform-latch workaround (sitecustomize imports jax at
    interpreter startup, so env vars alone don't switch platforms; the
    config must be updated in-process before any device query). With
    ``num_devices`` on cpu, provisions that many virtual host devices.
    Returns False when the backend was already initialized (config
    latched) — callers decide whether the devices that exist suffice."""
    if num_devices and platform == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={num_devices}"
            ).strip()
    try:
        jax.config.update("jax_platforms", platform)
    except Exception:  # backend already initialized
        return False
    if num_devices is not None and platform == "cpu":
        try:
            jax.config.update("jax_num_cpu_devices", int(num_devices))
        except Exception:
            # older jax has no jax_num_cpu_devices; the XLA_FLAGS
            # host-device-count flag set above provisions the devices
            pass
    return True


def initialize_distributed(ctx: ProcessContext, env: Optional[Dict[str, str]] = None) -> None:
    """Real multi-host path: one JAX process per TPU VM host. Gated on
    ``TFK8S_DISTRIBUTED=1`` so hermetic in-process runs (threads sharing one
    JAX runtime) never try to bind coordination ports."""
    e = dict(os.environ) if env is None else env
    if ctx.num_processes <= 1 or e.get("TFK8S_DISTRIBUTED") != "1":
        return
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None and is_init():
        return  # already initialized (idempotent re-entry)
    log.info(
        "jax.distributed.initialize(coordinator=%s, num_processes=%d, process_id=%d)",
        ctx.coordinator_address, ctx.num_processes, ctx.process_id,
    )
    try:
        jax.distributed.initialize(
            coordinator_address=ctx.coordinator_address,
            num_processes=ctx.num_processes,
            process_id=ctx.process_id,
        )
    except RuntimeError as exc:
        # older JAX without is_initialized(): double-init raises here
        if "already initialized" not in str(exc).lower():
            raise
        log.info("jax.distributed already initialized; continuing")


def build_mesh(ctx: ProcessContext):
    """The job's logical mesh over the job's devices. Multislice jobs
    (``TFK8S_NUM_SLICES`` > 1) get slice-major device order and the
    DCN-axis validation of ``MeshConfig.slice_axis_split`` — data/
    pipeline traffic crosses DCN, tensor/sequence/expert stay on ICI."""
    cfg = ctx.mesh or MeshConfig.create(data=jax.device_count())
    return cfg.build(num_slices=max(ctx.num_slices, 1))
