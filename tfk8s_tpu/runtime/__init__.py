"""Data-plane runtime: entrypoint registry, local kubelet (hermetic node
agent), JAX distributed launcher, mesh construction, train loop, and
checkpointing (SURVEY.md §7 step 5).
"""

from tfk8s_tpu.runtime.kubelet import LocalKubelet  # noqa: F401
from tfk8s_tpu.runtime import registry  # noqa: F401
