"""KV page handoff between the prefill and decode pools (disaggregated
serving, Splitwise/DistServe-style).

A prefill-pool replica runs chunked prefill to completion, picks the
request's FIRST output token, then exports the warm KV state —
:class:`KVHandoffBuffer` carries the prompt, the first token, the
generation budget, the page-aligned prefix digest chain
(runtime/paging.prefix_digest_chain), and the per-layer K/V rows of
every prompt page. A decode-pool replica imports the buffer straight
into a :class:`~tfk8s_tpu.runtime.server.DecodeLoopExecutor` slot: the
row starts decoding at position ``len(tokens)`` with the prefill
replica's pick as its last token, bit-identical to having prefilled
locally (same params — ``version`` is checked — same K/V bytes, same
packed decode step; test-pinned against single-replica
``gpt.generate``).

The buffer is SELF-DESCRIBING — a fixed magic, a JSON header (shapes,
dtypes, tokens, digests), then the raw leaf bytes — so the transfer
seam is a dumb byte mover. :class:`KVTransport` is that seam:
:class:`LocalKVTransport` is the one-box memcpy implementation (a
serialize/deserialize round trip, which is also what proves the buffer
self-describes). On a real TPU pod the same interface fronts the
device-to-device path: the exporter's pages are already contiguous
``[page*ps, (page+1)*ps)`` row ranges of the pool leaves, so a
production transport maps each leaf slice to one ICI/DMA transfer
(or a NIC send between pools on different slices) and skips the host
round trip entirely — the header still travels, the K/V bytes move
device-to-device.

Integrity is end-to-end, not transport-trusted: :meth:`KVHandoffBuffer
.verify` recomputes the digest chain from the tokens it carries and
refuses a buffer whose chain (or leaf sizes) don't match —
:class:`HandoffError`, a typed wire error the gateway maps like any
other dispatch failure (re-pick a decode replica, bounded retries).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, List, Tuple

from tfk8s_tpu.runtime.paging import prefix_digest_chain

#: buffer wire format identity (bump on layout change)
MAGIC = b"TFK8SKV1"


class HandoffError(Exception):
    """A KV handoff buffer that cannot be imported: corrupt framing,
    digest-chain mismatch, or a shape/version that doesn't match the
    importing replica. The gateway treats it like a failed dispatch hop:
    the buffer it still holds is re-sent to another decode replica
    under the bounded retry budget."""


@dataclass(eq=False)
class KVHandoffBuffer:
    """One request's warm prefill state, ready to cross the pool seam."""

    #: model identity (the serve checkpoint ref) — import refuses a
    #: buffer prefilled under different params; bit-identity would break
    version: str
    page_size: int
    #: full prompt (plain ints — hashable identically on both sides)
    tokens: List[int]
    #: the first OUTPUT token, picked at the last prompt position
    last_token: int
    #: decode-side generation budget (the first token counts against it)
    gen_budget: int
    #: chained digests of the FULL prompt pages (integrity + affinity)
    digests: List[str] = field(default_factory=list)
    #: per-layer K/V leaves in tree order, each
    #: ``[n_prompt_pages * page_size, heads, head_dim]`` — page ``k`` of
    #: the prompt is rows ``[k*ps, (k+1)*ps)`` of every leaf
    kv: List[Any] = field(default_factory=list)

    @property
    def n_pages(self) -> int:
        """Prompt pages carried (including a trailing partial page)."""
        return -(-len(self.tokens) // self.page_size)

    def verify(self) -> None:
        """End-to-end integrity: recompute the digest chain from the
        tokens the buffer carries and check every leaf covers exactly
        the prompt pages. Raises :class:`HandoffError` on any mismatch."""
        if self.page_size < 1 or not self.tokens:
            raise HandoffError(
                f"malformed buffer: page_size={self.page_size}, "
                f"{len(self.tokens)} token(s)"
            )
        want = prefix_digest_chain(
            self.tokens, self.page_size, len(self.tokens) // self.page_size
        )
        if list(self.digests) != want:
            raise HandoffError(
                "digest chain mismatch — buffer tokens and K/V disagree "
                f"({len(self.digests)} carried vs {len(want)} recomputed)"
            )
        rows = self.n_pages * self.page_size
        for i, leaf in enumerate(self.kv):
            if getattr(leaf, "shape", (None,))[0] != rows:
                raise HandoffError(
                    f"kv leaf {i} covers {getattr(leaf, 'shape', None)} — "
                    f"expected {rows} prompt rows"
                )

    @classmethod
    def prefix(cls, version: str, page_size: int, tokens: List[int],
               digests: List[str], kv: List[Any]) -> "KVHandoffBuffer":
        """A PREFIX-resident buffer (KV tier demotion/peer export,
        runtime/kvtier): page-aligned cached-prefix K/V with no
        generation state attached. ``gen_budget=0`` marks it
        non-admittable — ``submit_handoff`` refuses a zero budget, so a
        prefix buffer can only re-enter through the warm-insert path
        (cache adoption), never start a decode row by itself."""
        if len(tokens) % page_size != 0:
            raise HandoffError(
                f"prefix buffer must be page-aligned: {len(tokens)} "
                f"token(s) @ page_size {page_size}"
            )
        buf = cls(
            version=version, page_size=page_size, tokens=list(tokens),
            last_token=0, gen_budget=0, digests=list(digests), kv=kv,
        )
        buf.verify()
        return buf

    # -- wire form -----------------------------------------------------------

    def to_bytes(self) -> bytes:
        """MAGIC + u32 header length + JSON header + raw leaf bytes
        (C-order, header order). Self-describing: the importer needs
        nothing but these bytes."""
        import numpy as np

        leaves = [np.ascontiguousarray(leaf) for leaf in self.kv]
        header = json.dumps({
            "version": self.version,
            "page_size": self.page_size,
            "tokens": [int(t) for t in self.tokens],
            "last_token": int(self.last_token),
            "gen_budget": int(self.gen_budget),
            "digests": list(self.digests),
            "leaves": [
                {"dtype": str(leaf.dtype), "shape": list(leaf.shape)}
                for leaf in leaves
            ],
        }).encode()
        parts = [MAGIC, len(header).to_bytes(4, "big"), header]
        parts.extend(leaf.tobytes() for leaf in leaves)
        return b"".join(parts)

    @classmethod
    def from_bytes(cls, data: bytes) -> "KVHandoffBuffer":
        """Decode and :meth:`verify` a serialized buffer."""
        import numpy as np

        if data[: len(MAGIC)] != MAGIC:
            raise HandoffError("not a KV handoff buffer (bad magic)")
        off = len(MAGIC)
        hlen = int.from_bytes(data[off:off + 4], "big")
        off += 4
        try:
            header = json.loads(data[off:off + hlen].decode())
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise HandoffError(f"corrupt buffer header: {e}") from e
        off += hlen
        kv = []
        for spec in header.get("leaves", []):
            dtype = np.dtype(spec["dtype"])
            shape = tuple(spec["shape"])
            count = int(np.prod(shape)) if shape else 1
            end = off + count * dtype.itemsize
            if end > len(data):
                raise HandoffError("truncated buffer (leaf bytes missing)")
            kv.append(
                np.frombuffer(data[off:end], dtype=dtype).reshape(shape)
            )
            off = end
        buf = cls(
            version=header.get("version", ""),
            page_size=int(header.get("page_size", 0)),
            tokens=list(header.get("tokens", [])),
            last_token=int(header.get("last_token", 0)),
            gen_budget=int(header.get("gen_budget", 0)),
            digests=list(header.get("digests", [])),
            kv=kv,
        )
        buf.verify()
        return buf


class KVTransport:
    """The pool-to-pool seam. ``transfer`` moves one buffer and returns
    ``(buffer_at_destination, bytes_moved)``. Implementations own HOW the
    bytes move; callers own the retry/rerouting policy around it."""

    def transfer(self, buf: KVHandoffBuffer) -> Tuple[KVHandoffBuffer, int]:
        raise NotImplementedError


class LocalKVTransport(KVTransport):
    """One-box transport: a full serialize/deserialize round trip (the
    memcpy seam). Deliberately NOT a pass-through of the live object —
    the round trip is what proves the buffer self-describes and what a
    real device-to-device transport replaces."""

    def transfer(self, buf: KVHandoffBuffer) -> Tuple[KVHandoffBuffer, int]:
        wire = buf.to_bytes()
        return KVHandoffBuffer.from_bytes(wire), len(wire)


__all__ = [
    "HandoffError",
    "KVHandoffBuffer",
    "KVTransport",
    "LocalKVTransport",
    "MAGIC",
]
