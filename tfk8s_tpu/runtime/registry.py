"""Entrypoint registry: maps a ContainerSpec.entrypoint string to a Python
callable. The local/hermetic backend's analogue of an OCI image + command —
the thing the kubelet 'pulls and starts' (SURVEY.md §3.3 process boundary).

Entrypoints are ``"module.path:function"`` strings resolved by import, or
names registered explicitly (tests). The callable receives the pod's env
dict (the JAX coordination contract of trainer/replicas.py) and optionally
a ``stop`` threading.Event (second positional arg) for graceful teardown.
"""

from __future__ import annotations

import importlib
import inspect
import threading
from typing import Callable, Dict, Optional

_REGISTRY: Dict[str, Callable] = {}


class PodDrained(Exception):
    """Raised by an entrypoint that honored a reclaim notice: it finished
    its in-flight work, committed its drain checkpoint, and is exiting
    GRACEFULLY. The kubelet maps this to ``PodPhase.DRAINED`` (not
    Failed), which is what lets the job controller resize the gang
    instead of burning ``backoff_limit``."""


def register(name: str, fn: Optional[Callable] = None):
    """``register("name", fn)`` or ``@register("name")`` decorator."""
    if fn is None:
        def deco(f):
            _REGISTRY[name] = f
            return f
        return deco
    _REGISTRY[name] = fn
    return fn


def resolve(entrypoint: str) -> Callable:
    if entrypoint in _REGISTRY:
        return _REGISTRY[entrypoint]
    if ":" in entrypoint:
        mod_name, attr = entrypoint.split(":", 1)
        mod = importlib.import_module(mod_name)
        fn = getattr(mod, attr)
        if not callable(fn):
            raise TypeError(f"entrypoint {entrypoint!r} is not callable")
        return fn
    raise KeyError(f"entrypoint {entrypoint!r} is neither registered nor importable")


def call(fn: Callable, env: Dict[str, str], stop: threading.Event) -> None:
    """Invoke with (env) or (env, stop) depending on the signature."""
    try:
        sig = inspect.signature(fn)
        nparams = len([
            p for p in sig.parameters.values()
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
        ])
    except (TypeError, ValueError):
        nparams = 1
    if nparams >= 2:
        fn(env, stop)
    else:
        fn(env)
