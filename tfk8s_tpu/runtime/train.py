"""The sharded training loop: TrainTask -> pjit'd steps over a mesh.

This is the data plane the reference never had (its operator treats the TF
runtime as a black box, k8s-operator.md:6; SURVEY.md L0). Design rules, per
the TPU execution model:

- ONE jitted train step, traced once: optimizer update fused with the
  backward pass; no data-dependent Python control flow inside.
- Shardings are explicit at the jit boundary (``in_shardings`` /
  ``out_shardings`` from the task's logical-axis annotations), so GSPMD
  emits all collectives — gradient all-reduce over ``data`` rides ICI
  exactly as the north star prescribes (BASELINE.json).
- The step donates the state buffer (params/opt-state update in place —
  HBM is the budget).
- Host work per step is one synthetic-batch build + ``device_put`` with the
  batch sharding; everything else stays on device.

``run_task`` is the glue entrypoints use: env contract -> mesh -> optional
checkpoint restore (gang restart) -> fit -> final metrics.
"""

from __future__ import annotations

import collections
import dataclasses
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tfk8s_tpu.obs.trace import TRACEPARENT_ENV, get_tracer
from tfk8s_tpu.parallel import sharding as shd
from tfk8s_tpu.parallel.mesh import AXIS_DATA, AXIS_FSDP, MeshConfig
from tfk8s_tpu.runtime import progress
from tfk8s_tpu.runtime.checkpoint import Checkpointer
from tfk8s_tpu.runtime.launcher import ProcessContext, build_mesh, initialize_distributed
from tfk8s_tpu.runtime.registry import PodDrained
from tfk8s_tpu.utils.logging import get_logger

log = get_logger("train")

# A step counts as input-starved when the host wait for its batch exceeds
# this fraction of the step's wall time — the device sat idle waiting on
# input synthesis/IO rather than compute (the alert the windowed
# input_mb_per_sec report exists to explain).
_INPUT_STARVED_FRACTION = 0.2


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any


@dataclasses.dataclass
class TrainTask:
    """What a model family provides to be trainable (SURVEY.md §7 step 6).

    ``init`` returns a flax variable tree whose leaves may carry
    ``Partitioned`` metadata; ``loss_fn(params, batch, rng) -> (loss, aux)``
    computes the scalar objective; ``make_batch(np_rng, batch_size)``
    produces one host-side synthetic batch (hermetic: no dataset I/O)."""

    name: str
    init: Callable[[jax.Array], Any]
    loss_fn: Callable[[Any, Any, jax.Array], Tuple[jax.Array, Dict[str, jax.Array]]]
    make_batch: Callable[[np.random.Generator, int], Any]
    batch_size: int = 32
    rules: Sequence[Tuple[str, Any]] = shd.DEFAULT_RULES
    # metric name -> target the run should reach (convergence check)
    targets: Dict[str, float] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class TrainConfig:
    steps: int = 100
    learning_rate: float = 1e-3
    weight_decay: float = 0.0
    warmup_steps: int = 0
    log_every: int = 20
    checkpoint_every: int = 0
    checkpoint_dir: str = ""
    seed: int = 0
    resume: bool = False
    optimizer: Optional[optax.GradientTransformation] = None
    # Gradient accumulation: >1 splits each batch into this many
    # microbatches scanned INSIDE the jitted step (grads averaged, one
    # optimizer update) — the standard large-effective-batch /
    # HBM-relief trade. The host batch is reshaped to
    # [accum, B/accum, ...] and sharding moves to the microbatch dim, so
    # per-device microbatches stay contiguous (no reshape collectives).
    grad_accum_steps: int = 1
    # jax.profiler trace output dir (SURVEY.md §5 'Tracing: ABSENT' in the
    # reference — the build's addition); empty disables
    profile_dir: str = ""
    profile_skip: int = 3  # steps to skip (compile/warmup) before tracing
    profile_steps: int = 5  # traced step count
    # Input pipeline depth: >1 runs host batch synthesis + device_put on a
    # background thread, ``prefetch`` batches ahead of the consuming step
    # (double-buffering at 2) — host input work overlaps device compute
    # instead of serializing before every step. <=1 is the synchronous
    # path. The batch order (and thus the rng stream) is identical either
    # way; only the overlap changes.
    prefetch: int = 2
    # Async-dispatch window: how many steps may be in flight before the
    # loop waits on the oldest step's output. With the input pipeline
    # removing all host-side throttle, an unbounded loop enqueues every
    # remaining step at Python speed — the backend's inflight state grows
    # without bound (observed: CPU-client abort after ~200 unsynced
    # steps; on real chips it is an HBM liability). The wait is on a
    # SCALAR from ``max_inflight`` steps ago: zero transfer, no pipeline
    # bubble as long as the window exceeds the dispatch depth. None =
    # backend-aware default: the CPU client aborts somewhere between 16
    # and 48 inflight executions (measured), so 16 there; real TPU
    # runtimes take deep queues and every wait through the remote tunnel
    # costs a round trip, so 256 on tpu/axon.
    max_inflight: Optional[int] = None
    # Device-loop chunking: >1 dispatches this many steps as ONE jitted
    # ``lax.scan`` over a stacked batch — the classic TPU host-loop
    # pattern, amortizing per-dispatch overhead (through the remote
    # tunnel each dispatch costs ~10 ms; locally it tightens the host
    # loop the same way). Chunks never cross a log/checkpoint boundary,
    # the rng stream and trajectory are bit-identical to per-step
    # dispatch (the step fold happens inside the step), and stop events
    # are honored at chunk granularity. Forced to 1 while profiling so
    # the trace keeps per-step annotations. Costs k staged batches of
    # device memory.
    scan_steps: int = 1
    # Input synthesis topology (the TF_CONFIG-era per-task input division,
    # k8s-operator.md:6 — each worker owns its own input shard):
    # - "replicated": every process builds the FULL global batch from one
    #   sequential rng stream (single-host default; on multi-host it
    #   replicates all input work and global-batch host memory per host).
    # - "per_host": the global batch is the ordered concatenation of
    #   ``input_shards`` independently-seeded shard streams; each process
    #   synthesizes ONLY the shards covering its addressable rows and the
    #   global array is assembled with
    #   ``jax.make_array_from_process_local_data`` — host input work and
    #   memory scale 1/hosts.
    # - "files": batches come from RECORD SHARDS (tfk8s_tpu/data) named by
    #   ``input_files`` instead of the task's synthetic make_batch; on
    #   multi-process runs each process opens ONLY its round-robin share
    #   of the file list and reads just its addressable rows' worth of
    #   records per step (the TF_CONFIG-era per-task input division over
    #   real files), assembled with make_array_from_process_local_data.
    #   Record order is the dataset's seeded epoch shuffle; resume
    #   fast-forwards the iterator to the restart step without reading
    #   the skipped records.
    # None = auto: "files" when input_files is set, else "per_host" when
    # jax.process_count() > 1.
    # The per_host batch content depends only on (seed, step, input_shards)
    # — NOT on the process topology — so any process count produces the
    # same global stream (a single process can emulate any shard layout
    # bit-for-bit; tests/test_distributed.py proves 1-proc == 2-proc).
    # (files mode makes no such topology-independence claim: the file→host
    # assignment changes with the process count.)
    input_mode: Optional[str] = None
    # number of logical input shards in per_host mode (None = process
    # count); must divide batch_size (and batch_size/input_shards must be
    # a multiple of grad_accum_steps)
    input_shards: Optional[int] = None
    # comma-separated record-file paths/globs for input_mode="files"
    # (TFK8S_INPUT_FILES); examples must decode to the task's batch schema
    input_files: Optional[str] = None
    # What the record shards HOLD (TFK8S_INPUT_FORMAT):
    # - "array" (default): example.py array dicts decoding straight to
    #   the task's batch schema (the text families' packed token rows);
    # - "image": compressed JPEG/PNG image Examples (data/images) —
    #   decoded + augmented on a worker pool into the
    #   {"image": f32 [B,S,S,3], "label": i32 [B]} schema the vision
    #   tasks train on, replacing their synthetic generator. The target
    #   image size is read off the task's own example batch.
    input_format: str = "array"
    # image-decode pool width (TFK8S_DECODE_WORKERS; None = auto)
    decode_workers: Optional[int] = None
    # random-resized-crop area floor (TFK8S_AUG_MIN_SCALE): 0.08 is the
    # ImageNet-standard augmentation; small/synthetic image sets train
    # better around 0.3-0.6 (see data/images/transforms.train_transform)
    aug_min_scale: float = 0.08

    # Learning-rate decay after warmup: "constant" (default), "cosine"
    # (to min_lr_ratio * learning_rate over decay_steps), or "linear".
    # decay_steps=None decays over the remaining run (steps - warmup).
    lr_schedule: str = "constant"
    decay_steps: Optional[int] = None
    min_lr_ratio: float = 0.0

    def make_schedule(self):
        """The scalar step->lr schedule the optimizer runs on (exposed so
        tests and logging can evaluate it directly)."""
        peak, warm = self.learning_rate, max(self.warmup_steps, 0)
        decay = self.decay_steps or max(self.steps - warm, 1)
        floor = peak * self.min_lr_ratio
        if self.lr_schedule == "constant":
            main = optax.constant_schedule(peak)
        elif self.lr_schedule == "cosine":
            main = optax.cosine_decay_schedule(
                peak, decay, alpha=self.min_lr_ratio
            )
        elif self.lr_schedule == "linear":
            main = optax.linear_schedule(peak, floor, decay)
        else:
            raise ValueError(
                f"unknown lr_schedule {self.lr_schedule!r} "
                "(constant | cosine | linear)"
            )
        if warm > 0:
            return optax.join_schedules(
                [optax.linear_schedule(0.0, peak, warm), main], [warm]
            )
        return main

    def make_optimizer(self) -> optax.GradientTransformation:
        if self.optimizer is not None:
            return self.optimizer
        return optax.adamw(self.make_schedule(), weight_decay=self.weight_decay)


def _suffix_match_shardings(abstract_tree, params_paths, mesh):
    """Sharding tree for an optimizer state: leaves whose (path-suffix,
    shape) match a parameter reuse that parameter's sharding (adam's mu/nu
    mirror the param tree); everything else is replicated."""

    def one(path, leaf):
        key = tuple(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        shape = getattr(leaf, "shape", None)
        for ppath, (psharding, pshape) in params_paths.items():
            if shape == pshape and key[-len(ppath):] == ppath:
                return psharding
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(one, abstract_tree)


def _expand_input_files(spec: str) -> List[str]:
    """Expand a comma-separated list of record-file paths/globs (the
    TFK8S_INPUT_FILES / TFK8S_EVAL_INPUT_FILES value) into a concrete
    path list; a glob matching nothing fails loudly."""
    import glob as globlib

    paths: List[str] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if any(c in part for c in "*?["):
            hits = sorted(globlib.glob(part))
            if not hits:
                raise ValueError(f"input pattern matched nothing: {part!r}")
            paths.extend(hits)
        else:
            paths.append(part)
    if not paths:
        raise ValueError(f"input file spec is empty: {spec!r}")
    return paths


class _CheckedFileStream:
    """Iterator adapter over a RecordDataset iterator that validates the
    FIRST decoded batch against the task's batch schema (structure,
    per-row shapes, dtypes, local row count) — a records/task mismatch
    must fail with a schema message, not a shape error deep inside jit."""

    def __init__(self, it, want_example, local_rows: int, dataset=None):
        self._it = it
        self._want = want_example
        self._rows = local_rows
        self._checked = False
        # exposes the dataset's bytes_read so the fit loop's windowed
        # progress report can surface input MB/s (input-starvation alert)
        self.dataset = dataset

    def __iter__(self):
        return self

    def __next__(self):
        raw = next(self._it)
        if not self._checked:
            self._checked = True
            got_def = jax.tree_util.tree_structure(raw)
            want_def = jax.tree_util.tree_structure(self._want)
            if got_def != want_def:
                raise ValueError(
                    f"record schema {got_def} does not match the task's "
                    f"batch schema {want_def}"
                )
            for g, w in zip(
                jax.tree_util.tree_leaves(raw),
                jax.tree_util.tree_leaves(self._want),
            ):
                ga, wa = np.asarray(g), np.asarray(w)
                if ga.shape[1:] != wa.shape[1:] or ga.dtype != wa.dtype:
                    raise ValueError(
                        "record example mismatch: got "
                        f"{ga.dtype}{list(ga.shape[1:])} per row, "
                        f"task expects {wa.dtype}{list(wa.shape[1:])}"
                    )
                if ga.shape[0] != self._rows:
                    raise ValueError(
                        f"dataset produced {ga.shape[0]} rows, "
                        f"expected {self._rows}"
                    )
        return raw

    def close(self) -> None:
        self._it.close()
        if self.dataset is not None:
            # releases any decode worker pool (images input); no-op for
            # plain record datasets
            self.dataset.close()


def _image_geometry(want_example) -> int:
    """Target decode size from a vision task's own example batch: the
    ``image`` leaf must be square [*, S, S, 3] float32 — the contract
    ``models/resnet.py``/``models/vit.py`` batches satisfy. Failing here
    names the actual mismatch instead of letting a non-vision task fall
    into the image decoder."""
    leaf = (want_example or {}).get("image") if isinstance(want_example, dict) else None
    if leaf is None:
        raise ValueError(
            'input_format="image" needs a task whose batch schema has an '
            '"image" leaf (the vision families); this task has '
            f"{sorted(want_example.keys()) if isinstance(want_example, dict) else type(want_example)}"
        )
    shape = np.asarray(leaf).shape
    if len(shape) != 4 or shape[1] != shape[2] or shape[3] != 3:
        raise ValueError(
            f"image input needs a square [B, S, S, 3] image leaf, task "
            f"expects {list(shape)}"
        )
    return int(shape[1])


def _open_image_dataset(
    paths, local_rows: int, want_example, *, train: bool, seed: int = 0,
    workers: Optional[int] = None, min_scale: float = 0.08,
    host_index: int = 0, num_hosts: int = 1,
):
    """Build the decode+augment pipeline (data/images.ImageDataset) over
    ``paths`` sized to this process's rows, targeting the geometry the
    task's batch schema declares."""
    from tfk8s_tpu.data.images import ImageDataset

    return ImageDataset(
        paths,
        batch_size=local_rows,
        image_size=_image_geometry(want_example),
        train=train,
        workers=workers,
        host_index=host_index,
        num_hosts=num_hosts,
        seed=seed,
        min_scale=min_scale,
    )


class _BatchPrefetcher:
    """Bounded producer thread for prepared HOST batches.

    The producer synthesizes and shape-prepares batches (in step order,
    so the rng stream matches the synchronous path exactly); the
    CONSUMER does the ``device_put`` on dequeue. Keeping every JAX call
    on the consumer thread matters: concurrent ``device_put`` against a
    running jitted step intermittently aborts the CPU client (observed
    as suite-killing ``Fatal Python error: Aborted``), and on TPU the
    transfer is an async enqueue anyway — the overlap that pays is the
    HOST synthesis, which is exactly what the thread offloads. Producer
    exceptions re-raise in the consumer."""

    _DONE = object()

    def __init__(self, make_batch: Callable[[int], Any], start: int, stop_step: int, depth: int):
        self._make = make_batch
        self._q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._stop = threading.Event()
        self._exc: Optional[BaseException] = None
        self._range = (start, stop_step)
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="batch-prefetch"
        )
        self._thread.start()

    def _run(self) -> None:
        try:
            for step in range(*self._range):
                if self._stop.is_set():
                    return
                item = self._make(step)
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # noqa: BLE001 — re-raised in consumer
            self._exc = e
        finally:
            while not self._stop.is_set():
                try:
                    self._q.put(self._DONE, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def get(self) -> Any:
        item = self._q.get()
        if item is self._DONE:
            if self._exc is not None:
                raise self._exc
            raise RuntimeError("batch prefetcher exhausted early")
        return item

    def depth(self) -> int:
        """Batches currently staged (the input-starvation early-warning:
        pinned at 0 means the producer, not the device, is the
        bottleneck)."""
        return self._q.qsize()

    def close(self) -> None:
        self._stop.set()
        # unblock a producer stuck on a full queue
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)


class Trainer:
    def __init__(self, task: TrainTask, config: TrainConfig, mesh: Mesh):
        self.task = task
        self.config = config
        self.mesh = mesh
        self.optimizer = config.make_optimizer()
        # set by fit() when a reclaim notice drained the run: the step the
        # drain checkpoint committed at (run_task turns this into a
        # PodDrained exit instead of a missed-target failure)
        self.drained_at: Optional[int] = None
        # set by fit() in per-host input mode: (shard_lo, shard_hi, total)
        self.input_shard_range: Optional[Tuple[int, int, int]] = None
        self._per_host_active = False
        self._stack_fns: Dict[int, Any] = {}  # arity -> jitted metric stack
        self._build()

    # -- sharding/jit plumbing ---------------------------------------------

    def _build(self) -> None:
        task, mesh = self.task, self.mesh
        rng = jax.random.key(self.config.seed)

        boxed_abstract = jax.eval_shape(task.init, rng)
        self.param_shardings = shd.params_shardings(boxed_abstract, mesh, task.rules)
        abstract_params = shd.unbox(boxed_abstract)

        # path -> (sharding, shape), for matching optimizer-state leaves
        flat = jax.tree_util.tree_flatten_with_path(abstract_params)[0]
        flat_sh = jax.tree_util.tree_flatten_with_path(self.param_shardings)[0]
        params_paths = {}
        for (path, leaf), (_, s) in zip(flat, flat_sh):
            key = tuple(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
            params_paths[key] = (s, leaf.shape)

        abstract_opt = jax.eval_shape(self.optimizer.init, abstract_params)
        self.opt_shardings = _suffix_match_shardings(abstract_opt, params_paths, mesh)
        self.state_shardings = TrainState(
            step=NamedSharding(mesh, P()),
            params=self.param_shardings,
            opt_state=self.opt_shardings,
        )

        def _init(r) -> TrainState:
            with shd.activation_sharding(mesh, task.rules):
                params = shd.unbox(task.init(r))
                return TrainState(
                    step=jnp.zeros((), jnp.int32),
                    params=params,
                    opt_state=self.optimizer.init(params),
                )

        self._init_fn = jax.jit(_init, out_shardings=self.state_shardings)

        accum = max(self.config.grad_accum_steps, 1)
        if accum > 1 and task.batch_size % accum:
            raise ValueError(
                f"grad_accum_steps={accum} does not divide "
                f"batch_size={task.batch_size}"
            )

        def _grads_of(params, batch, r):
            return jax.value_and_grad(
                lambda p: task.loss_fn(p, batch, r), has_aux=True
            )(params)

        def _step(state: TrainState, batch, r):
            # Fold the step index into the rng INSIDE the jit: callers
            # pass one base key for the whole run, so the fit loop does
            # zero per-step host-side key computations (each of which is
            # a separate device dispatch — ruinous through a remote
            # tunnel, and wasted latency anywhere).
            r = jax.random.fold_in(r, state.step)
            # Establish the activation-constraint scope for the trace:
            # model code pins [b,l,e] activations to the canonical layout
            # (batch over data+fsdp) via shd.act_constraint, which is a
            # no-op outside this context (see parallel/sharding.py).
            with shd.activation_sharding(mesh, task.rules):
                return _step_inner(state, batch, r)

        def _step_inner(state: TrainState, batch, r):
            if accum == 1:
                (loss, aux), grads = _grads_of(state.params, batch, r)
            else:
                # batch leaves arrive [accum, B/accum, ...] (scalars pass
                # through unstacked); scan the microbatches, summing
                # grads/metrics in fp32 carries
                def micro(i):
                    return jax.tree_util.tree_map(
                        lambda x: x if jnp.ndim(x) == 0 else x[i], batch
                    )

                f32 = lambda t: jax.tree_util.tree_map(
                    lambda a: a.astype(jnp.float32), t
                )
                (loss0, aux0), g0 = _grads_of(
                    state.params, micro(0), jax.random.fold_in(r, 0)
                )
                carry0 = (loss0.astype(jnp.float32), f32(aux0), f32(g0))

                def body(carry, i):
                    loss_s, aux_s, g_s = carry
                    (loss_i, aux_i), g_i = _grads_of(
                        state.params, micro(i), jax.random.fold_in(r, i)
                    )
                    add32 = lambda a, b: a + b.astype(jnp.float32)
                    return (
                        loss_s + loss_i.astype(jnp.float32),
                        jax.tree_util.tree_map(add32, aux_s, aux_i),
                        jax.tree_util.tree_map(add32, g_s, g_i),
                    ), None

                (loss_sum, aux_sum, g_sum), _ = jax.lax.scan(
                    body, carry0, jnp.arange(1, accum)
                )
                loss = loss_sum / accum
                aux = jax.tree_util.tree_map(lambda a: a / accum, aux_sum)
                # back to the params' native grad dtype for the optimizer
                grads = jax.tree_util.tree_map(
                    lambda g, p: (g / accum).astype(p.dtype), g_sum, state.params
                )
            updates, new_opt = self.optimizer.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            metrics = {"loss": loss, "grad_norm": optax.global_norm(grads), **aux}
            return (
                TrainState(step=state.step + 1, params=new_params, opt_state=new_opt),
                metrics,
            )

        self._chunk_fns: Dict[int, Any] = {}
        self.batch_shardings = self._batch_shardings()
        self.stacked_batch_shardings = self._stacked_batch_shardings()
        self._step_fn = jax.jit(
            _step,
            in_shardings=(self.state_shardings, self.batch_shardings, None),
            out_shardings=(self.state_shardings, None),
            donate_argnums=(0,),
        )

    def _batch_shardings(self):
        """Batch leaves shard their batch dim over data(+fsdp); scalars
        replicate. With gradient accumulation the batch dim is dim 1
        (leaves are [accum, B/accum, ...], see prepare_batch) and the
        accumulation dim stays unsharded. Computed once in _build
        (synthesizes a throwaway example batch); use the cached
        ``batch_shardings`` afterwards."""
        example = self.prepare_batch(
            self.task.make_batch(np.random.default_rng(0), self.task.batch_size)
        )
        accum = max(self.config.grad_accum_steps, 1)

        def one(leaf):
            arr = np.asarray(leaf)
            if arr.ndim == 0:
                return NamedSharding(self.mesh, P())
            axes = tuple(
                a for a in (AXIS_DATA, AXIS_FSDP) if a in self.mesh.axis_names
            )
            if not axes:
                return NamedSharding(self.mesh, P())
            spec = axes if len(axes) > 1 else axes[0]
            if accum > 1:
                return NamedSharding(
                    self.mesh, P(None, spec, *([None] * (arr.ndim - 2)))
                )
            return NamedSharding(
                self.mesh, P(spec, *([None] * (arr.ndim - 1)))
            )

        self._example_batch = example
        return jax.tree_util.tree_map(one, example)

    def prepare_batch(self, host_batch):
        """Host-side shape adapter: with grad_accum_steps > 1, reshape
        each [B, ...] leaf to [accum, B/accum, ...] (scalars pass
        through) so the jitted step can scan microbatches."""
        accum = max(self.config.grad_accum_steps, 1)
        if accum == 1:
            return host_batch

        def one(leaf):
            arr = np.asarray(leaf)
            if arr.ndim == 0:
                return arr
            return arr.reshape(accum, arr.shape[0] // accum, *arr.shape[1:])

        return jax.tree_util.tree_map(one, host_batch)

    # -- host-fetch batching -----------------------------------------------

    def _fetch_metrics(self, metrics: Dict[str, Any]) -> Dict[str, float]:
        """Fetch a metrics dict in ONE host transfer. Per-scalar ``float()``
        costs a full tunnel round trip EACH (~50-90 ms measured on the
        remote rig) even for ready values; stacking on device first makes
        a log boundary cost one fetch instead of len(metrics)."""
        keys = sorted(metrics)
        stack = self._stack_fns.get(len(keys))
        if stack is None:
            stack = jax.jit(lambda *xs: jnp.stack([x.astype(jnp.float32) for x in xs]))
            self._stack_fns[len(keys)] = stack
        vals = np.asarray(stack(*(metrics[k] for k in keys)))
        return dict(zip(keys, map(float, vals)))

    # -- per-host input plumbing -------------------------------------------

    def _batch_dim(self) -> int:
        """Index of the sharded batch dim in a PREPARED batch leaf (the
        microbatch dim under gradient accumulation)."""
        return 1 if max(self.config.grad_accum_steps, 1) > 1 else 0

    def _input_shard_plan(
        self, num_shards: Optional[int] = None
    ) -> Tuple[int, int, int]:
        """Per-host input decomposition: returns ``(shard_lo, shard_hi,
        num_shards)`` — the half-open range of input shards THIS process
        must synthesize, derived from which rows of the sharded batch dim
        its addressable devices actually hold (``devices_indices_map``),
        so the row→process mapping is read off the real sharding rather
        than assumed. ``num_shards=None`` uses the config (per_host mode);
        files mode passes the process count explicitly (one file-backed
        stream per process)."""
        cfg, task = self.config, self.task
        num_shards = num_shards or cfg.input_shards or jax.process_count()
        accum = max(cfg.grad_accum_steps, 1)
        if task.batch_size % num_shards:
            raise ValueError(
                f"input_shards={num_shards} does not divide "
                f"batch_size={task.batch_size}"
            )
        if (task.batch_size // num_shards) % accum:
            raise ValueError(
                f"per-shard batch {task.batch_size // num_shards} must be "
                f"a multiple of grad_accum_steps={accum}"
            )
        dim = self._batch_dim()
        pair = next(
            (
                (np.shape(l), s)
                for l, s in zip(
                    jax.tree_util.tree_leaves(self._example_batch),
                    jax.tree_util.tree_leaves(self.batch_shardings),
                )
                if len(np.shape(l)) > dim
            ),
            None,
        )
        if pair is None:
            raise ValueError("per-host input needs at least one batched leaf")
        shape, sharding = pair
        rows = shape[dim]
        me = jax.process_index()
        owned = sorted(
            {
                r
                for dev, idx in sharding.devices_indices_map(shape).items()
                if dev.process_index == me
                for r in range(
                    idx[dim].start or 0,
                    rows if idx[dim].stop is None else idx[dim].stop,
                )
            }
        )
        lo, hi = owned[0], owned[-1] + 1
        if owned != list(range(lo, hi)):
            raise ValueError(
                f"per-host input needs a contiguous local batch range; "
                f"process {me} owns non-contiguous rows {owned[:8]}..."
            )
        rows_per_shard = rows // num_shards
        if lo % rows_per_shard or hi % rows_per_shard:
            raise ValueError(
                f"process-local rows [{lo},{hi}) are not aligned to "
                f"{rows_per_shard} rows/shard; pick input_shards such that "
                "shards don't straddle processes"
            )
        return lo // rows_per_shard, hi // rows_per_shard, num_shards

    def _open_input_files(self, start_step: int):
        """Open the record-shard input stream (input_mode="files"): expand
        ``config.input_files`` (comma-separated paths/globs), give THIS
        process its round-robin file share (or, when the file list can't
        cover the processes, a record STRIPE — auto fallback, warned
        loudly, every process then index-scans all files) and a local
        batch sized to its addressable rows, validate the first decoded
        batch against the task's schema, and fast-forward to
        ``start_step`` (one batch per step) so checkpoint resume
        continues the exact record stream. Returns an endless iterator of
        RAW host batches (prepare_batch is applied by the caller)."""
        from tfk8s_tpu.data.dataset import RecordDataset

        cfg, task = self.config, self.task
        paths = _expand_input_files(cfg.input_files or "")
        nproc = jax.process_count()
        if cfg.input_shards is not None:
            # files mode divides by PROCESS (one file share per host);
            # input_shards governs only the per_host synthetic mode —
            # silently ignoring a set knob would contradict the loud
            # ValueError the inverse mismatch raises
            log.warning(
                "%s: input_shards=%d is ignored in input_mode='files' "
                "(file input divides per process: %d); unset it or use "
                "input_mode='per_host'",
                task.name, cfg.input_shards, nproc,
            )
        if nproc > 1:
            shard_lo, shard_hi, num_shards = self._input_shard_plan(
                num_shards=nproc
            )
            local_rows = (shard_hi - shard_lo) * (task.batch_size // num_shards)
            self.input_shard_range = (shard_lo, shard_hi, num_shards)
        else:
            local_rows = task.batch_size
        want = self.task.make_batch(np.random.default_rng(0), 1)
        if cfg.input_format == "image":
            ds = _open_image_dataset(
                paths, local_rows, want, train=True, seed=cfg.seed,
                workers=cfg.decode_workers, min_scale=cfg.aug_min_scale,
                host_index=jax.process_index(), num_hosts=nproc,
            )
        elif cfg.input_format == "array":
            ds = RecordDataset(
                paths,
                batch_size=local_rows,
                host_index=jax.process_index(),
                num_hosts=nproc,
                seed=cfg.seed,
            )
        else:
            raise ValueError(
                f"unknown input_format {cfg.input_format!r} (array | image)"
            )
        if ds.shard_by == "records" and nproc > 1:
            # the auto fallback trades the 1/hosts file-IO property for
            # record striping (every process index-scans ALL files) —
            # loud, because at scale this is usually a misprovisioned
            # shard count, not a choice
            log.warning(
                "%s: only %d record files for %d processes — falling back "
                "to RECORD striping (every process reads all files; write "
                ">= one file per host to restore per-host file IO)",
                task.name, len(ds.files), nproc,
            )
        backend = getattr(ds, "backend", None)  # image decode backend
        log.info(
            "%s: %s file input (%s-sharded%s) — process %d/%d reads %d "
            "files / %d records, %d rows/step, resuming at batch %d",
            task.name, cfg.input_format, ds.shard_by,
            f", {backend} decode" if backend else "",
            jax.process_index(), nproc, len(ds.files), len(ds), local_rows,
            start_step,
        )
        # prefetch=0: fit's own _BatchPrefetcher supplies the background
        # thread; a second producer here would double-buffer the batches
        # (the image decode pool still parallelizes WITHIN each batch)
        it = ds.iterator(prefetch=0, start_batch=start_step)

        return _CheckedFileStream(it, want, local_rows, dataset=ds)

    def _make_shard_batch(self, step: int, shard_lo: int, shard_hi: int,
                          num_shards: int):
        """Synthesize this process's input shards for one step. Each shard
        draws from a fresh generator seeded by (seed, step, shard) — order-
        independent and thread-safe by construction (no cross-call rng
        state), unlike the replicated path's sequential stream."""
        shard_size = self.task.batch_size // num_shards
        dim = self._batch_dim()
        parts = [
            self.prepare_batch(
                self.task.make_batch(
                    np.random.default_rng(
                        np.random.SeedSequence(
                            [self.config.seed, step, s]
                        )
                    ),
                    shard_size,
                )
            )
            for s in range(shard_lo, shard_hi)
        ]
        if len(parts) == 1:
            return parts[0]
        return jax.tree_util.tree_map(
            lambda *xs: xs[0]
            if np.ndim(xs[0]) == 0
            else np.concatenate(xs, axis=dim),
            *parts,
        )

    def _put_global(self, host_tree, shardings, stack: int = 0):
        """Move a host batch to devices. Single-process (including the
        per-host emulation, where local rows == all rows): plain
        ``device_put``. Multi-process per-host: each process holds only
        its local rows, so assemble the global array with
        ``jax.make_array_from_process_local_data``. ``stack`` > 0 means
        the tree is a [k, ...] stack of prepared batches."""
        if jax.process_count() == 1 or not getattr(self, "_per_host_active", False):
            return jax.device_put(host_tree, shardings)
        # flattened zip (not tree_map): global-shape TUPLES would
        # themselves be flattened as pytrees
        flat_data, treedef = jax.tree_util.tree_flatten(host_tree)
        flat_sh = jax.tree_util.tree_leaves(shardings)
        flat_gs = [
            np.shape(l) for l in jax.tree_util.tree_leaves(self._example_batch)
        ]
        if stack:
            flat_gs = [(stack, *g) for g in flat_gs]
        out = [
            jax.make_array_from_process_local_data(s, np.asarray(d), g)
            for d, s, g in zip(flat_data, flat_sh, flat_gs)
        ]
        return jax.tree_util.tree_unflatten(treedef, out)

    # -- multi-step device loop --------------------------------------------

    def _stacked_batch_shardings(self):
        """Shardings for a [k, ...] stack of batches: the stack dim is
        unsharded (it is scanned over), each element keeps the per-step
        batch sharding."""
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(s.mesh, P(None, *s.spec)),
            self.batch_shardings,
        )

    def _chunk_fn(self, k: int):
        """One jitted dispatch advancing ``k`` steps via lax.scan (cached
        per k — chunk lengths repeat, so the set of compilations is
        small). Scanning over calls to the already-jitted ``_step_fn``
        traces through it; the rng stream is identical to per-step
        dispatch because the step fold lives inside the step."""
        fn = self._chunk_fns.get(k)
        if fn is None:

            def chunk(state, batches, key):
                def body(s, b):
                    return self._step_fn(s, b, key)

                return jax.lax.scan(body, state, batches)

            fn = jax.jit(
                chunk,
                in_shardings=(
                    self.state_shardings,
                    self.stacked_batch_shardings,
                    None,
                ),
                out_shardings=(self.state_shardings, None),
                donate_argnums=(0,),
            )
            self._chunk_fns[k] = fn
        return fn

    # -- lifecycle ----------------------------------------------------------

    def init_state(self) -> TrainState:
        return self._init_fn(jax.random.key(self.config.seed))

    def abstract_state(self) -> TrainState:
        """Shapes + shardings of the train state WITHOUT materializing
        anything on device — the restore donor for processes that only
        read checkpoints (run_eval)."""
        return jax.tree_util.tree_map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            jax.eval_shape(self._init_fn, jax.random.key(self.config.seed)),
            self.state_shardings,
        )

    def fit(
        self,
        state: Optional[TrainState] = None,
        stop: Optional[Any] = None,  # threading.Event-like graceful preemption
    ) -> Tuple[TrainState, List[Dict[str, float]]]:
        cfg = self.config
        ckpt = Checkpointer(cfg.checkpoint_dir) if cfg.checkpoint_dir else None

        if state is None:
            state = self.init_state()
            if cfg.resume and ckpt and ckpt.enabled and ckpt.latest_step() is not None:
                state = ckpt.restore(state)
                log.info("%s: resumed at step %d", self.task.name, int(state.step))

        history: List[Dict[str, float]] = []
        start_step = int(state.step)
        batch_shardings = self.batch_shardings
        stacked_shardings = self.stacked_batch_shardings

        input_mode = cfg.input_mode or (
            "files"
            if cfg.input_files
            else ("per_host" if jax.process_count() > 1 else "replicated")
        )
        if input_mode not in ("replicated", "per_host", "files"):
            raise ValueError(f"unknown input_mode {cfg.input_mode!r}")
        if input_mode == "files" and not cfg.input_files:
            raise ValueError('input_mode="files" needs input_files')
        if cfg.input_files and input_mode != "files":
            # silently training on synthetic data while the user's record
            # shards sit unopened would be the worst kind of misconfig
            raise ValueError(
                f"input_files is set but input_mode={input_mode!r} would "
                'ignore it — use input_mode="files" (or unset one)'
            )
        # files mode reuses the per-host ASSEMBLY path on multi-process
        # runs (_put_global short-circuits to device_put single-process)
        self._per_host_active = input_mode != "replicated"
        files_iter = None
        if input_mode == "files":
            files_iter = self._open_input_files(start_step)
        elif self._per_host_active:
            shard_lo, shard_hi, num_shards = self._input_shard_plan()
            # surfaced for tests/operators: which input shards THIS
            # process synthesizes (disjoint across the gang)
            self.input_shard_range = (shard_lo, shard_hi, num_shards)
            log.info(
                "%s: per-host input — process %d/%d builds shards "
                "[%d, %d) of %d",
                self.task.name, jax.process_index(), jax.process_count(),
                shard_lo, shard_hi, num_shards,
            )
        else:
            # Replicated batch stream. The generator is created HERE and
            # owned EXCLUSIVELY by the batch producer — the prefetch
            # thread when prefetching, this thread otherwise (numpy
            # Generators are not thread-safe; nothing else may touch it
            # while fit runs).
            np_rng = np.random.default_rng(cfg.seed + int(state.step))

        prof_start = start_step + cfg.profile_skip if cfg.profile_dir else -1
        prof_stop = prof_start + cfg.profile_steps
        profiling = False
        # one base key for the run; the jitted step folds in state.step
        base_key = jax.random.key(cfg.seed)

        def _make_host_batch(step: int):
            if files_iter is not None:
                return self.prepare_batch(next(files_iter))
            if self._per_host_active:
                return self._make_shard_batch(step, shard_lo, shard_hi, num_shards)
            return self.prepare_batch(
                self.task.make_batch(np_rng, self.task.batch_size)
            )

        prefetcher = (
            _BatchPrefetcher(
                _make_host_batch, start_step, cfg.steps, cfg.prefetch
            )
            if cfg.prefetch > 1
            else None
        )

        inflight: "collections.deque" = collections.deque()
        if cfg.max_inflight is not None:
            max_inflight = max(cfg.max_inflight, 1)
        else:
            plat = jax.devices()[0].platform
            max_inflight = 256 if plat in ("tpu", "axon") else 16
        t0 = time.perf_counter()
        # window anchor for the REPORTED step rate: rates must describe
        # the last interval (what an operator alert needs), not a
        # cumulative average that still carries the first-step compile
        last_report = (start_step, t0)
        last_bytes = 0  # input-bandwidth window anchor (files input)
        last_images = 0  # decoded-image window anchor (image input)
        # chunked device loop: scan_steps steps per dispatch, never
        # crossing a log/checkpoint boundary; profiling forces per-step
        # dispatch so the trace keeps step-level annotations
        scan = max(cfg.scan_steps, 1)
        if cfg.profile_dir and scan > 1:
            log.info(
                "%s: profiling active — forcing scan_steps=1", self.task.name
            )
            scan = 1

        def _next_batch(step):
            return (
                prefetcher.get() if prefetcher is not None
                else _make_host_batch(step)
            )

        tracer = get_tracer()
        first_dispatch = True
        compile_s: Optional[float] = None
        input_wait_total = 0.0  # cumulative host wait for batches
        starved_steps = 0  # steps whose input wait dominated the loop

        def _dispatch(call):
            """Run one device dispatch; the FIRST one is wrapped in
            trainer.first_step / trainer.first_compile spans and fetched
            to completion — the compile-vs-execute split of step 1 is the
            number cold-start debugging needs, and the spans are the tail
            of the reconcile→pod→kubelet trace (obs/trace.py)."""
            nonlocal first_dispatch, compile_s
            if not first_dispatch:
                return call()
            first_dispatch = False
            with tracer.start_span(
                "trainer.first_step", attributes={"task": self.task.name}
            ):
                c0 = time.perf_counter()
                with tracer.start_span("trainer.first_compile"):
                    # trace+compile run synchronously inside the first
                    # call; execution is enqueued async
                    out = call()
                compile_s = time.perf_counter() - c0
                # fetch one metric leaf so the span covers the step's real
                # execution, not just its enqueue (block_until_ready
                # returns early through the remote tunnel)
                leaves = jax.tree_util.tree_leaves(out[1])
                if leaves:
                    np.asarray(leaves[0])
            progress.report(compile_seconds=compile_s)
            return out

        try:
            step = start_step
            while step < cfg.steps:
                if stop is not None and getattr(stop, "is_set", lambda: False)():
                    log.info("%s: stop requested at step %d", self.task.name, step)
                    break
                if stop is not None and getattr(stop, "drain_requested", False):
                    # reclaim notice (runtime/kubelet.py PodStopSignal):
                    # the previous step is finished — fall out to the
                    # drain checkpoint below and exit Drained
                    self.drained_at = step
                    log.info(
                        "%s: reclaim notice at step %d; draining",
                        self.task.name, step,
                    )
                    break
                it_t0 = time.perf_counter()
                if step == prof_start:
                    jax.profiler.start_trace(cfg.profile_dir)
                    profiling = True
                k = min(scan, cfg.steps - step)
                k = min(k, cfg.log_every - step % cfg.log_every)
                if ckpt and cfg.checkpoint_every:
                    k = min(k, cfg.checkpoint_every - step % cfg.checkpoint_every)
                if k == 1:
                    t_in = time.perf_counter()
                    host = _next_batch(step)
                    input_wait = time.perf_counter() - t_in
                    # device transfer stays on THIS thread (see
                    # _BatchPrefetcher); it is an async enqueue
                    batch = self._put_global(host, batch_shardings)
                    state, metrics = _dispatch(
                        lambda: self._step_fn(state, batch, base_key)
                    )
                else:
                    t_in = time.perf_counter()
                    hosts = [_next_batch(step + i) for i in range(k)]
                    input_wait = time.perf_counter() - t_in
                    stacked = jax.tree_util.tree_map(
                        lambda *xs: np.stack(xs), *hosts
                    )
                    batch = self._put_global(stacked, stacked_shardings, stack=k)
                    state, ys = _dispatch(
                        lambda: self._chunk_fn(k)(state, batch, base_key)
                    )
                    metrics = jax.tree_util.tree_map(lambda x: x[-1], ys)
                step += k
                # the window counts STEPS, not dispatches: a k-step chunk
                # holds k staged batches, so it weighs k against the bound
                inflight.append((metrics["loss"], k))
                inflight_steps = sum(w for _, w in inflight)
                if inflight_steps > max_inflight:
                    # Drain to HALF the window with ONE host fetch on the
                    # newest drained entry: device completion is ordered,
                    # so its arrival implies everything older is done.
                    # A host fetch (not block_until_ready — through the
                    # remote-execution tunnel that returns before device
                    # work drains, BENCH_BASELINE.json note) per POPPED
                    # step would cost a full round trip each (~50-90 ms
                    # measured); amortizing to one per half-window keeps
                    # the bound with O(2/window) fetches per step.
                    newest = None
                    while inflight and inflight_steps > max_inflight // 2:
                        newest, w = inflight.popleft()
                        inflight_steps -= w
                    if newest is not None:
                        float(newest)
                # input-starvation accounting: compare the host wait for
                # this iteration's batch(es) against the whole iteration
                # (including any inflight drain — the steady-state step
                # cost). A dominating wait means the device idled on input.
                it_dt = time.perf_counter() - it_t0
                input_wait_total += input_wait
                if input_wait > _INPUT_STARVED_FRACTION * max(it_dt, 1e-9):
                    starved_steps += k
                if profiling and step >= prof_stop:
                    float(metrics["loss"])  # honest drain before stopping
                    jax.profiler.stop_trace()
                    profiling = False
                    log.info("%s: profile trace written to %s", self.task.name, cfg.profile_dir)
                if ckpt and cfg.checkpoint_every and step % cfg.checkpoint_every == 0:
                    ckpt.save(step, state)
                elif ckpt:
                    # commit the previous periodic save's marker as soon as
                    # its async write drains — a cold kill later in this
                    # window must not discard a durable checkpoint
                    ckpt.maybe_commit()
                if step % cfg.log_every == 0 or step == cfg.steps:
                    # ONE batched transfer for the whole metrics dict
                    # (per-scalar fetches cost a tunnel round trip each)
                    m = self._fetch_metrics(metrics)
                    m["step"] = step
                    now = time.perf_counter()
                    m["steps_per_s"] = (step - start_step) / (now - t0)
                    history.append(m)
                    # surface step-rate/throughput to the node agent →
                    # pod status → operator /metrics (runtime/progress.py);
                    # WINDOWED rate: steps/seconds since the last report
                    w_steps = step - last_report[0]
                    w_dt = max(now - last_report[1], 1e-9)
                    last_report = (step, now)
                    rate = w_steps / w_dt
                    report_kw = dict(
                        step=step,
                        steps_per_sec=rate,
                        examples_per_sec=rate * self.task.batch_size,
                        step_seconds=w_dt / w_steps,
                        # cumulative input health: total host wait for
                        # batches + steps the wait dominated (operator
                        # counter for input-starvation alerts)
                        input_wait_seconds=input_wait_total,
                        input_starved_steps=float(starved_steps),
                    )
                    if compile_s is not None:
                        report_kw["compile_seconds"] = compile_s
                    if files_iter is not None and files_iter.dataset is not None:
                        # windowed input bandwidth: an operator alert can
                        # SEE input starvation (pure-Python codec fallback
                        # reads at ~1% of native — VERDICT r4 weak #3)
                        b_now = files_iter.dataset.bytes_read
                        report_kw["input_mb_per_sec"] = (
                            (b_now - last_bytes) / w_dt / 1e6
                        )
                        last_bytes = b_now
                        i_now = getattr(
                            files_iter.dataset, "images_decoded", None
                        )
                        if i_now is not None:
                            # the decode pool's delivered rate — an
                            # operator alert can see the image-input
                            # ceiling directly, next to input MB/s
                            report_kw["decoded_images_per_sec"] = (
                                (i_now - last_images) / w_dt
                            )
                            last_images = i_now
                            if prefetcher is not None:
                                # the staged-batch gauge on the WIRED
                                # path: fit's own prefetcher is the
                                # queue between decode and device here
                                from tfk8s_tpu.data.images.pipeline import (
                                    get_metrics as _img_metrics,
                                )

                                im = _img_metrics()
                                if im is not None:
                                    # mode-labeled: a concurrent
                                    # evaluator owns its own series
                                    im.set_gauge(
                                        "tfk8s_image_decode_queue_depth",
                                        float(prefetcher.depth()),
                                        labels={"mode": "train"},
                                    )
                    progress.report(**report_kw)
                    log.info(
                        "%s step %d: %s", self.task.name, step,
                        {k2: round(v, 4) for k2, v in m.items()},
                    )
        finally:
            # a step-loop exception must not leak the producer thread (it
            # would spin on its bounded queue holding staged device batches)
            if prefetcher is not None:
                prefetcher.close()
            if files_iter is not None:
                files_iter.close()
        if profiling:  # run ended inside the trace window
            jax.profiler.stop_trace()
        if ckpt and ckpt.enabled:
            if self.drained_at is not None:
                # drain checkpoint: async start (overlaps the reclaim
                # grace window), then barrier on the commit marker —
                # durability is the whole point of the notice. A kill
                # landing mid-save leaves an uncommitted partial dir that
                # latest-step discovery skips (runtime/checkpoint.py).
                t0 = time.perf_counter()
                final_step = int(state.step)
                ckpt.save_async(final_step, state)
                ckpt.wait_until_finished()
                drain_s = time.perf_counter() - t0
                self.drained_at = final_step
                progress.report(
                    drain_checkpoint_seconds=drain_s, step=final_step
                )
                log.info(
                    "%s: drain checkpoint step=%d committed in %.3fs",
                    self.task.name, final_step, drain_s,
                )
            else:
                ckpt.save(int(state.step), state, wait=True)
            ckpt.close()
        return state, history


def run_eval(
    task: TrainTask,
    env: Optional[Dict[str, str]] = None,
    stop: Optional[Any] = None,
    mesh: Optional[Mesh] = None,
) -> Dict[str, float]:
    """Evaluator-replica entrypoint glue (the reference's Evaluator role,
    SURVEY.md C4): poll the job's checkpoint dir, evaluate each NEW
    checkpoint on fresh held-out batches, exit once the final training
    step (``TFK8S_TRAIN_STEPS``) has been evaluated. Raises if no final
    checkpoint appears within ``TFK8S_EVAL_TIMEOUT`` seconds — a failed
    evaluator pod is how the control plane learns evaluation is wedged."""
    env = dict(env or {})
    ctx = ProcessContext.from_env(env)
    if not ctx.checkpoint_dir:
        raise RuntimeError(
            f"{task.name}: evaluator needs TFK8S_CHECKPOINT_DIR "
            "(set the tfk8s.dev/checkpoint-dir job annotation)"
        )
    # The evaluator is a rank in the job's coordination barrier
    # (TFK8S_NUM_PROCESSES counts every replica, trainer/replicas.py) —
    # skipping initialize would wedge the worker gang at startup.
    initialize_distributed(ctx, env)
    if mesh is None:
        mesh = build_mesh(ctx)
    final_step = int(env.get("TFK8S_TRAIN_STEPS", "0"))
    timeout = float(env.get("TFK8S_EVAL_TIMEOUT", "300"))
    eval_batches = int(env.get("TFK8S_EVAL_BATCHES", "4"))

    trainer = Trainer(task, TrainConfig(steps=0), mesh)
    # ABSTRACT donor for restore — shapes+shardings without materializing
    # params or optimizer state on device: the evaluator only ever holds
    # one restored state (and uses only its params).
    state = trainer.abstract_state()
    eval_fn = jax.jit(task.loss_fn)
    np_rng = np.random.default_rng(10_000)  # held-out stream
    # held-out RECORD SHARDS (TFK8S_EVAL_INPUT_FILES): the evaluator reads
    # its eval set from disk through the same data plane training uses —
    # deterministic unshuffled order, every restore evaluates the SAME
    # batches (comparable metrics across checkpoints). Falls back to the
    # synthetic held-out stream when unset.
    eval_files = env.get("TFK8S_EVAL_INPUT_FILES")
    if eval_files:
        from tfk8s_tpu.data.dataset import RecordDataset

        want = task.make_batch(np.random.default_rng(0), 1)
        if env.get("TFK8S_INPUT_FORMAT", "array") == "image":
            # the deterministic eval view (resize + center-crop,
            # unshuffled) — every restore scores the SAME pixels
            eval_ds = _open_image_dataset(
                _expand_input_files(eval_files), task.batch_size, want,
                train=False,
                workers=(
                    int(env["TFK8S_DECODE_WORKERS"])
                    if env.get("TFK8S_DECODE_WORKERS")
                    else None
                ),
            )
        else:
            eval_ds = RecordDataset(
                _expand_input_files(eval_files),
                batch_size=task.batch_size,
                shuffle=False,
            )
        avail = eval_ds.batches_per_epoch()
        if avail < eval_batches:
            log.info(
                "%s-eval: eval set holds %d batches; clamping "
                "TFK8S_EVAL_BATCHES from %d", task.name, avail, eval_batches,
            )
            eval_batches = avail
        # materialize ONCE: the batches are identical for every
        # checkpoint by design (unshuffled epoch 0), so paying file IO +
        # CRC + decode + schema check per evaluation would be pure waste
        checked = _CheckedFileStream(eval_ds.batches(0), want, task.batch_size)
        eval_set = [next(checked) for _ in range(eval_batches)]
        eval_ds.close()  # decode pool is done once the set materializes
    ckpt = Checkpointer(ctx.checkpoint_dir)

    last_seen = -1
    metrics: Dict[str, float] = {}
    # timeout bounds time WITHOUT PROGRESS (a wedged evaluator/trainer),
    # not total training duration — reset on every new checkpoint.
    deadline = time.time() + timeout
    try:
        while time.time() < deadline:
            if stop is not None and getattr(stop, "is_set", lambda: False)():
                log.info("%s-eval: stop requested", task.name)
                return metrics
            step = ckpt.latest_step()
            if step is not None and step > last_seen:
                state = ckpt.restore(state, step=step)
                sums: Dict[str, float] = {}
                for bi in range(eval_batches):
                    host = (
                        eval_set[bi]
                        if eval_files
                        else task.make_batch(np_rng, task.batch_size)
                    )
                    batch = jax.device_put(host, trainer.batch_shardings)
                    loss, aux = eval_fn(state.params, batch, jax.random.key(0))
                    for k, v in {"loss": loss, **aux}.items():
                        sums[k] = sums.get(k, 0.0) + float(v)
                metrics = {k: v / eval_batches for k, v in sums.items()}
                metrics["step"] = float(step)
                log.info(
                    "%s-eval step %d: %s", task.name, step,
                    {k: round(v, 4) for k, v in metrics.items()},
                )
                last_seen = step
                if final_step and step >= final_step:
                    return metrics
                deadline = time.time() + timeout  # progress -> new window
            time.sleep(0.2)
    finally:
        ckpt.close()
    raise RuntimeError(
        f"{task.name}: evaluator saw no new checkpoint (step > {last_seen}) "
        f"for {timeout:.0f}s (final step wanted: {final_step})"
    )


def run_task(
    task: TrainTask,
    env: Optional[Dict[str, str]] = None,
    stop: Optional[Any] = None,
    config: Optional[TrainConfig] = None,
    mesh: Optional[Mesh] = None,
) -> Dict[str, float]:
    """Entrypoint glue: env contract -> mesh -> (resume ->) fit -> metrics.
    Raises if the task declares convergence targets and misses them — a
    failed pod is how the control plane learns training went wrong
    (SURVEY.md §3.5). Pass ``mesh`` when the caller already built it (e.g.
    to construct a mesh-bound attention fn); it must match the env's
    TFK8S_MESH contract.

    Continues the trace stamped into the pod env (TFK8S_TRACEPARENT):
    ``trainer.run`` is the umbrella under which startup / first-compile /
    first-step spans nest — on the hermetic kubelet the parent is already
    the calling thread's ``kubelet.launch`` span, across a real process
    boundary the env var carries the link."""
    env = dict(env or {})
    tracer = get_tracer()
    with tracer.start_span(
        "trainer.run",
        traceparent=env.get(TRACEPARENT_ENV),
        attributes={"task": task.name},
    ):
        with tracer.start_span("trainer.startup", attributes={"task": task.name}):
            ctx = ProcessContext.from_env(env)
            initialize_distributed(ctx, env)
            if mesh is None:
                mesh = build_mesh(ctx)
        return _run_task_inner(task, env, stop, config, mesh, ctx)


def _run_task_inner(
    task: TrainTask,
    env: Dict[str, str],
    stop: Optional[Any],
    config: Optional[TrainConfig],
    mesh: Mesh,
    ctx: ProcessContext,
) -> Dict[str, float]:

    if config is None:
        config = TrainConfig(
            steps=int(env.get("TFK8S_TRAIN_STEPS", "100")),
            learning_rate=float(env.get("TFK8S_LEARNING_RATE", "1e-3")),
            log_every=int(env.get("TFK8S_LOG_EVERY", "20")),
            checkpoint_every=int(env.get("TFK8S_CHECKPOINT_EVERY", "0")),
            checkpoint_dir=ctx.checkpoint_dir,
            seed=int(env.get("TFK8S_SEED", "0")),
            resume=ctx.resuming,
            profile_dir=env.get("TFK8S_PROFILE_DIR", ""),
            grad_accum_steps=int(env.get("TFK8S_GRAD_ACCUM", "1")),
            scan_steps=int(env.get("TFK8S_SCAN_STEPS", "1")),
            input_mode=env.get("TFK8S_INPUT_MODE") or None,
            input_shards=(
                int(env["TFK8S_INPUT_SHARDS"])
                if env.get("TFK8S_INPUT_SHARDS")
                else None
            ),
            input_files=env.get("TFK8S_INPUT_FILES") or None,
            input_format=env.get("TFK8S_INPUT_FORMAT", "array"),
            decode_workers=(
                int(env["TFK8S_DECODE_WORKERS"])
                if env.get("TFK8S_DECODE_WORKERS")
                else None
            ),
            aug_min_scale=float(env.get("TFK8S_AUG_MIN_SCALE", "0.08")),
            warmup_steps=int(env.get("TFK8S_WARMUP_STEPS", "0")),
            lr_schedule=env.get("TFK8S_LR_SCHEDULE", "constant"),
            decay_steps=(
                int(env["TFK8S_DECAY_STEPS"])
                if env.get("TFK8S_DECAY_STEPS")
                else None
            ),
            min_lr_ratio=float(env.get("TFK8S_MIN_LR_RATIO", "0.0")),
        )

    trainer = Trainer(task, config, mesh)
    state, history = trainer.fit(stop=stop)
    if trainer.drained_at is not None:
        # a drained run is INCOMPLETE by design — skip the convergence
        # targets and exit the graceful terminal phase the controller's
        # elastic resize keys off (kubelet maps this to PodPhase.DRAINED)
        raise PodDrained(
            f"{task.name}: drained at step {trainer.drained_at} on reclaim "
            "notice"
        )
    final = history[-1] if history else {}
    for metric, target in task.targets.items():
        got = final.get(metric)
        if got is None:
            raise RuntimeError(f"{task.name}: target metric {metric!r} was never reported")
        # loss-like metrics must go below target; accuracy-like above
        ok = got <= target if "loss" in metric else got >= target
        if not ok:
            raise RuntimeError(
                f"{task.name}: {metric}={got:.4f} missed target {target} "
                f"after {final.get('step')} steps"
            )
    return final
