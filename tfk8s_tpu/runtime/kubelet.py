"""LocalKubelet: executes pods in-process — the node agent of the hermetic
backend.

In the reference's world the kubelet pulls the image and starts the
container, which is the control->data plane handoff (SURVEY.md §3.3,
'PROCESS+MACHINE BOUNDARY'). Here each pod's entrypoint runs on a thread:
the kubelet claims Pending pods from the watch, flips them to Running,
invokes the entrypoint with the pod's env (the JAX coordination contract),
and records Succeeded/Failed with the exit message — which flows back
through the watch into the controller's reconcile, closing the loop of
SURVEY.md §3.5.

Failure injection for tests: an env of ``TFK8S_TEST_FAIL_TIMES=n`` makes a
pod raise on its first n attempts per pod name (counted in-process), which
exercises restart policies end-to-end.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
import traceback
from typing import Deque, Dict, List, Optional, Tuple

from tfk8s_tpu.api.types import Pod, PodPhase
from tfk8s_tpu.runtime.registry import PodDrained
from tfk8s_tpu.client.clientset import Clientset
from tfk8s_tpu.client.informer import ResourceEventHandler, SharedIndexInformer
from tfk8s_tpu.client.store import Conflict, NotFound, Unavailable
from tfk8s_tpu.obs.trace import TRACEPARENT_ENV, get_tracer
from tfk8s_tpu.runtime import progress as _progress
from tfk8s_tpu.runtime import registry
from tfk8s_tpu.utils.logging import get_logger

log = get_logger("kubelet")

# `kubectl logs` parity: how many tail lines a pod's status carries, and
# how often the kubelet flushes a running pod's buffer into status.
LOG_TAIL_LIMIT = 200
LOG_FLUSH_SECONDS = 1.0

# Node heartbeat (the k8s node-lease mechanism): the kubelet renews a
# Lease named node-<name>; the controller marks a node's RUNNING pods
# Failed(NodeLost) once the lease goes stale — without this, a dead node
# agent strands its pods Running forever and the gang never recovers
# (SURVEY.md §3.5 failure path; slice loss must become job restart).
NODE_LEASE_PREFIX = "node-"
# Deployment-tunable (TFK8S_NODE_LEASE_*): the heartbeat thread shares
# the pod entrypoints' process (and GIL), so long JAX traces can stall
# renewal — the default staleness window (2x duration = 40s, the k8s
# node-lease timeout) must comfortably exceed any single trace. The
# node-failure test shrinks both to keep the suite fast. Env vars are
# read at LocalKubelet CONSTRUCTION, not import (r3 advisor finding:
# settings applied after first import were silently ignored).
NODE_LEASE_DURATION_DEFAULT_S = 20.0
NODE_LEASE_RENEW_DEFAULT_S = 4.0
# Reclaim notice (spot/preemptible capacity): the deadline-stamped pod
# annotation that warns a pod its host is about to be pulled — the
# hermetic analogue of the 30-second TPU reclaim notice. Writers (chaos
# harness, the job controller's resize drain, reclaim_node) PATCH the
# annotation through the apiserver; the kubelet's pod watch turns it into
# a soft drain signal on the entrypoint's PodStopSignal, ahead of any
# hard kill. Value: absolute epoch-seconds deadline.
RECLAIM_AT_ANNOTATION = "tfk8s.dev/reclaim-at"


def reclaim_patch(deadline: float) -> dict:
    """The merge-patch body that stamps a reclaim deadline on an object —
    the ONE place the annotation's wire format is written (kubelet,
    controller resize drain, chaos harness all patch through this)."""
    return {"metadata": {"annotations": {
        RECLAIM_AT_ANNOTATION: f"{deadline:.3f}"
    }}}


def parse_reclaim_at(obj) -> Optional[float]:
    """Deadline from an object's reclaim annotation, or None when absent
    or malformed — the ONE place the wire format is read."""
    raw = obj.metadata.annotations.get(RECLAIM_AT_ANNOTATION)
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        log.warning(
            "malformed reclaim deadline %r on %s", raw, obj.metadata.key
        )
        return None

# How long a pod phase write keeps retrying through an apiserver outage.
# Sized to cover a full control-plane restart (journal replay + interpreter
# start, tens of seconds under load) with margin; teardown paths exit
# early via the kubelet stop event.
STATUS_WRITE_RETRY_S = 300.0


class PodStopSignal(threading.Event):
    """The per-pod stop handle the kubelet hands each entrypoint. The
    Event itself is the HARD stop (deletion / node death — SIGKILL
    equivalent); ``request_drain`` layers the SOFT reclaim phase on top
    (SIGTERM equivalent): entrypoints that check ``drain_requested`` get
    ``drain_deadline`` seconds to finish the in-flight step, commit a
    checkpoint, and raise :class:`~tfk8s_tpu.runtime.registry.PodDrained`;
    entrypoints that only watch the Event keep the legacy semantics."""

    def __init__(self):
        super().__init__()
        self._drain = threading.Event()
        self.drain_deadline: Optional[float] = None

    def request_drain(self, deadline: float) -> None:
        # first notice wins: a re-delivered (or later) notice must not
        # push the deadline out from under a drain already in progress
        if not self._drain.is_set():
            self.drain_deadline = deadline
        self._drain.set()

    @property
    def drain_requested(self) -> bool:
        return self._drain.is_set()


class _PodLogRouter(logging.Handler):
    """Captures the ``tfk8s.*`` log records emitted by pod entrypoint
    threads into per-pod bounded buffers — the hermetic analogue of the
    container stdout a real node agent captures. Routing is by thread
    ident: each pod runs on its own kubelet thread, so a record's
    ``record.thread`` names its pod (child threads an entrypoint spawns
    are not captured — same as a container process writing to a file
    instead of stdout)."""

    def __init__(self):
        super().__init__()
        self.setFormatter(
            logging.Formatter("%(asctime)s %(levelname).1s %(name)s] %(message)s")
        )
        self._by_thread: Dict[int, Deque[str]] = {}
        self._route_lock = threading.Lock()

    def register(self, ident: int) -> Deque[str]:
        buf: Deque[str] = collections.deque(maxlen=LOG_TAIL_LIMIT)
        with self._route_lock:
            self._by_thread[ident] = buf
        return buf

    def unregister(self, ident: int) -> None:
        with self._route_lock:
            self._by_thread.pop(ident, None)

    def emit(self, record: logging.LogRecord) -> None:
        # append under the route lock: the flusher snapshots buffers with
        # list(buf), which raises 'deque mutated during iteration' if an
        # append lands mid-copy
        with self._route_lock:
            buf = self._by_thread.get(record.thread)
            if buf is not None:
                try:
                    buf.append(self.format(record))
                except Exception:  # noqa: BLE001 — logging must never raise
                    pass

    def snapshot(self, buf: Deque[str]) -> List[str]:
        with self._route_lock:
            return list(buf)


class LocalKubelet:
    """Watches pods and runs their entrypoints on daemon threads."""

    def __init__(
        self,
        clientset: Clientset,
        name: str = "local-kubelet",
        lease_duration_s: Optional[float] = None,
        lease_renew_s: Optional[float] = None,
    ):
        self.cs = clientset
        self.name = name
        self.lease_duration_s = (
            float(os.environ.get(
                "TFK8S_NODE_LEASE_DURATION_S", NODE_LEASE_DURATION_DEFAULT_S
            ))
            if lease_duration_s is None
            else lease_duration_s
        )
        self.lease_renew_s = (
            float(os.environ.get(
                "TFK8S_NODE_LEASE_RENEW_S", NODE_LEASE_RENEW_DEFAULT_S
            ))
            if lease_renew_s is None
            else lease_renew_s
        )
        self.informer = SharedIndexInformer(clientset.pods(namespace=None), name="kubelet-pod")
        self.informer.add_event_handler(
            ResourceEventHandler(
                on_add=self._maybe_run,
                on_update=self._on_update,
                on_delete=self._on_delete,
            )
        )
        self._claimed: Dict[Tuple[str, str], PodStopSignal] = {}
        self._lock = threading.Lock()
        # chaos-harness hook (tests/chaos.py): (key, uid) -> failure
        # message. A poisoned pod's thread raises when its entrypoint
        # returns — the hermetic simulation of the host dying out from
        # under the process (dropped/late reclaim notice).
        self._chaos_fail: Dict[Tuple[str, str], str] = {}
        # Always a real Event (run() swaps in the caller's): every retry
        # wait in this file can be a stop-aware _stop.wait, so shutdown
        # never stalls behind a fixed sleep. _started gates the loops
        # that must not spin before run().
        self._stop: threading.Event = threading.Event()
        self._started = False
        self._fail_counts: Dict[str, int] = {}
        # (pod key, uid) -> live log buffer, drained by the flusher
        self._log_bufs: Dict[Tuple[str, str], Deque[str]] = {}
        # last tail actually published per pod — skips the per-cycle GET
        # for pods whose buffer hasn't changed
        self._log_published: Dict[Tuple[str, str], List[str]] = {}
        # (pod key, uid) -> entrypoint thread ident, for reading the
        # thread's training-progress report (runtime/progress.py)
        self._progress_idents: Dict[Tuple[str, str], int] = {}
        self._progress_published: Dict[Tuple[str, str], Dict[str, float]] = {}
        self._log_router = _PodLogRouter()

    def run(self, stop: threading.Event) -> None:
        self._stop = stop
        self._started = True
        tfk8s_logger = logging.getLogger("tfk8s")
        tfk8s_logger.addHandler(self._log_router)
        # The node agent must see container INFO logs even when the
        # process never called init_logging (hermetic tests): an unset
        # level would inherit the root default (WARNING) and drop the
        # records before they reach any handler.
        if tfk8s_logger.getEffectiveLevel() > logging.INFO:
            tfk8s_logger.setLevel(logging.INFO)
        self.informer.run(stop)
        threading.Thread(
            target=self._flush_logs_loop, name=f"{self.name}-logflush", daemon=True
        ).start()
        threading.Thread(
            target=self._heartbeat_loop, name=f"{self.name}-heartbeat", daemon=True
        ).start()

    # -- node heartbeat -----------------------------------------------------

    def _heartbeat_loop(self) -> None:
        """Renew this node's Lease until stopped. Best-effort: apiserver
        flaps are logged and retried — the controller only acts once the
        lease is STALE, so transient failures inside the lease duration
        are invisible."""
        import time

        from tfk8s_tpu.api.types import Lease, LeaseSpec, ObjectMeta
        from tfk8s_tpu.client.store import StoreError

        leases = self.cs.generic("Lease", "default")
        name = NODE_LEASE_PREFIX + self.name
        while not self._stop.is_set():
            now = time.time()
            try:
                try:
                    lease = leases.get(name)
                    lease.spec.holder = self.name
                    lease.spec.lease_duration_s = self.lease_duration_s
                    lease.spec.renew_time = now
                    leases.update(lease)
                except NotFound:
                    leases.create(
                        Lease(
                            metadata=ObjectMeta(name=name, namespace="default"),
                            spec=LeaseSpec(
                                holder=self.name,
                                lease_duration_s=self.lease_duration_s,
                                acquire_time=now,
                                renew_time=now,
                            ),
                        )
                    )
            except (StoreError, OSError) as e:
                log.debug("%s: heartbeat failed: %s", self.name, e)
            self._stop.wait(self.lease_renew_s)

    # -- pod log plumbing ---------------------------------------------------

    def _flush_logs_loop(self) -> None:
        """Periodically publish running pods' captured log tails into pod
        status, so `logs` works mid-run (final flush rides the terminal
        _set_phase). Runs OUTSIDE the logging handler — a flush that
        itself logs (update conflicts) must not recurse into capture."""
        while not self._stop.is_set():
            try:
                with self._lock:
                    snapshot = {
                        k: self._log_router.snapshot(buf)
                        for k, buf in self._log_bufs.items()
                    }
                    idents = dict(self._progress_idents)
                for (key, uid), lines in snapshot.items():
                    training = (
                        _progress.snapshot(idents[(key, uid)])
                        if (key, uid) in idents
                        else {}
                    )
                    stale_logs = (
                        lines and self._log_published.get((key, uid)) != lines
                    )
                    stale_training = (
                        training
                        and self._progress_published.get((key, uid)) != training
                    )
                    if stale_logs or stale_training:
                        self._publish_status(key, uid, lines, training)
            except Exception:  # noqa: BLE001 — the flusher must survive
                log.debug("log flush cycle failed:\n%s", traceback.format_exc())
            self._stop.wait(LOG_FLUSH_SECONDS)
        logging.getLogger("tfk8s").removeHandler(self._log_router)

    def _publish_status(
        self, pod_key: str, uid: str, lines: List[str],
        training: Optional[Dict[str, float]] = None,
    ) -> bool:
        # the terminal _set_phase owns the FINAL tail: once the pod's
        # buffer is retired, a stale snapshot must not overwrite it
        with self._lock:
            if (pod_key, uid) not in self._log_bufs:
                return False
        ns, name = pod_key.split("/", 1)
        for _ in range(3):
            try:
                current = self.cs.pods(ns).get(name)
            except NotFound:
                return False
            if current.metadata.uid != uid:
                return False
            if current.status.phase in (
                PodPhase.SUCCEEDED, PodPhase.FAILED, PodPhase.DRAINED
            ):
                return False  # terminal writer already published
            if (
                current.status.log_tail == lines
                and (not training or current.status.training == training)
            ):
                self._log_published[(pod_key, uid)] = lines
                if training:
                    self._progress_published[(pod_key, uid)] = training
                return True  # nothing new since the last flush
            current.status.log_tail = lines
            if training:
                current.status.training = dict(training)
            try:
                self.cs.pods(ns).update_status(current)
                self._log_published[(pod_key, uid)] = lines
                if training:
                    self._progress_published[(pod_key, uid)] = training
                return True
            except Conflict:
                continue
            except NotFound:
                return False
        return False

    # -- pod lifecycle ------------------------------------------------------

    def _on_update(self, old: Pod, new: Pod) -> None:
        if new.metadata.deletion_timestamp is not None:
            self._signal_stop(new.metadata.key)
            return
        reclaim_at = parse_reclaim_at(new)
        if reclaim_at is not None:
            self._signal_drain(new.metadata.key, reclaim_at)
        self._maybe_run(new)

    def _on_delete(self, obj) -> None:
        # Deletion is how the controller stops a pod (gang restart,
        # teardown): signal the entrypoint's stop event so the old trainer
        # exits instead of running concurrently with its replacement.
        meta = getattr(obj, "obj", obj).metadata  # unwrap DeletedFinalStateUnknown
        self._signal_stop(meta.key)

    def _signal_stop(self, key: str) -> None:
        with self._lock:
            evs = [ev for (k, _uid), ev in self._claimed.items() if k == key]
        for ev in evs:
            ev.set()

    def _signal_drain(self, key: str, deadline: float) -> None:
        with self._lock:
            evs = [ev for (k, _uid), ev in self._claimed.items() if k == key]
        for ev in evs:
            ev.request_drain(deadline)

    # -- reclaim / chaos hooks ---------------------------------------------

    def deliver_reclaim(self, pod_key: str, grace_s: float) -> float:
        """Deliver a reclaim notice to one pod: stamp the deadline
        annotation through the apiserver (so every watcher — controller
        included — sees the notice) AND signal the local drain event
        directly, so the grace clock starts now rather than a watch
        round-trip later. Returns the deadline."""
        deadline = time.time() + grace_s
        ns, name = pod_key.split("/", 1)
        try:
            self.cs.pods(ns).patch(name, reclaim_patch(deadline))
        except (NotFound, Conflict, Unavailable, OSError) as e:
            log.warning("%s: reclaim annotation for %s failed: %s",
                        self.name, pod_key, e)
        self._signal_drain(pod_key, deadline)
        return deadline

    def reclaim_node(self, grace_s: float) -> List[str]:
        """Node-level reclaim notice (the v5p 30-second pull): mark THIS
        node's Lease with the reclaim deadline — the ReclaimNotice node
        condition any controller can observe — and drain every pod the
        node is running. Returns the notified pod keys."""
        deadline = time.time() + grace_s
        try:
            leases = self.cs.generic("Lease", "default")
            lease = leases.get(NODE_LEASE_PREFIX + self.name)
            lease.metadata.annotations.update(
                reclaim_patch(deadline)["metadata"]["annotations"]
            )
            leases.update(lease)
        except Exception as e:  # noqa: BLE001 — notice delivery is best-effort
            log.warning("%s: node reclaim condition failed: %s", self.name, e)
        with self._lock:
            keys = sorted({k for (k, _uid) in self._claimed})
        for key in keys:
            self.deliver_reclaim(key, grace_s)
        return keys

    def chaos_fail(self, pod_key: str, message: str = "chaos: node died") -> None:
        """Chaos-harness hook: kill a pod's host WITHOUT (or after) a
        notice — the entrypoint is hard-stopped and its exit is recorded
        as FAILED with ``message``, even if it was mid-drain. This is how
        tests/chaos.py simulates a dropped or late reclaim notice."""
        with self._lock:
            targets = [
                (claim, ev) for claim, ev in self._claimed.items()
                if claim[0] == pod_key
            ]
            for claim, _ev in targets:
                self._chaos_fail[claim] = message
        for _claim, ev in targets:
            ev.set()

    def _maybe_run(self, pod: Pod) -> None:
        if pod.status.phase != PodPhase.PENDING:
            return
        # Claims are keyed by (key, uid): a recreated pod reuses its name but
        # gets a fresh uid, so it is a new claim even if the old thread is
        # still draining.
        claim = (pod.metadata.key, pod.metadata.uid)
        with self._lock:
            if claim in self._claimed:
                return
            pod_stop = PodStopSignal()
            self._claimed[claim] = pod_stop
        reclaim_at = parse_reclaim_at(pod)
        if reclaim_at is not None:
            pod_stop.request_drain(reclaim_at)
        t = threading.Thread(
            target=self._run_pod, args=(pod, pod_stop), name=f"pod-{pod.metadata.name}",
            daemon=True,
        )
        t.start()

    def _set_phase(
        self, pod_key: str, uid: str, phase: PodPhase, message: str = "",
        exit_code=None, log_tail: Optional[List[str]] = None,
        training: Optional[Dict[str, float]] = None,
    ) -> bool:
        """Phase writes must survive a transient apiserver outage: a
        SUCCEEDED/FAILED result dropped on the floor leaves the pod Running
        forever in a journal-restored store (no later event corrects it).
        Unavailable (connection refused/reset, 5xx) retries with backoff
        until the kubelet stops or the outage outlasts
        ``STATUS_WRITE_RETRY_S``; permanent errors (401/403/422) fail fast
        — they will never succeed by waiting. Conflict retries are folded
        into the same loop (each iteration re-reads)."""
        ns, name = pod_key.split("/", 1)
        deadline = time.monotonic() + STATUS_WRITE_RETRY_S
        conflicts = 0
        while True:
            try:
                current = self.cs.pods(ns).get(name)
                if current.metadata.uid != uid:
                    return False  # a successor pod took this name; not ours
                current.status.phase = phase
                current.status.message = message
                current.status.exit_code = exit_code
                current.status.host = self.name
                if log_tail is not None:
                    current.status.log_tail = log_tail
                if training:
                    current.status.training = dict(training)
                self.cs.pods(ns).update_status(current)
                return True
            except NotFound:
                return False
            except Conflict:
                # Bounded by the SAME deadline as outages, not a fixed
                # count: each iteration re-reads and can succeed, so 409s
                # accumulated across a long outage must never abort a
                # terminal SUCCEEDED/FAILED write (ADVICE r5 — the exact
                # dropped-outcome this loop exists to prevent). The brief
                # pause keeps a racing writer from turning this into a
                # hot re-read loop.
                conflicts += 1
                if time.monotonic() > deadline:
                    log.warning(
                        "%s: giving up updating %s to %s (%d conflicts, "
                        "deadline exceeded)",
                        self.name, pod_key, phase, conflicts,
                    )
                    return False
                # real sleep, NOT _stop.wait: conflicts are retried even
                # during shutdown (the final phases are the point of
                # stopping gracefully), and wait() on a set event returns
                # immediately — which would turn this into the hot
                # re-read loop the pause exists to prevent
                time.sleep(0.05)
                continue
            except (Unavailable, OSError) as e:
                stopping = self._stop.is_set()
                if stopping or time.monotonic() > deadline:
                    log.warning(
                        "%s: dropping %s -> %s (%s; %s)", self.name, pod_key,
                        phase, e, "stopping" if stopping else "outage too long",
                    )
                    return False
                log.info(
                    "%s: apiserver unreachable writing %s -> %s; retrying: %s",
                    self.name, pod_key, phase, e,
                )
                # stop-aware retry wait: a kubelet shutting down mid-
                # outage must not stall a second per pending retry (the
                # next loop iteration sees the stop and drops cleanly)
                self._stop.wait(1.0)

    def _run_pod(self, pod: Pod, pod_stop: threading.Event) -> None:
        key, uid = pod.metadata.key, pod.metadata.uid
        ident = threading.get_ident()
        buf = self._log_router.register(ident)
        # Thread idents are REUSED by the OS: a progress slot leaked by a
        # previous occupant of this ident (e.g. a direct run_task outside
        # any kubelet) must not surface as THIS pod's training progress
        # until its first real report.
        _progress.clear(ident)
        with self._lock:
            self._log_bufs[(key, uid)] = buf
            self._progress_idents[(key, uid)] = ident
        # Continue the trace the creating controller sync stamped into the
        # pod env (obs/trace.py): the launch span is the bridge between
        # the reconcile spans and the trainer's spans. The env copy is
        # shared with the entrypoint call; a malformed spec (no
        # containers) leaves it empty here and fails inside the span,
        # where the ordinary FAILED path records it.
        try:
            env = dict(pod.spec.containers[0].env)
        except Exception:  # noqa: BLE001
            env = {}
        span = get_tracer().start_span(
            "kubelet.launch",
            traceparent=env.get(TRACEPARENT_ENV),
            attributes={"pod": key, "node": self.name},
        )
        try:
            with span:
                container = pod.spec.containers[0]
                # test-only failure injection
                fail_times = int(env.get("TFK8S_TEST_FAIL_TIMES", "0"))
                if not self._set_phase(key, uid, PodPhase.RUNNING):
                    return
                if fail_times:
                    with self._lock:
                        n = self._fail_counts.get(pod.metadata.name, 0)
                        self._fail_counts[pod.metadata.name] = n + 1
                    if n < fail_times:
                        raise RuntimeError(
                            f"injected failure {n + 1}/{fail_times}"
                        )
                fn = registry.resolve(container.entrypoint)
                try:
                    registry.call(fn, env, pod_stop)
                    phase, message, code = PodPhase.SUCCEEDED, "", 0
                except PodDrained as e:
                    # the entrypoint honored the reclaim notice: in-flight
                    # step finished, drain checkpoint committed — a
                    # GRACEFUL terminal phase, not a failure
                    phase, message, code = PodPhase.DRAINED, str(e), 0
                # chaos poison outranks the entrypoint's own exit: the
                # "host" died, so even a drained result never made it out
                poison = self._chaos_fail.pop((key, uid), None)
                if poison is not None:
                    raise RuntimeError(poison)
                # the terminal write carries the FINAL progress report too
                # — the 1s flusher usually misses the report fired right
                # before the entrypoint returns (the step==steps boundary)
                self._set_phase(
                    key, uid, phase, message=message, exit_code=code,
                    log_tail=list(buf), training=_progress.snapshot(ident),
                )
        except Exception as e:  # noqa: BLE001 — container or kubelet failure
            log.info("%s: pod %s failed: %s", self.name, key, e)
            try:
                self._set_phase(
                    key, uid, PodPhase.FAILED,
                    message=f"{type(e).__name__}: {e}", exit_code=1,
                    log_tail=list(buf), training=_progress.snapshot(ident),
                )
            except Exception:  # noqa: BLE001 — apiserver gone (teardown)
                log.debug("%s: terminal status write for %s failed:\n%s",
                          self.name, key, traceback.format_exc())
            log.debug("%s", traceback.format_exc())
        finally:
            self._log_router.unregister(ident)
            _progress.clear(ident)
            with self._lock:
                self._claimed.pop((key, uid), None)
                self._chaos_fail.pop((key, uid), None)
                self._log_bufs.pop((key, uid), None)
                self._log_published.pop((key, uid), None)
                self._progress_idents.pop((key, uid), None)
                self._progress_published.pop((key, uid), None)
