"""LocalKubelet: executes pods in-process — the node agent of the hermetic
backend.

In the reference's world the kubelet pulls the image and starts the
container, which is the control->data plane handoff (SURVEY.md §3.3,
'PROCESS+MACHINE BOUNDARY'). Here each pod's entrypoint runs on a thread:
the kubelet claims Pending pods from the watch, flips them to Running,
invokes the entrypoint with the pod's env (the JAX coordination contract),
and records Succeeded/Failed with the exit message — which flows back
through the watch into the controller's reconcile, closing the loop of
SURVEY.md §3.5.

Failure injection for tests: an env of ``TFK8S_TEST_FAIL_TIMES=n`` makes a
pod raise on its first n attempts per pod name (counted in-process), which
exercises restart policies end-to-end.
"""

from __future__ import annotations

import threading
import traceback
from typing import Dict, Optional

from tfk8s_tpu.api.types import Pod, PodPhase
from tfk8s_tpu.client.clientset import Clientset
from tfk8s_tpu.client.informer import ResourceEventHandler, SharedIndexInformer
from tfk8s_tpu.client.store import Conflict, NotFound
from tfk8s_tpu.runtime import registry
from tfk8s_tpu.utils.logging import get_logger

log = get_logger("kubelet")


class LocalKubelet:
    """Watches pods and runs their entrypoints on daemon threads."""

    def __init__(self, clientset: Clientset, name: str = "local-kubelet"):
        self.cs = clientset
        self.name = name
        self.informer = SharedIndexInformer(clientset.pods(namespace=None), name="kubelet-pod")
        self.informer.add_event_handler(
            ResourceEventHandler(
                on_add=self._maybe_run,
                on_update=self._on_update,
                on_delete=self._on_delete,
            )
        )
        self._claimed: Dict[str, threading.Event] = {}
        self._lock = threading.Lock()
        self._stop: Optional[threading.Event] = None
        self._fail_counts: Dict[str, int] = {}

    def run(self, stop: threading.Event) -> None:
        self._stop = stop
        self.informer.run(stop)

    # -- pod lifecycle ------------------------------------------------------

    def _on_update(self, old: Pod, new: Pod) -> None:
        if new.metadata.deletion_timestamp is not None:
            self._signal_stop(new.metadata.key)
        else:
            self._maybe_run(new)

    def _on_delete(self, obj) -> None:
        # Deletion is how the controller stops a pod (gang restart,
        # teardown): signal the entrypoint's stop event so the old trainer
        # exits instead of running concurrently with its replacement.
        meta = getattr(obj, "obj", obj).metadata  # unwrap DeletedFinalStateUnknown
        self._signal_stop(meta.key)

    def _signal_stop(self, key: str) -> None:
        with self._lock:
            evs = [ev for (k, _uid), ev in self._claimed.items() if k == key]
        for ev in evs:
            ev.set()

    def _maybe_run(self, pod: Pod) -> None:
        if pod.status.phase != PodPhase.PENDING:
            return
        # Claims are keyed by (key, uid): a recreated pod reuses its name but
        # gets a fresh uid, so it is a new claim even if the old thread is
        # still draining.
        claim = (pod.metadata.key, pod.metadata.uid)
        with self._lock:
            if claim in self._claimed:
                return
            pod_stop = threading.Event()
            self._claimed[claim] = pod_stop
        t = threading.Thread(
            target=self._run_pod, args=(pod, pod_stop), name=f"pod-{pod.metadata.name}",
            daemon=True,
        )
        t.start()

    def _set_phase(self, pod_key: str, uid: str, phase: PodPhase, message: str = "", exit_code=None) -> bool:
        ns, name = pod_key.split("/", 1)
        for _ in range(5):
            try:
                current = self.cs.pods(ns).get(name)
            except NotFound:
                return False
            if current.metadata.uid != uid:
                return False  # a successor pod took this name; not ours
            current.status.phase = phase
            current.status.message = message
            current.status.exit_code = exit_code
            current.status.host = self.name
            try:
                self.cs.pods(ns).update_status(current)
                return True
            except Conflict:
                continue
            except NotFound:
                return False
        log.warning("%s: giving up updating %s to %s", self.name, pod_key, phase)
        return False

    def _run_pod(self, pod: Pod, pod_stop: threading.Event) -> None:
        key, uid = pod.metadata.key, pod.metadata.uid
        try:
            container = pod.spec.containers[0]
            env = dict(container.env)
            # test-only failure injection
            fail_times = int(env.get("TFK8S_TEST_FAIL_TIMES", "0"))
            if not self._set_phase(key, uid, PodPhase.RUNNING):
                return
            if fail_times:
                with self._lock:
                    n = self._fail_counts.get(pod.metadata.name, 0)
                    self._fail_counts[pod.metadata.name] = n + 1
                if n < fail_times:
                    raise RuntimeError(f"injected failure {n + 1}/{fail_times}")
            fn = registry.resolve(container.entrypoint)
            registry.call(fn, env, pod_stop)
            self._set_phase(key, uid, PodPhase.SUCCEEDED, exit_code=0)
        except Exception as e:  # noqa: BLE001 — container failure, not ours
            log.info("%s: pod %s failed: %s", self.name, key, e)
            self._set_phase(
                key,
                uid,
                PodPhase.FAILED,
                message=f"{type(e).__name__}: {e}",
                exit_code=1,
            )
            log.debug("%s", traceback.format_exc())
        finally:
            with self._lock:
                self._claimed.pop((key, uid), None)
