"""Lease-based leader election — SURVEY.md C17 ("uses the leaderelection
package for high availability", k8s-operator.md:59; design heading
k8s-operator.md:237).

Only the lease holder runs the reconcile loop; standbys poll and take over
when the lease expires. Acquisition and renewal go through the store's
optimistic-concurrency update, so two candidates racing produce exactly one
winner (the loser's write fails with Conflict).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from tfk8s_tpu.api.types import Lease, LeaseSpec, ObjectMeta
from tfk8s_tpu.client.store import AlreadyExists, Conflict, NotFound
from tfk8s_tpu.utils.logging import get_logger

log = get_logger("leaderelection")


class LeaderElector:
    def __init__(
        self,
        client,  # TypedClient for kind Lease
        identity: str,
        lease_name: str = "tfk8s-tpu-operator",
        namespace: str = "default",
        lease_duration_s: float = 15.0,
        renew_period_s: float = 5.0,
        retry_period_s: float = 2.0,
        clock: Callable[[], float] = time.time,
    ):
        self.client = client
        self.identity = identity
        self.lease_name = lease_name
        self.namespace = namespace
        self.lease_duration_s = lease_duration_s
        self.renew_period_s = renew_period_s
        self.retry_period_s = retry_period_s
        self._clock = clock
        self._is_leader = False

    @property
    def is_leader(self) -> bool:
        return self._is_leader

    # -- lease arithmetic ---------------------------------------------------

    def _expired(self, lease: Lease) -> bool:
        if not lease.spec.holder:
            return True  # released leases are immediately up for grabs
        rt = lease.spec.renew_time
        if rt is None:
            rt = lease.spec.acquire_time if lease.spec.acquire_time is not None else 0.0
        return self._clock() > rt + lease.spec.lease_duration_s

    def try_acquire_or_renew(self) -> bool:
        """One acquisition/renewal attempt. Returns True while leading."""
        now = self._clock()
        try:
            lease = self.client.get(self.lease_name)
        except NotFound:
            lease = Lease(
                metadata=ObjectMeta(name=self.lease_name, namespace=self.namespace),
                spec=LeaseSpec(
                    holder=self.identity,
                    lease_duration_s=self.lease_duration_s,
                    acquire_time=now,
                    renew_time=now,
                ),
            )
            try:
                self.client.create(lease)
                self._is_leader = True
                log.info("%s: acquired new lease %s", self.identity, self.lease_name)
                return True
            except AlreadyExists:
                return False

        if lease.spec.holder != self.identity and not self._expired(lease):
            self._is_leader = False
            return False

        if lease.spec.holder != self.identity:
            lease.spec.lease_transitions += 1
            lease.spec.acquire_time = now
            log.info(
                "%s: taking over expired lease from %s", self.identity, lease.spec.holder
            )
        lease.spec.holder = self.identity
        lease.spec.renew_time = now
        try:
            self.client.update(lease)
        except (Conflict, NotFound):
            self._is_leader = False
            return False
        self._is_leader = True
        return True

    def release(self) -> None:
        """Voluntarily drop the lease so a standby takes over immediately.
        Best-effort: on a dead/unreachable apiserver (process teardown)
        the lease simply expires instead — never raise from here."""
        try:
            lease = self.client.get(self.lease_name)
            if lease.spec.holder == self.identity:
                lease.spec.holder = ""
                lease.spec.renew_time = None
                self.client.update(lease)
        except Exception:  # noqa: BLE001 — includes remote transport errors
            pass
        self._is_leader = False

    # -- run ----------------------------------------------------------------

    def run(
        self,
        on_started_leading: Callable[[threading.Event], None],
        stop: threading.Event,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ) -> None:
        """Block until leadership is acquired, call ``on_started_leading``
        (with a child stop event), keep renewing in the background, and fire
        ``on_stopped_leading`` if the lease is lost (k8s-operator.md:59 —
        the leaderelection gate ahead of Controller.Run)."""
        while not stop.is_set():
            if self.try_acquire_or_renew():
                break
            stop.wait(self.retry_period_s)
        if stop.is_set():
            return

        lost = threading.Event()

        def renew_loop():
            while not stop.is_set() and not lost.is_set():
                stop.wait(self.renew_period_s)
                if stop.is_set():
                    break
                if not self.try_acquire_or_renew():
                    log.warning("%s: lost lease %s", self.identity, self.lease_name)
                    lost.set()
            if stop.is_set():
                self.release()

        renewer = threading.Thread(target=renew_loop, name="lease-renewer", daemon=True)
        renewer.start()

        child_stop = threading.Event()

        def propagate():
            while not stop.is_set() and not lost.is_set():
                lost.wait(0.2) or stop.wait(0.2)
            child_stop.set()

        threading.Thread(target=propagate, daemon=True).start()
        try:
            on_started_leading(child_stop)
        finally:
            if lost.is_set() and on_stopped_leading:
                on_stopped_leading()
