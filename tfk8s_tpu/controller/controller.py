"""Generic level-triggered controller core — SURVEY.md C15.

The exact machinery of the reference's sample controller
(k8s-operator.md:80-203), componentized:

- events enqueue **keys** (namespace/name) through
  ``DeletionHandlingMetaNamespaceKeyFunc`` (k8s-operator.md:132-139);
- an update filter skips no-op enqueues (the PodIP-diff pattern,
  k8s-operator.md:142-150);
- ``run(workers, stop)``: start informers, ``wait_for_cache_sync`` barrier,
  spawn N worker threads, block on stop, shut the queue down
  (k8s-operator.md:184-203);
- each worker: ``get -> lookup in cache -> sync -> done`` with rate-limited
  requeue on error and ``forget`` on success — the hot loop the system's
  latency hangs off (SURVEY.md §3.2).

Controllers supply ``sync(key)``; everything else is shared.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Callable, List, Optional, Sequence

from tfk8s_tpu.client.informer import (
    ResourceEventHandler,
    SharedIndexInformer,
    deletion_handling_key,
    wait_for_cache_sync,
)
from tfk8s_tpu.client.workqueue import RateLimitingQueue
from tfk8s_tpu.obs.trace import Tracer, get_tracer
from tfk8s_tpu.utils.logging import EventRecorder, Metrics, get_logger

log = get_logger("controller")

# Default reconcile workers. The workqueue's dirty/processing accounting
# already guarantees per-key in-flight exclusion (one worker per key at a
# time — the single-writer contract), so extra workers only add
# parallelism across DIFFERENT keys; 4 keeps a burst of job submissions
# from serializing behind one slow sync even on a 1-core box, where the
# win is overlapping the waits (status round trips, rate-limiter sleeps).
DEFAULT_SYNC_WORKERS = 4


class Controller:
    """Informer-fed, workqueue-decoupled reconcile loop."""

    def __init__(
        self,
        name: str,
        sync: Callable[[str], None],
        informers: Sequence[SharedIndexInformer] = (),
        max_retries: int = 15,
        recorder: Optional[EventRecorder] = None,
        metrics: Optional[Metrics] = None,
        kind: str = "",
        tracer: Optional[Tracer] = None,
    ):
        self.name = name
        self.kind = kind or name
        self.sync = sync
        self.informers = list(informers)
        self.max_retries = max_retries
        self.recorder = recorder or EventRecorder()
        self.metrics = metrics or Metrics()
        self.tracer = tracer or get_tracer()
        self.queue = RateLimitingQueue(name, metrics=self.metrics)
        self.metrics.describe(
            f"{name}.syncs_total", "Successful reconcile passes."
        )
        self.metrics.describe(
            f"{name}.sync_errors_total", "Reconcile passes that raised."
        )
        self.metrics.describe(
            f"{name}.sync_seconds", "Wall time of one reconcile pass."
        )
        self._workers: List[threading.Thread] = []
        self._stop_event: Optional[threading.Event] = None

    # -- enqueue paths (k8s-operator.md:132-150) ----------------------------

    def enqueue(self, obj) -> None:
        self.queue.add(deletion_handling_key(obj))

    def enqueue_key(self, key: str) -> None:
        self.queue.add(key)

    def enqueue_after(self, key: str, delay: float) -> None:
        self.queue.add_after(key, delay)

    def default_handler(
        self, update_filter: Optional[Callable[[object, object], bool]] = None
    ) -> ResourceEventHandler:
        """Standard add/update/delete -> enqueue wiring. ``update_filter``
        returns True when an update is worth reconciling (the old/new diff
        check of k8s-operator.md:142-150); default: resource_version
        changed."""

        def on_update(old, new):
            if update_filter is not None:
                if not update_filter(old, new):
                    return
            elif (
                old is not None
                and old.metadata.resource_version == new.metadata.resource_version
            ):
                return
            self.enqueue(new)

        return ResourceEventHandler(
            on_add=self.enqueue, on_update=on_update, on_delete=self.enqueue
        )

    # -- run loop (k8s-operator.md:184-203) ---------------------------------

    def run(
        self,
        workers: int = DEFAULT_SYNC_WORKERS,
        stop: Optional[threading.Event] = None,
        block: bool = True,
    ) -> bool:
        """Start informers, wait for cache sync, run N workers. With
        ``block=True`` this only returns after ``stop`` is set (the
        reference's ``Run`` never returns until stopCh closes). Workers
        never process the same key concurrently (queue dedup), so the
        count is safe to raise — see DEFAULT_SYNC_WORKERS. With ``stop``
        omitted an internal event is created; :meth:`shutdown` sets it,
        so the informer/worker threads remain stoppable."""
        if stop is None:
            stop = threading.Event()
        self._stop_event = stop
        log.info("%s: starting", self.name)
        for inf in self.informers:
            inf.run(stop)
        if not wait_for_cache_sync(stop, *self.informers):
            log.error("%s: cache sync failed", self.name)
            return False
        log.info("%s: caches synced; starting %d workers", self.name, workers)
        for i in range(workers):
            t = threading.Thread(
                target=self._worker, name=f"{self.name}-worker-{i}", daemon=True
            )
            t.start()
            self._workers.append(t)
        if block:
            stop.wait()
            self.shutdown()
        return True

    def shutdown(self) -> None:
        log.info("%s: shutting down queue", self.name)
        # release the informer reflector threads too — essential when
        # run() fabricated the stop event (no other handle exists)
        if self._stop_event is not None:
            self._stop_event.set()
        self.queue.shut_down()
        for t in self._workers:
            t.join(timeout=5)

    # -- the hot loop (k8s-operator.md:153-181) ------------------------------

    def _worker(self) -> None:
        while True:
            key, shutting_down = self.queue.get()
            if shutting_down:
                return
            if key is None:
                continue
            # time-in-queue, measured by the queue at dequeue — recorded
            # retroactively as the reconcile trace's first child so the
            # trace shows waiting separately from working
            qlat = self.queue.pop_queue_latency(key)
            t0 = time.perf_counter()
            with self.tracer.start_span(
                "reconcile",
                attributes={"controller": self.name, "key": str(key)},
            ) as span:
                if qlat is not None:
                    self.tracer.record_span(
                        "dequeue",
                        start=span.start_time - qlat,
                        end=span.start_time,
                        parent=span,
                        attributes={"queue": self.name},
                    )
                try:
                    self.sync(key)
                except Exception as e:  # noqa: BLE001 — one bad key must not kill the worker
                    span.set_status("error", f"{type(e).__name__}: {e}")
                    self.metrics.inc(f"{self.name}.sync_errors_total")
                    retries = self.queue.num_requeues(key)
                    if retries < self.max_retries:
                        log.warning(
                            "%s: sync %s failed (retry %d/%d): %s",
                            self.name, key, retries + 1, self.max_retries, e,
                        )
                        self.queue.add_rate_limited(key)
                    else:
                        log.error(
                            "%s: sync %s dropped after %d retries:\n%s",
                            self.name, key, retries, traceback.format_exc(),
                        )
                        self.recorder.event(
                            self.kind, key, "SyncDropped",
                            f"gave up after {retries} retries: {e}",
                        )
                        self.queue.forget(key)
                else:
                    self.metrics.inc(f"{self.name}.syncs_total")
                    self.queue.forget(key)
                finally:
                    self.metrics.observe(
                        f"{self.name}.sync_seconds", time.perf_counter() - t0
                    )
                    self.queue.done(key)
