"""L4 controller layer: generic reconcile loop + leader election
(SURVEY.md C15/C17). The TPUJob-specific controller lives in
``tfk8s_tpu.trainer.tpujob_controller`` next to the trainer it drives.
"""

from tfk8s_tpu.controller.controller import Controller  # noqa: F401
from tfk8s_tpu.controller.leaderelection import LeaderElector  # noqa: F401
