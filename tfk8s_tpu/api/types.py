"""L1 API types: the TPUJob resource and the core objects it reconciles to.

TPU-native re-design of the reference's ``pkg/apis/tensorflow/v1alpha1/types.go``
(SURVEY.md C4; domain model at k8s-operator.md:6 — *task = one process per
machine; tasks form a job; jobs are PS or WORKER; jobs form a cluster*).

Differences from the reference, by design (SURVEY.md §0 north star):

- replica sets request TPU slices (``TPUSpec``: accelerator type + topology +
  num_slices) instead of ``nvidia.com/gpu`` counts;
- the job carries an optional ``MeshSpec`` — the logical device-mesh axes
  (data/fsdp/tensor/sequence/expert/pipeline) the data plane will build with
  ``jax.sharding.Mesh`` — because on TPU the parallelism layout is a
  *scheduling* concern (slice shape must match mesh shape), not a container
  detail;
- restart semantics keep the reference's ``OnFailure`` / ``Never`` meaning
  (k8s-operator.md:47-49) but add gang semantics: a TPU slice fails as a
  unit, so replica-level restart escalates to whole-gang restart-from-
  checkpoint (SURVEY.md §2 "Elastic / gang semantics").

Everything is a plain dataclass; serialization lives in ``api/serde.py``
(the scheme-registration equivalent of the reference's ``register.go``, C5).
"""

from __future__ import annotations

import copy
import dataclasses
import enum
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from tfk8s_tpu import API_VERSION


# Wire-encoding marker for epoch-seconds fields that serialize as RFC3339
# (api/serde.py to_wire). An explicit per-field registry — NOT a name
# heuristic — so a future duration named *_time can never be silently
# mangled into a timestamp.
RFC3339 = {"wire": "rfc3339"}


# ---------------------------------------------------------------------------
# Metadata (the k8s ObjectMeta equivalent; finalizer/deletion semantics per
# k8s-operator.md:36-43)
# ---------------------------------------------------------------------------


@dataclass
class OwnerReference:
    """Back-pointer from a child object (pod/service) to its owning TPUJob."""

    kind: str
    name: str
    uid: str
    controller: bool = True


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    resource_version: int = 0
    generation: int = 1
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    finalizers: List[str] = field(default_factory=list)
    owner_references: List[OwnerReference] = field(default_factory=list)
    creation_timestamp: Optional[float] = field(default=None, metadata=RFC3339)
    # Deletion only *marks* the object; controllers run finalizers and then
    # clear them, at which point the store actually removes the object
    # (k8s-operator.md:36-43).
    deletion_timestamp: Optional[float] = field(default=None, metadata=RFC3339)

    @property
    def key(self) -> str:
        """The ``namespace/name`` cache key (MetaNamespaceKeyFunc)."""
        return f"{self.namespace}/{self.name}"


# ---------------------------------------------------------------------------
# Enums
# ---------------------------------------------------------------------------


class ReplicaType(str, enum.Enum):
    """Replica roles. CHIEF/WORKER/PS mirror the reference's job types
    (k8s-operator.md:6; 'master/chief per north star' SURVEY.md C4)."""

    CHIEF = "Chief"
    WORKER = "Worker"
    PS = "PS"
    EVALUATOR = "Evaluator"


class RestartPolicy(str, enum.Enum):
    """Per-replica restart semantics (k8s-operator.md:47-49):

    - ON_FAILURE: restart the task in place.
    - NEVER: a failed task is replaced by a fresh one; the failed record is
      kept for inspection (completed pods are not auto-deleted,
      k8s-operator.md:50-52).
    - ALWAYS: restart regardless of exit status (long-running PS tasks).
    - EXIT_CODE: retryable exit codes restart in place, permanent codes fail
      the replica.
    """

    ALWAYS = "Always"
    ON_FAILURE = "OnFailure"
    NEVER = "Never"
    EXIT_CODE = "ExitCode"


class CleanPodPolicy(str, enum.Enum):
    """What to clean up when the job finishes."""

    ALL = "All"
    RUNNING = "Running"
    NONE = "None"


class JobConditionType(str, enum.Enum):
    CREATED = "Created"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    SUSPENDED = "Suspended"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


class PodPhase(str, enum.Enum):
    PENDING = "Pending"
    SCHEDULED = "Scheduled"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    # Terminal like FAILED, but GRACEFUL: the entrypoint honored a reclaim
    # notice (runtime/kubelet.py RECLAIM_AT_ANNOTATION) — finished its
    # in-flight step, committed a drain checkpoint, and exited. The job
    # controller answers a Drained worker with an elastic resize (or a
    # preemption-style restart) instead of burning backoff_limit.
    DRAINED = "Drained"


# ---------------------------------------------------------------------------
# Spec
# ---------------------------------------------------------------------------


@dataclass
class ContainerSpec:
    """What each replica task runs. ``entrypoint`` names a registered Python
    callable (the in-process/local backend analogue of an image+command);
    ``image``/``command`` are carried for real-cluster rendering parity."""

    entrypoint: str = ""
    image: str = ""
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    resources: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ReplicaSpec:
    """One replica set (the reference's TFReplicaSpec): N tasks of one role."""

    replicas: Optional[int] = None
    restart_policy: Optional[RestartPolicy] = None
    # Cap on per-replica restarts before the whole job is failed.
    max_restarts: Optional[int] = None
    template: ContainerSpec = field(default_factory=ContainerSpec)


@dataclass
class TPUSpec:
    """TPU slice request — replaces the reference's nvidia.com/gpu resource
    counts (north star, BASELINE.json). ``accelerator`` is a type string like
    ``v5p-32`` / ``v5litepod-8`` / ``cpu`` (hermetic tests); ``topology`` an
    optional explicit chip grid like ``2x2x4``; ``num_slices`` > 1 means
    multislice over DCN."""

    accelerator: str = ""
    topology: str = ""
    num_slices: int = 1
    # "" = hermetic/local rendering (tfk8s.dev/* node selectors only);
    # "gke" additionally renders google.com/tpu resource requests and
    # cloud.google.com/gke-tpu-* node selectors a real GKE TPU nodepool
    # admits (the north star's GKE provisioning, BASELINE.json)
    provider: str = ""


@dataclass
class MeshSpec:
    """Logical device-mesh axes for the data plane, in order. Axis names
    follow the scaling-book convention: data / fsdp / tensor / sequence /
    expert / pipeline (SURVEY.md §2 parallelism table). The product of sizes
    must equal chips-per-slice x num_slices (validated in api/validation.py).
    """

    axes: Dict[str, int] = field(default_factory=dict)

    def size(self) -> int:
        n = 1
        for v in self.axes.values():
            n *= v
        return n


@dataclass
class SchedulingPolicy:
    """Gang-scheduling knobs. TPU slices admit all-or-nothing by hardware
    construction (SURVEY.md §2 'Elastic / gang semantics')."""

    gang: bool = True
    priority: int = 0
    # Max seconds a gang may sit Pending before the job is marked Failed.
    admission_timeout_s: Optional[float] = None


@dataclass
class ElasticPolicy:
    """Elastic world sizing for the Worker replica set (TorchElastic-style
    min/max bounds translated to TPU gang semantics). When set, a Drained
    worker (reclaim notice honored, runtime/kubelet.py) shrinks the gang
    to the surviving count instead of triggering a whole-gang
    restart-from-checkpoint — as long as the survivors stay >=
    ``min_replicas`` — and the controller grows the gang back toward the
    spec count (debounced by ``resize_debounce_s``) when capacity
    returns. Resizes never consume ``backoff_limit``. On real TPU slices
    the resize granularity is a WHOLE slice (a slice admits and fails as
    a unit), so validation rejects bounds that are not slice-aligned."""

    min_replicas: Optional[int] = None
    max_replicas: Optional[int] = None
    # Seconds a downsized gang must hold steady before scaling back up —
    # capacity that flaps must not thrash the mesh.
    resize_debounce_s: Optional[float] = None


@dataclass
class RunPolicy:
    clean_pod_policy: Optional[CleanPodPolicy] = None
    ttl_seconds_after_finished: Optional[float] = None
    active_deadline_seconds: Optional[float] = None
    # Whole-gang restarts-from-checkpoint before the job is failed.
    backoff_limit: Optional[int] = None
    # Kueue-style suspend: True evicts the gang (pods deleted, slices
    # returned to the pool) while keeping the job object; flipping back
    # to False re-admits and resumes from checkpoint.
    suspend: bool = False
    scheduling: SchedulingPolicy = field(default_factory=SchedulingPolicy)
    # Elastic world sizing (None = fixed-size gang, the legacy semantics).
    elastic: Optional[ElasticPolicy] = None


@dataclass
class TPUJobSpec:
    replica_specs: Dict[ReplicaType, ReplicaSpec] = field(default_factory=dict)
    tpu: TPUSpec = field(default_factory=TPUSpec)
    mesh: Optional[MeshSpec] = None
    run_policy: RunPolicy = field(default_factory=RunPolicy)


# ---------------------------------------------------------------------------
# Status
# ---------------------------------------------------------------------------


@dataclass
class Condition:
    type: JobConditionType
    status: bool = True
    reason: str = ""
    message: str = ""
    last_transition_time: float = field(default_factory=time.time, metadata=RFC3339)


@dataclass
class ReplicaStatus:
    active: int = 0
    succeeded: int = 0
    failed: int = 0
    restarts: int = 0


@dataclass
class TPUJobStatus:
    conditions: List[Condition] = field(default_factory=list)
    replica_statuses: Dict[ReplicaType, ReplicaStatus] = field(default_factory=dict)
    start_time: Optional[float] = field(default=None, metadata=RFC3339)
    completion_time: Optional[float] = field(default=None, metadata=RFC3339)
    # Whole-gang restarts performed so far (counts against backoff_limit).
    gang_restarts: int = 0
    # Times this job's gang was evicted without failing: preempted by a
    # higher-priority job, or suspended via RunPolicy.suspend. An
    # eviction IS a gang restart for the resume contract (the recreated
    # gang restores from checkpoint) but does NOT consume backoff_limit.
    preemptions: int = 0
    # Checkpoint step the gang last persisted (resume point on restart).
    checkpoint_step: Optional[int] = None
    # Elastic state (RunPolicy.elastic): the CURRENT effective Worker
    # count (None = the spec-desired count), and a monotonically bumped
    # world version rendered into every pod as TFK8S_WORLD_VERSION — a
    # resize re-forms the mesh at the new size and the nonzero version
    # makes the relaunched processes resume from the drain checkpoint.
    elastic_replicas: Optional[int] = None
    world_version: int = 0


# ---------------------------------------------------------------------------
# Serving (TPUServe): the inference workload — a replicated, dynamically
# batched model server with rolling updates and a queue-depth autoscaler.
# The training CRD reconciles a *gang* (all-or-nothing, fails as a unit);
# serving replicas are deliberately independent: each holds its own model
# copy, so the controller can surge/drain them one at a time.
# ---------------------------------------------------------------------------


class ServeConditionType(str, enum.Enum):
    AVAILABLE = "Available"    # ready replicas >= spec.replicas, all updated
    PROGRESSING = "Progressing"  # a rollout or scale is converging
    DEGRADED = "Degraded"      # validation failed / replicas crash-looping


@dataclass
class ServeCondition:
    type: ServeConditionType
    status: bool = True
    reason: str = ""
    message: str = ""
    last_transition_time: float = field(default_factory=time.time, metadata=RFC3339)


@dataclass
class SamplingParams:
    """Per-request token sampling knobs (models/gpt.filter_logits
    semantics, threaded per-row through the packed decode step).
    ``temperature`` 0 means greedy — bit-identical to the argmax path;
    ``top_k`` 0 and ``top_p`` 1.0 disable their cuts. ``seed`` plus the
    absolute-position PRNG fold make a sampled stream deterministic
    under resume (preemption spill/restore, KV handoff).

    This dataclass IS the wire contract for a request's ``sampling``
    block: runtime/server's request parsing normalizes through
    :meth:`from_payload`, so the defaults and ranges here are what the
    serving path enforces."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0

    @classmethod
    def from_payload(cls, raw: Any) -> "SamplingParams":
        """Normalize a request payload's ``sampling`` block. Accepts both
        wire casings (``topK``/``top_k``) since gateway payloads arrive
        camelCase while tests speak snake_case. Raises ``ValueError`` on
        malformed blocks and out-of-range knobs — callers on the serving
        path re-type it as their client-visible InvalidRequest."""
        if not isinstance(raw, dict):
            raise ValueError(
                f"sampling must be a dict, got {type(raw).__name__}"
            )

        def _get(snake: str, camel: str, default):
            return raw.get(snake, raw.get(camel, default))

        try:
            params = cls(
                temperature=float(_get("temperature", "temperature", 0.0)),
                top_k=int(_get("top_k", "topK", 0)),
                top_p=float(_get("top_p", "topP", 1.0)),
                seed=int(_get("seed", "seed", 0)),
            )
        except (TypeError, ValueError):
            raise ValueError(f"malformed sampling block: {raw!r}") from None
        if params.temperature < 0:
            raise ValueError(
                f"temperature must be >= 0, got {params.temperature}"
            )
        if params.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {params.top_k}")
        if not 0.0 < params.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {params.top_p}")
        return params

    def as_tuple(self) -> "tuple":
        """The (temperature, top_k, top_p, seed) form the decode loop
        threads through the packed device step."""
        return (self.temperature, self.top_k, self.top_p, self.seed)


@dataclass
class SchedulerPolicy:
    """Token-scheduler knobs for the decode loop (runtime/sched).
    ``policy`` picks admission order: ``fifo`` (arrival order, the
    default — bit-identical to pre-scheduler behavior) or ``priority``
    (per-priority-class queues, aged weighted pick; a request gains one
    priority level per ``aging_s`` seconds queued, the anti-starvation
    bound). ``preemption`` (priority policy only) lets a stalled
    higher-priority admission spill a low-priority row's KV pages to a
    host buffer and requeue it. ``spec_decode`` enables speculative
    decoding: a ``spec_draft``-sized draft model proposes ``spec_tokens``
    tokens per row and the serving model verifies them in one packed
    step — output token-identical to plain decoding, throughput up by
    the accept ratio."""

    policy: str = "fifo"
    preemption: bool = True
    aging_s: float = 5.0
    spec_decode: bool = False
    spec_tokens: int = 4
    spec_draft: str = "tiny"


@dataclass
class BatchingPolicy:
    """Dynamic micro-batching knobs (runtime/server.py): a batch closes at
    ``max_batch_size`` or after ``batch_timeout_ms`` — whichever first —
    and the request queue is bounded at ``queue_limit``; past it, submits
    shed with the typed overload error instead of queuing unboundedly
    (Clipper-style adaptive batching under a latency SLO).

    Generative tasks run the continuous-batching decode loop instead:
    ``max_batch_size`` becomes the decode SLOT capacity (rows admitted
    and retired at token granularity), and the block-paged KV cache is
    sized by ``page_size`` (tokens per page) × ``max_pages`` (pool
    pages, one reserved as the trash page) — admission is gated on the
    pool covering a request's worst-case prompt + generation budget, so
    out-of-pages stalls admission and never corrupts live rows."""

    max_batch_size: int = 8
    batch_timeout_ms: float = 10.0
    queue_limit: int = 128
    # block-paged KV cache (decode loop only; ignored by classifiers)
    page_size: int = 16
    max_pages: int = 256
    # token scheduler (decode loop only): admission order, preemption,
    # speculative decode — see SchedulerPolicy
    scheduler: SchedulerPolicy = field(default_factory=SchedulerPolicy)


@dataclass
class RollingUpdatePolicy:
    """Deployment-style surge rollout: during an update at most
    ``max_surge`` replicas exist above ``spec.replicas``, and the count of
    READY replicas never drops below ``replicas - max_unavailable`` (old
    replicas drain before deletion, gated on new ones passing readiness)."""

    max_surge: int = 1
    max_unavailable: int = 0


@dataclass
class AutoscalePolicy:
    """Queue-depth autoscaling: the controller smooths the replicas'
    reported queue depth (EMA) and sizes ``replicas`` to hold the
    per-replica depth near ``target_queue_depth``. Hysteresis bands
    (scale up only above ``target * high_band``, down only below
    ``target * low_band``) plus ``cooldown_s`` between scale events keep
    it from flapping."""

    enabled: bool = False
    min_replicas: int = 1
    max_replicas: int = 4
    target_queue_depth: float = 4.0
    high_band: float = 1.25
    low_band: float = 0.5
    cooldown_s: float = 30.0


@dataclass
class DisaggregationPolicy:
    """Splitwise/DistServe-style phase disaggregation: the serve runs
    TWO labeled replica pools — prefill (compute-bound, bursty) and
    decode (memory-bound, steady) — instead of one. The gateway runs
    chunked prefill on a prefill replica, moves the warm KV across the
    pool seam (runtime/handoff.py), and admits the row directly into a
    decode replica's loop; a prompt burst then queues on the prefill
    pool instead of stalling in-flight generations. Present in the spec
    ⇒ pool counts REPLACE ``spec.replicas`` and each pool autoscales
    off its own signal (prefill queue depth vs decode slot occupancy);
    absent ⇒ single-pool serving, bit-for-bit today's behavior."""

    prefill_replicas: int = 1
    decode_replicas: int = 1


@dataclass
class KVTierPolicy:
    """The KV economy (runtime/kvtier): tiered prefix-cache residency —
    device page pool, host-RAM LRU behind it, and peer pulls over the
    KV transport, with a gateway cache directory steering affinity
    routing at actual cache contents. Present in the spec ⇒ each
    replica runs a ``host_bytes``-bounded host tier (device evictions
    demote instead of drop), the gateway polls per-replica digest
    reports and overrides the consistent-hash guess on a fresh
    directory hit, and a miss routed next to a warm peer pulls the
    prefix instead of re-prefilling; absent ⇒ bit-for-bit today's
    behavior (no demotions, no directory traffic, no peer pulls)."""

    #: host-tier capacity per replica, in bytes of serialized prefix
    #: buffers (0 disables the host tier but keeps the directory)
    host_bytes: int = 64 << 20
    #: pull warm prefixes from directory-advertised peers on a local miss
    peer_fetch: bool = True
    #: directory staleness bound — reports older than this are ignored
    #: (a wrong entry costs only a fallback prefill, so this trades
    #: report traffic against routing accuracy, not correctness)
    directory_ttl_s: float = 5.0


@dataclass
class TenantQuota:
    """One tenant's admission budget at the gateway (gateway/admission.py).
    ``qps``/``burst`` parameterize a reservation-style token bucket
    (client/ratelimit.py); ``max_concurrency`` caps in-flight requests
    (0 = unlimited); ``priority`` orders tenants under overload — when the
    target replica set saturates, LOWER priorities shed first."""

    qps: float = 100.0
    # bucket capacity in requests; 0 defaults to max(1, ceil(qps))
    burst: int = 0
    max_concurrency: int = 0
    priority: int = 0


@dataclass
class TenantPolicy:
    """Multi-tenant admission at the serving front door. Tenants are
    identified by the request's ``X-Tenant`` header (map keys are data and
    pass through the wire verbatim, like labels); a tenant absent from
    ``tenants`` gets its OWN bucket sized by ``default_quota``. Disabled
    (the default) the gateway admits everything and only the replicas'
    bounded queues shed. Quota edits deliberately do NOT change the
    pod-template hash — tightening a tenant must never roll the serving
    pods."""

    enabled: bool = False
    tenants: Dict[str, TenantQuota] = field(default_factory=dict)
    default_quota: TenantQuota = field(default_factory=TenantQuota)


@dataclass
class TPUServeSpec:
    """What to serve and how. ``task`` names a registered served-model
    family (runtime/server.py: ``echo`` / ``mlp`` / ``gpt``);
    ``checkpoint`` is the model-weights ref the server loads before
    reporting Ready (``seed:<n>`` for hermetic deterministic params, or a
    checkpoint directory/URI). Changing ``checkpoint`` (or the template /
    batching) changes the pod-template hash and triggers a rolling
    update."""

    task: str = ""
    checkpoint: str = ""
    replicas: int = 1
    # image/env parity with training replicas; entrypoint defaults to the
    # in-process model server (runtime/server.py:serve)
    template: ContainerSpec = field(default_factory=ContainerSpec)
    batching: BatchingPolicy = field(default_factory=BatchingPolicy)
    rolling_update: RollingUpdatePolicy = field(default_factory=RollingUpdatePolicy)
    autoscale: AutoscalePolicy = field(default_factory=AutoscalePolicy)
    # gateway admission only — excluded from the pod-template hash
    tenancy: TenantPolicy = field(default_factory=TenantPolicy)
    tpu: TPUSpec = field(default_factory=TPUSpec)
    # phase-split pools (None = single-pool serving, today's behavior);
    # changing pool COUNTS scales in place, but adding/removing the
    # block itself rolls the template (the pods' phase env changes)
    disaggregation: Optional[DisaggregationPolicy] = None
    # KV economy (None = single-tier prefix cache, today's behavior);
    # knob changes roll the template — host capacity renders into the
    # pods' env, so the hash must see it
    kv_tier: Optional[KVTierPolicy] = None


@dataclass
class TPUServeStatus:
    conditions: List[ServeCondition] = field(default_factory=list)
    # live (non-terminal, non-deleting) serving pods observed
    replicas: int = 0
    # live pods that loaded the checkpoint and passed the health probe
    ready_replicas: int = 0
    # live pods rendered from the CURRENT pod-template hash
    updated_replicas: int = 0
    # the template hash fully rolled out (== desired once a rollout ends)
    observed_version: str = ""
    # smoothed load signals the autoscaler acts on (mirrored from the
    # replicas' per-pod reports)
    queue_depth: float = 0.0
    qps: float = 0.0
    last_scale_time: Optional[float] = field(default=None, metadata=RFC3339)
    # gateway route for this serve (path under the gateway's base URL)
    endpoint: str = ""


@dataclass
class TPUServe:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: TPUServeSpec = field(default_factory=TPUServeSpec)
    status: TPUServeStatus = field(default_factory=TPUServeStatus)
    api_version: str = API_VERSION
    kind: str = "TPUServe"

    def deepcopy(self) -> "TPUServe":
        return copy.deepcopy(self)


# ---------------------------------------------------------------------------
# Top-level objects
# ---------------------------------------------------------------------------


@dataclass
class TPUJob:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: TPUJobSpec = field(default_factory=TPUJobSpec)
    status: TPUJobStatus = field(default_factory=TPUJobStatus)
    api_version: str = API_VERSION
    kind: str = "TPUJob"

    def deepcopy(self) -> "TPUJob":
        return copy.deepcopy(self)


@dataclass
class PodSpec:
    containers: List[ContainerSpec] = field(default_factory=list)
    # Topology placement request: which slice / which host within the slice
    # this task must land on (filled by the trainer, consumed by the
    # scheduler; SURVEY.md §7 hard part 1).
    node_selector: Dict[str, str] = field(default_factory=dict)
    restart_policy: RestartPolicy = RestartPolicy.NEVER
    scheduler_name: str = "gang"


@dataclass
class PodStatus:
    phase: PodPhase = PodPhase.PENDING
    exit_code: Optional[int] = None
    message: str = ""
    host: str = ""
    restarts: int = 0
    # Bounded container-log tail captured by the kubelet (the hermetic
    # analogue of `kubectl logs`: real k8s proxies the kubelet for logs;
    # here the tail rides pod status so any client — including the remote
    # apiserver path — reads it with a plain GET, no kubelet proxy).
    log_tail: List[str] = field(default_factory=list)
    # Latest training-progress values reported by the entrypoint
    # (runtime/progress.py): step, steps_per_sec, examples_per_sec,
    # step_seconds. Published by the kubelet's flush loop; the operator
    # mirrors them into per-job /metrics series.
    training: Dict[str, float] = field(default_factory=dict)


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)
    api_version: str = "v1"
    kind: str = "Pod"

    def deepcopy(self) -> "Pod":
        return copy.deepcopy(self)


@dataclass
class ServicePort:
    name: str = ""
    port: int = 0


@dataclass
class ServiceSpec:
    selector: Dict[str, str] = field(default_factory=dict)
    ports: List[ServicePort] = field(default_factory=list)


@dataclass
class Service:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)
    api_version: str = "v1"
    kind: str = "Service"

    def deepcopy(self) -> "Service":
        return copy.deepcopy(self)


@dataclass
class LeaseSpec:
    holder: str = ""
    lease_duration_s: float = 15.0
    acquire_time: Optional[float] = field(default=None, metadata=RFC3339)
    renew_time: Optional[float] = field(default=None, metadata=RFC3339)
    lease_transitions: int = 0


@dataclass
class Lease:
    """Leader-election lease (SURVEY.md C17: 'uses the leaderelection
    package for high availability', k8s-operator.md:59,237)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LeaseSpec = field(default_factory=LeaseSpec)
    api_version: str = "coordination/v1"
    kind: str = "Lease"


@dataclass
class Event:
    """A control-plane event as an API OBJECT (k8s core/v1 Event parity):
    the operator's EventRecorder mirrors its in-memory log into these so
    any client — including `describe`/`get --kind events` across the
    HTTP apiserver — can read a job's history without reaching into the
    operator process. Aggregated k8s-style: one object per (involved
    object, reason), bumping ``count``/``last_timestamp`` on repeats."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_kind: str = ""
    involved_key: str = ""  # namespace/name of the involved object
    reason: str = ""
    message: str = ""
    count: int = 1
    first_timestamp: Optional[float] = field(default=None, metadata=RFC3339)
    last_timestamp: Optional[float] = field(default=None, metadata=RFC3339)
    api_version: str = "core/v1"
    kind: str = "Event"


# All registerable top-level kinds, for the scheme (serde.py).
TOP_LEVEL_KINDS = {
    "TPUJob": TPUJob,
    "TPUServe": TPUServe,
    "Pod": Pod,
    "Service": Service,
    "Lease": Lease,
    "Event": Event,
}
