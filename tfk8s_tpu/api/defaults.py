"""Defaulting for TPUJob specs — the ``defaults.go`` equivalent (SURVEY.md
C6). Idempotent: ``set_defaults(set_defaults(job)) == set_defaults(job)``.

Defaults chosen to mirror the reference's semantics where they exist:
``restart_policy`` defaults to OnFailure — the in-place-restart behavior the
doc singles out (k8s-operator.md:47-49) — and PS replicas default to Always
(a parameter server is a long-running service, never 'done'). TPU-specific
defaults (mesh = pure data-parallel over all chips) are new surface.
"""

from __future__ import annotations

import math

from tfk8s_tpu.api.types import (
    CleanPodPolicy,
    MeshSpec,
    ReplicaType,
    RestartPolicy,
    TPUJob,
    TPUServe,
)
from tfk8s_tpu.utils import topology as topo

DEFAULT_ACCELERATOR = "cpu-1"
DEFAULT_MAX_RESTARTS = 3
DEFAULT_BACKOFF_LIMIT = 3
# Seconds a downsized elastic gang holds steady before scaling back up.
DEFAULT_RESIZE_DEBOUNCE_S = 5.0

# The in-process model server (runtime/server.py): what a TPUServe pod
# runs unless the template pins another entrypoint.
DEFAULT_SERVE_ENTRYPOINT = "tfk8s_tpu.runtime.server:serve"


def set_defaults(job: TPUJob) -> TPUJob:
    """Fill unset spec fields in place and return the job."""
    spec = job.spec

    for rtype, rspec in spec.replica_specs.items():
        if rspec.replicas is None:
            rspec.replicas = 1
        if rspec.restart_policy is None:
            rspec.restart_policy = (
                RestartPolicy.ALWAYS if rtype == ReplicaType.PS else RestartPolicy.ON_FAILURE
            )
        if rspec.max_restarts is None:
            rspec.max_restarts = DEFAULT_MAX_RESTARTS

    if not spec.tpu.accelerator:
        spec.tpu.accelerator = DEFAULT_ACCELERATOR
    # num_slices < 1 is left as-is: validation reports it (clamping here
    # would make the numSlices validation error unreachable).

    rp = spec.run_policy
    if rp.clean_pod_policy is None:
        rp.clean_pod_policy = CleanPodPolicy.RUNNING
    if rp.backoff_limit is None:
        rp.backoff_limit = DEFAULT_BACKOFF_LIMIT

    el = rp.elastic
    if el is not None:
        if el.resize_debounce_s is None:
            el.resize_debounce_s = DEFAULT_RESIZE_DEBOUNCE_S
        worker = spec.replica_specs.get(ReplicaType.WORKER)
        if el.max_replicas is None:
            el.max_replicas = (worker.replicas if worker else None) or 1
        if el.min_replicas is None:
            # the smallest world a resize may shrink to: one host on the
            # hermetic backend, one whole slice on real TPU (a slice
            # admits and fails as a unit — validation enforces alignment)
            try:
                info = topo.parse_accelerator(
                    spec.tpu.accelerator, spec.tpu.topology
                )
                el.min_replicas = 1 if info.generation == "cpu" else info.hosts
            except topo.TopologyError:
                el.min_replicas = 1

    # Default mesh: one pure data-parallel axis over every chip in the job.
    if spec.mesh is None:
        try:
            info = topo.parse_accelerator(spec.tpu.accelerator, spec.tpu.topology)
            spec.mesh = MeshSpec(axes={"data": info.chips * max(spec.tpu.num_slices, 1)})
        except topo.TopologyError:
            pass  # malformed accelerator -> leave unset; validation reports it

    return job


def set_serve_defaults(serve: TPUServe) -> TPUServe:
    """Fill unset TPUServe spec fields in place and return it. Idempotent,
    like :func:`set_defaults`."""
    spec = serve.spec
    if not spec.template.entrypoint and not spec.template.image:
        spec.template.entrypoint = DEFAULT_SERVE_ENTRYPOINT
    if not spec.tpu.accelerator:
        spec.tpu.accelerator = DEFAULT_ACCELERATOR
    auto = spec.autoscale
    if auto.enabled:
        # the autoscaler owns replicas between its bounds; a spec count
        # outside them is clamped rather than rejected (HPA semantics)
        spec.replicas = min(max(spec.replicas, auto.min_replicas), auto.max_replicas)
        if spec.disaggregation is not None:
            # each phase pool is autoscaled independently against the
            # same bounds (per-pool signals, trainer/serve_controller)
            d = spec.disaggregation
            d.prefill_replicas = min(
                max(d.prefill_replicas, auto.min_replicas), auto.max_replicas
            )
            d.decode_replicas = min(
                max(d.decode_replicas, auto.min_replicas), auto.max_replicas
            )
    ten = spec.tenancy
    if ten.enabled:
        # burst=0 means "one second's worth of tokens, at least 1" — the
        # smallest bucket that still admits a full-rate steady stream
        for quota in [ten.default_quota, *ten.tenants.values()]:
            if quota.burst == 0 and quota.qps > 0:
                quota.burst = max(1, math.ceil(quota.qps))
    return serve
