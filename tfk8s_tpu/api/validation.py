"""TPUJob spec validation — the ``validation/validation.go`` equivalent
(SURVEY.md C7). Returns the full list of problems (field path + message)
rather than failing fast, so a user fixes a spec in one round trip.
"""

from __future__ import annotations

import re
from typing import List

from tfk8s_tpu.api.types import ReplicaType, TPUJob, TPUServe
from tfk8s_tpu.utils import topology as topo

# DNS-1123 label: what k8s accepts for object names.
_NAME_RE = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
MAX_NAME_LEN = 63


class ValidationError(ValueError):
    def __init__(self, errors: List[str]):
        self.errors = errors
        super().__init__("; ".join(errors))


def validate(job: TPUJob) -> List[str]:
    """Validate a (defaulted) TPUJob. Returns a list of error strings —
    empty means valid."""
    errs: List[str] = []
    meta, spec = job.metadata, job.spec

    if not meta.name:
        errs.append("metadata.name: required")
    elif len(meta.name) > MAX_NAME_LEN or not _NAME_RE.match(meta.name):
        errs.append(
            f"metadata.name: {meta.name!r} must be a DNS-1123 label "
            f"(<= {MAX_NAME_LEN} chars, [a-z0-9-])"
        )
    if not meta.namespace:
        errs.append("metadata.namespace: required")

    if not spec.replica_specs:
        errs.append("spec.replicaSpecs: at least one replica set is required")
    for rtype, rspec in spec.replica_specs.items():
        path = f"spec.replicaSpecs[{rtype.value}]"
        if rspec.replicas is not None and rspec.replicas < 0:
            errs.append(f"{path}.replicas: must be >= 0, got {rspec.replicas}")
        if rtype == ReplicaType.CHIEF and (rspec.replicas or 0) > 1:
            errs.append(f"{path}.replicas: at most one Chief, got {rspec.replicas}")
        if not rspec.template.entrypoint and not rspec.template.image:
            errs.append(f"{path}.template: entrypoint or image is required")
        if rspec.max_restarts is not None and rspec.max_restarts < 0:
            errs.append(f"{path}.maxRestarts: must be >= 0")
    compute = {
        rt: rs
        for rt, rs in spec.replica_specs.items()
        if rt in (ReplicaType.CHIEF, ReplicaType.WORKER)
    }
    n_compute = sum(rs.replicas or 0 for rs in compute.values())
    if spec.replica_specs and n_compute == 0:
        errs.append(
            "spec.replicaSpecs: at least one Chief or Worker replica is required"
        )

    info = None
    if spec.tpu.accelerator:
        try:
            info = topo.parse_accelerator(spec.tpu.accelerator, spec.tpu.topology)
        except topo.TopologyError as e:
            errs.append(f"spec.tpu: {e}")
    if spec.tpu.num_slices < 1:
        errs.append(f"spec.tpu.numSlices: must be >= 1, got {spec.tpu.num_slices}")
    if spec.tpu.provider not in ("", "gke"):
        errs.append(
            f"spec.tpu.provider: must be '' (hermetic) or 'gke', "
            f"got {spec.tpu.provider!r}"
        )
    elif spec.tpu.provider == "gke" and info is not None:
        if info.generation not in topo.GKE_ACCELERATOR:
            errs.append(
                f"spec.tpu.provider: 'gke' has no nodepool shape for "
                f"generation {info.generation!r} "
                f"(supported: {sorted(set(topo.GKE_ACCELERATOR))})"
            )

    # Gang consistency: the compute replicas are the slice's hosts. One JAX
    # process per host (SURVEY.md §3.3 'pod scheduled onto TPU VM; JAX
    # process attaches to its chips'), so compute replica count must equal
    # hosts-per-slice x num_slices.
    if info is not None and info.generation != "cpu":
        want = info.hosts * max(spec.tpu.num_slices, 1)
        if n_compute and n_compute != want:
            errs.append(
                f"spec.replicaSpecs: {n_compute} compute replicas (Chief+Worker) "
                f"but {spec.tpu.accelerator} x{spec.tpu.num_slices} has {want} "
                f"host(s); one process per host"
            )

    if spec.mesh is not None:
        for name, size in spec.mesh.axes.items():
            if size < 1:
                errs.append(f"spec.mesh.axes[{name}]: must be >= 1, got {size}")
        if info is not None:
            want = info.chips * max(spec.tpu.num_slices, 1)
            if spec.mesh.size() != want:
                errs.append(
                    f"spec.mesh: axes product {spec.mesh.size()} != total chips {want} "
                    f"({spec.tpu.accelerator} x {spec.tpu.num_slices})"
                )

    el = job.spec.run_policy.elastic
    if el is not None:
        path = "spec.runPolicy.elastic"
        worker = spec.replica_specs.get(ReplicaType.WORKER)
        n_workers = (worker.replicas if worker else None) or 0
        if not job.spec.run_policy.scheduling.gang:
            errs.append(f"{path}: requires gang scheduling (resize re-forms the gang)")
        if worker is None:
            errs.append(f"{path}: requires a Worker replica set to resize")
        mn, mx = el.min_replicas or 0, el.max_replicas or 0
        if mn < 1:
            errs.append(f"{path}.minReplicas: must be >= 1, got {mn}")
        if mx < mn:
            errs.append(
                f"{path}.maxReplicas: must be >= minReplicas ({mn}), got {mx}"
            )
        if worker is not None and not mn <= n_workers <= mx:
            errs.append(
                f"{path}: Worker replicas {n_workers} outside "
                f"[minReplicas={mn}, maxReplicas={mx}]"
            )
        if el.resize_debounce_s is not None and el.resize_debounce_s < 0:
            errs.append(
                f"{path}.resizeDebounceS: must be >= 0, got {el.resize_debounce_s}"
            )
        if info is not None and info.generation != "cpu":
            # TPU resize granularity is a WHOLE slice: a slice admits and
            # fails as a unit, so the gang cannot shrink below (or sit
            # between) slice boundaries. One process per host means the
            # boundary is hosts-per-slice.
            if ReplicaType.CHIEF in spec.replica_specs:
                errs.append(
                    f"{path}: elastic TPU gangs must be Worker-only "
                    "(a Chief pins process 0 outside the resizable set)"
                )
            for fname, v in (("minReplicas", mn), ("maxReplicas", mx)):
                if v and info.hosts and v % info.hosts:
                    errs.append(
                        f"{path}.{fname}: {v} is not a multiple of "
                        f"hosts-per-slice ({info.hosts}) — a gang cannot "
                        "shrink below a slice boundary"
                    )
            if spec.mesh is not None and set(spec.mesh.axes) != {"data"}:
                errs.append(
                    f"{path}: only a pure data-parallel mesh can be "
                    f"re-derived on resize (got axes "
                    f"{sorted(spec.mesh.axes)})"
                )

    rp = job.spec.run_policy
    if rp.backoff_limit is not None and rp.backoff_limit < 0:
        errs.append("spec.runPolicy.backoffLimit: must be >= 0")
    if rp.active_deadline_seconds is not None and rp.active_deadline_seconds <= 0:
        errs.append("spec.runPolicy.activeDeadlineSeconds: must be > 0")
    if rp.ttl_seconds_after_finished is not None and rp.ttl_seconds_after_finished < 0:
        errs.append("spec.runPolicy.ttlSecondsAfterFinished: must be >= 0")

    return errs


def validate_or_raise(job: TPUJob) -> None:
    errs = validate(job)
    if errs:
        raise ValidationError(errs)


def validate_serve(serve: TPUServe) -> List[str]:
    """Validate a (defaulted) TPUServe; empty list means valid."""
    errs: List[str] = []
    meta, spec = serve.metadata, serve.spec

    if not meta.name:
        errs.append("metadata.name: required")
    elif len(meta.name) > MAX_NAME_LEN or not _NAME_RE.match(meta.name):
        errs.append(
            f"metadata.name: {meta.name!r} must be a DNS-1123 label "
            f"(<= {MAX_NAME_LEN} chars, [a-z0-9-])"
        )
    if not meta.namespace:
        errs.append("metadata.namespace: required")

    if not spec.task:
        errs.append("spec.task: required (a registered served-model family)")
    if spec.replicas < 0:
        errs.append(f"spec.replicas: must be >= 0, got {spec.replicas}")
    if not spec.template.entrypoint and not spec.template.image:
        errs.append("spec.template: entrypoint or image is required")

    b = spec.batching
    if b.max_batch_size < 1:
        errs.append(f"spec.batching.maxBatchSize: must be >= 1, got {b.max_batch_size}")
    if b.batch_timeout_ms < 0:
        errs.append(
            f"spec.batching.batchTimeoutMs: must be >= 0, got {b.batch_timeout_ms}"
        )
    if b.queue_limit < b.max_batch_size:
        errs.append(
            f"spec.batching.queueLimit: must be >= maxBatchSize "
            f"({b.max_batch_size}), got {b.queue_limit}"
        )
    if b.page_size < 1:
        errs.append(f"spec.batching.pageSize: must be >= 1, got {b.page_size}")
    if b.max_pages < 2:
        # page 0 is the reserved trash page — a pool of 1 could never
        # admit anything (the model-side max_len fit is checked at
        # replica startup, where max_len is known)
        errs.append(
            f"spec.batching.maxPages: must be >= 2 (trash page + 1 usable), "
            f"got {b.max_pages}"
        )
    sch = b.scheduler
    if sch.policy not in ("fifo", "priority"):
        errs.append(
            f"spec.batching.scheduler.policy: must be 'fifo' or 'priority', "
            f"got {sch.policy!r}"
        )
    if sch.aging_s <= 0:
        errs.append(
            f"spec.batching.scheduler.agingS: must be > 0, got {sch.aging_s}"
        )
    if sch.spec_tokens < 1:
        errs.append(
            f"spec.batching.scheduler.specTokens: must be >= 1, "
            f"got {sch.spec_tokens}"
        )
    if sch.spec_draft not in ("tiny", "mid", "base"):
        errs.append(
            f"spec.batching.scheduler.specDraft: must be one of "
            f"('tiny', 'mid', 'base'), got {sch.spec_draft!r}"
        )

    ru = spec.rolling_update
    if ru.max_surge < 0 or ru.max_unavailable < 0:
        errs.append("spec.rollingUpdate: maxSurge and maxUnavailable must be >= 0")
    if ru.max_surge == 0 and ru.max_unavailable == 0:
        errs.append(
            "spec.rollingUpdate: maxSurge and maxUnavailable cannot both be 0 "
            "(no replica could ever be replaced)"
        )

    a = spec.autoscale
    if a.enabled:
        if a.min_replicas < 1:
            # scale-to-zero would be a one-way door: the scale-up signal
            # is the replicas' own queue-depth reports, and zero replicas
            # report nothing — an external activator (not built) is the
            # prerequisite for min 0
            errs.append(f"spec.autoscale.minReplicas: must be >= 1, got {a.min_replicas}")
        if a.max_replicas < max(a.min_replicas, 1):
            errs.append(
                f"spec.autoscale.maxReplicas: must be >= max(minReplicas, 1), "
                f"got {a.max_replicas}"
            )
        if a.target_queue_depth <= 0:
            errs.append(
                f"spec.autoscale.targetQueueDepth: must be > 0, got "
                f"{a.target_queue_depth}"
            )
        if not (a.low_band < 1.0 <= a.high_band):
            errs.append(
                "spec.autoscale: need lowBand < 1.0 <= highBand "
                f"(got low={a.low_band}, high={a.high_band}) — overlapping "
                "bands would oscillate"
            )
        if a.cooldown_s < 0:
            errs.append(f"spec.autoscale.cooldownS: must be >= 0, got {a.cooldown_s}")

    d = spec.disaggregation
    if d is not None:
        if spec.task not in ("gpt", "t5"):
            # phase splitting only means something for generative tasks:
            # the handoff plane moves KV pages, which classifiers and
            # echo replicas don't have
            errs.append(
                f"spec.disaggregation: only generative tasks (gpt, t5) "
                f"can split prefill/decode pools, got task {spec.task!r}"
            )
        if d.prefill_replicas < 1:
            errs.append(
                f"spec.disaggregation.prefillReplicas: must be >= 1, "
                f"got {d.prefill_replicas}"
            )
        if d.decode_replicas < 1:
            errs.append(
                f"spec.disaggregation.decodeReplicas: must be >= 1, "
                f"got {d.decode_replicas}"
            )

    kv = spec.kv_tier
    if kv is not None:
        if spec.task not in ("gpt", "t5"):
            # the KV economy moves prompt-prefix K/V pages between
            # tiers; only generative tasks have any
            errs.append(
                f"spec.kvTier: only generative tasks (gpt, t5) have a "
                f"KV cache to tier, got task {spec.task!r}"
            )
        if kv.host_bytes < 0:
            errs.append(
                f"spec.kvTier.hostBytes: must be >= 0, got {kv.host_bytes}"
            )
        if kv.directory_ttl_s <= 0:
            errs.append(
                f"spec.kvTier.directoryTtlS: must be > 0, got "
                f"{kv.directory_ttl_s}"
            )

    ten = spec.tenancy
    if ten.enabled:
        for path, quota in [
            ("spec.tenancy.defaultQuota", ten.default_quota),
            *((f"spec.tenancy.tenants[{name!r}]", q)
              for name, q in sorted(ten.tenants.items())),
        ]:
            if quota.qps < 0:
                errs.append(f"{path}.qps: must be >= 0, got {quota.qps}")
            if quota.burst < 0:
                errs.append(f"{path}.burst: must be >= 0, got {quota.burst}")
            if quota.max_concurrency < 0:
                errs.append(
                    f"{path}.maxConcurrency: must be >= 0, got "
                    f"{quota.max_concurrency}"
                )
        for name in sorted(ten.tenants):
            if not name:
                errs.append("spec.tenancy.tenants: tenant name cannot be empty")

    if spec.tpu.accelerator:
        try:
            topo.parse_accelerator(spec.tpu.accelerator, spec.tpu.topology)
        except topo.TopologyError as e:
            errs.append(f"spec.tpu: {e}")

    return errs


def validate_serve_or_raise(serve: TPUServe) -> None:
    errs = validate_serve(serve)
    if errs:
        raise ValidationError(errs)
