"""L1 API layer: TPUJob schema, defaults, validation, helpers, serde.

Equivalent of the reference's ``pkg/apis/tensorflow/`` tree (SURVEY.md
C4-C9; images/tf3.PNG at k8s-operator.md:229).
"""

from tfk8s_tpu.api.types import (  # noqa: F401
    AutoscalePolicy,
    BatchingPolicy,
    CleanPodPolicy,
    Condition,
    ContainerSpec,
    DisaggregationPolicy,
    ElasticPolicy,
    JobConditionType,
    KVTierPolicy,
    MeshSpec,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodPhase,
    PodSpec,
    PodStatus,
    ReplicaSpec,
    ReplicaStatus,
    ReplicaType,
    RestartPolicy,
    RollingUpdatePolicy,
    RunPolicy,
    SchedulingPolicy,
    ServeCondition,
    ServeConditionType,
    Service,
    ServicePort,
    ServiceSpec,
    TPUJob,
    TPUJobSpec,
    TPUJobStatus,
    TPUServe,
    TPUServeSpec,
    TPUServeStatus,
    TPUSpec,
)
from tfk8s_tpu.api.defaults import set_defaults, set_serve_defaults  # noqa: F401
from tfk8s_tpu.api.validation import (  # noqa: F401
    ValidationError,
    validate,
    validate_or_raise,
    validate_serve,
    validate_serve_or_raise,
)
from tfk8s_tpu.api import helpers  # noqa: F401
from tfk8s_tpu.api import serde  # noqa: F401
