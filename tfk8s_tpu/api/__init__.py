"""L1 API layer: TPUJob schema, defaults, validation, helpers, serde.

Equivalent of the reference's ``pkg/apis/tensorflow/`` tree (SURVEY.md
C4-C9; images/tf3.PNG at k8s-operator.md:229).
"""

from tfk8s_tpu.api.types import (  # noqa: F401
    CleanPodPolicy,
    Condition,
    ContainerSpec,
    JobConditionType,
    MeshSpec,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodPhase,
    PodSpec,
    PodStatus,
    ReplicaSpec,
    ReplicaStatus,
    ReplicaType,
    RestartPolicy,
    RunPolicy,
    SchedulingPolicy,
    Service,
    ServicePort,
    ServiceSpec,
    TPUJob,
    TPUJobSpec,
    TPUJobStatus,
    TPUSpec,
)
from tfk8s_tpu.api.defaults import set_defaults  # noqa: F401
from tfk8s_tpu.api.validation import ValidationError, validate, validate_or_raise  # noqa: F401
from tfk8s_tpu.api import helpers  # noqa: F401
from tfk8s_tpu.api import serde  # noqa: F401
