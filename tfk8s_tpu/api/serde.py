"""Scheme registration + (de)serialization — the equivalent of the
reference's ``register.go`` / ``zz_generated.deepcopy.go`` codec layer
(SURVEY.md C5/C9; ``SchemeGroupVersion`` + ``DirectCodecFactory`` in
images/tf6.PNG).

Where the reference registers Go types with a runtime.Scheme and lets
codegen produce deepcopy/codecs, here a single generic encoder/decoder walks
the dataclass field types: enums serialize by value, enum-keyed dicts (the
``replica_specs`` map) serialize by the enum's value, and kinds round-trip
through the ``SCHEME`` registry keyed by the object's ``kind`` field.
"""

from __future__ import annotations

import dataclasses
import enum
import typing
from typing import Any, Dict, Type, get_args, get_origin, get_type_hints

from tfk8s_tpu.api import types as t

# kind -> class; the runtime.Scheme equivalent.
SCHEME: Dict[str, type] = dict(t.TOP_LEVEL_KINDS)


def register(kind: str, cls: type) -> None:
    SCHEME[kind] = cls


def to_dict(obj: Any) -> Any:
    """Encode a dataclass (or nested structure) to JSON-safe primitives."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            out[f.name] = to_dict(getattr(obj, f.name))
        return out
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, dict):
        return {_key_to_str(k): to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    return obj


def _key_to_str(k: Any) -> str:
    return k.value if isinstance(k, enum.Enum) else str(k)


def from_dict(cls: Type, data: Any) -> Any:
    """Decode primitives into an instance of dataclass ``cls``, following the
    declared field types (including Optional/List/Dict and enum keys)."""
    return _decode(cls, data)


def decode_object(data: Dict[str, Any]) -> Any:
    """Decode a top-level object by its ``kind`` via the scheme."""
    kind = data.get("kind", "")
    if kind not in SCHEME:
        raise KeyError(f"kind {kind!r} is not registered in the scheme")
    return _decode(SCHEME[kind], data)


def _decode(tp: Any, data: Any) -> Any:
    if data is None:
        return None
    origin = get_origin(tp)
    if origin is typing.Union:  # Optional[X]
        args = [a for a in get_args(tp) if a is not type(None)]
        return _decode(args[0], data) if args else data
    if origin in (dict,):
        kt, vt = get_args(tp) or (str, Any)
        return {_decode(kt, k): _decode(vt, v) for k, v in data.items()}
    if origin is tuple:
        args = get_args(tp)
        if len(args) == 2 and args[1] is Ellipsis:  # Tuple[X, ...]
            return tuple(_decode(args[0], v) for v in data)
        if args:  # fixed-arity Tuple[X, Y, ...]
            return tuple(_decode(a, v) for a, v in zip(args, data))
        return tuple(data)
    if origin is list:
        (vt,) = get_args(tp) or (Any,)
        return [_decode(vt, v) for v in data]
    if isinstance(tp, type) and issubclass(tp, enum.Enum):
        return tp(data)
    if dataclasses.is_dataclass(tp):
        hints = get_type_hints(tp)
        kwargs = {}
        for f in dataclasses.fields(tp):
            if f.name in data:
                kwargs[f.name] = _decode(hints[f.name], data[f.name])
        return tp(**kwargs)
    return data


def roundtrip(obj: Any) -> Any:
    """Encode then decode via the scheme — used by tests to assert lossless
    round-trip serialization (the ``DirectCodecFactory`` parity check)."""
    return decode_object(to_dict(obj))
