"""Scheme registration + (de)serialization — the equivalent of the
reference's ``register.go`` / ``zz_generated.deepcopy.go`` codec layer
(SURVEY.md C5/C9; ``SchemeGroupVersion`` + ``DirectCodecFactory`` in
images/tf6.PNG).

Where the reference registers Go types with a runtime.Scheme and lets
codegen produce deepcopy/codecs, here a single generic encoder/decoder walks
the dataclass field types: enums serialize by value, enum-keyed dicts (the
``replica_specs`` map) serialize by the enum's value, and kinds round-trip
through the ``SCHEME`` registry keyed by the object's ``kind`` field.

Two encodings share one decoder:

- ``to_dict`` — internal snake_case dump (tests, logs, legacy bodies);
- ``to_wire`` — the KUBERNETES wire form served over HTTP
  (client/apiserver.py): camelCase keys from dataclass field names (map
  keys like labels/annotations/replica-type names pass through verbatim),
  an ``apiVersion``/``kind`` envelope on every top-level object,
  ``metadata.resourceVersion`` as an opaque string, and timestamps as
  RFC3339 — the JSON a client-go-shaped tool expects at
  ``/apis/<group>/<version>/...`` (k8s-operator.md:33-34, images/tf5-tf6
  ``APIPath="/apis"``).

``from_dict``/``decode_object`` accept BOTH casings (each dataclass field
is looked up by camelCase first, then snake_case), so k8s-conventional
manifests and the legacy snake form both decode.
"""

from __future__ import annotations

import dataclasses
import datetime
import enum
import typing
from typing import Any, Dict, Type, get_args, get_origin, get_type_hints

from tfk8s_tpu import API_VERSION
from tfk8s_tpu.api import types as t

# kind -> class; the runtime.Scheme equivalent.
SCHEME: Dict[str, type] = dict(t.TOP_LEVEL_KINDS)

def api_version_of(kind: str) -> str:
    """The group/version a kind serves under, from its class default
    (TPUJob -> the CRD group; Pod/Service -> core)."""
    for f in dataclasses.fields(SCHEME[kind]):
        if f.name == "api_version" and isinstance(f.default, str):
            return f.default
    return API_VERSION


def register(kind: str, cls: type) -> None:
    SCHEME[kind] = cls


def to_dict(obj: Any) -> Any:
    """Encode a dataclass (or nested structure) to JSON-safe primitives."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            out[f.name] = to_dict(getattr(obj, f.name))
        return out
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, dict):
        return {_key_to_str(k): to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    return obj


def _key_to_str(k: Any) -> str:
    return k.value if isinstance(k, enum.Enum) else str(k)


def _camel(name: str) -> str:
    first, *rest = name.split("_")
    return first + "".join(p[:1].upper() + p[1:] for p in rest)


def _rfc3339(epoch: float) -> str:
    # MicroTime precision: k8s RFC3339 allows fractional seconds, and the
    # store's TTL/ordering logic compares these as floats — keep the
    # round-trip lossless to the microsecond.
    return (
        datetime.datetime.fromtimestamp(epoch, datetime.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%S.%fZ")
    )


def to_wire(obj: Any) -> Any:
    """Encode to the Kubernetes wire form (module docstring). Dataclass
    field names camelCase (the ``api_version`` field becomes the
    ``apiVersion`` envelope key); plain-dict keys (labels, replica-type
    names) are data and pass through verbatim; timestamps RFC3339;
    ``metadata.resourceVersion`` an opaque string."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out: Dict[str, Any] = {}
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            if f.name == "resource_version":
                out["resourceVersion"] = str(v)
            elif (
                # explicit registry (types.RFC3339 field metadata), not a
                # name heuristic — a numeric duration named *_time passes
                # through untouched (r3 advisor finding)
                f.metadata.get("wire") == "rfc3339"
                and isinstance(v, (int, float))
                and not isinstance(v, bool)
            ):
                out[_camel(f.name)] = _rfc3339(float(v))
            else:
                out[_camel(f.name)] = to_wire(v)
        return out
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, dict):
        return {_key_to_str(k): to_wire(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_wire(v) for v in obj]
    return obj


def from_dict(cls: Type, data: Any) -> Any:
    """Decode primitives into an instance of dataclass ``cls``, following the
    declared field types (including Optional/List/Dict and enum keys)."""
    return _decode(cls, data)


def decode_object(data: Dict[str, Any]) -> Any:
    """Decode a top-level object by its ``kind`` via the scheme."""
    kind = data.get("kind", "")
    if kind not in SCHEME:
        raise KeyError(f"kind {kind!r} is not registered in the scheme")
    return _decode(SCHEME[kind], data)


def _decode(tp: Any, data: Any) -> Any:
    if data is None:
        return None
    origin = get_origin(tp)
    if origin is typing.Union:  # Optional[X]
        args = [a for a in get_args(tp) if a is not type(None)]
        return _decode(args[0], data) if args else data
    if origin in (dict,):
        kt, vt = get_args(tp) or (str, Any)
        return {_decode(kt, k): _decode(vt, v) for k, v in data.items()}
    if origin is tuple:
        args = get_args(tp)
        if len(args) == 2 and args[1] is Ellipsis:  # Tuple[X, ...]
            return tuple(_decode(args[0], v) for v in data)
        if args:  # fixed-arity Tuple[X, Y, ...]
            return tuple(_decode(a, v) for a, v in zip(args, data))
        return tuple(data)
    if origin is list:
        (vt,) = get_args(tp) or (Any,)
        return [_decode(vt, v) for v in data]
    if isinstance(tp, type) and issubclass(tp, enum.Enum):
        return tp(data)
    if dataclasses.is_dataclass(tp):
        hints = get_type_hints(tp)
        kwargs = {}
        for f in dataclasses.fields(tp):
            # wire form (camelCase) first, legacy snake_case second
            camel = _camel(f.name)
            if camel in data:
                kwargs[f.name] = _decode(hints[f.name], data[camel])
            elif f.name in data:
                kwargs[f.name] = _decode(hints[f.name], data[f.name])
        return tp(**kwargs)
    # wire-form scalar coercions: resourceVersion is an opaque string of
    # an int; timestamps are RFC3339 strings of epoch floats
    if tp is int and isinstance(data, str):
        return int(data)
    if tp is float and isinstance(data, str):
        try:
            return float(data)
        except ValueError:
            # RFC3339 in any legal spelling ("Z" or numeric offset)
            dt = datetime.datetime.fromisoformat(data.replace("Z", "+00:00"))
            if dt.tzinfo is None:
                dt = dt.replace(tzinfo=datetime.timezone.utc)
            return dt.timestamp()
    return data


def roundtrip(obj: Any) -> Any:
    """Encode then decode via the scheme — used by tests to assert lossless
    round-trip serialization (the ``DirectCodecFactory`` parity check)."""
    return decode_object(to_dict(obj))
