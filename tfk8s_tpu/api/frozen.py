"""Copy-on-write object freezing — the immutability substrate of the
control-plane hot path.

client-go's shared informers hand every consumer the SAME cached object
and make it work by convention: cached objects are treated as immutable,
so a read costs a pointer, not a deep copy. The Python port needs the
convention ENFORCED — a silent mutation of a shared object would
corrupt the store/cache for every other consumer with no trace. This
module provides that enforcement:

- :func:`freeze` walks an object IN PLACE: every plain ``dict``/``list``
  becomes a :class:`FrozenDict`/:class:`FrozenList` (same types for
  ``isinstance``/iteration/json, mutators raise), and every dataclass
  gets a guarded ``__setattr__`` plus a per-instance frozen flag.
  Idempotent; returns its argument.
- Mutating anything frozen raises :class:`FrozenObjectError` (a typed
  ``TypeError``) — the read-isolation contract the store tests pin.
- ``copy.deepcopy`` of a frozen object yields an ordinary MUTABLE deep
  copy (:func:`thaw` is the explicit spelling): the one escape hatch for
  clients that legitimately mutate (the kubelet's read-modify-write
  status loop goes through it at the typed-client boundary).

The store (client/store.py) freezes each object once at the write
barrier; get/list/watch/informer-cache reads then share the frozen
instance by reference. That single property is what turned the
control-plane bench's ~20 deepcopy sites (one per get/list/create/patch
plus one PER WATCHER per event) into one copy per write.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Any, Set

_FROZEN_ATTR = "__tfk8s_frozen__"


class FrozenObjectError(TypeError):
    """Attempted mutation of a frozen (shared, copy-on-write) object.

    Raised by attribute writes on frozen dataclasses and by every
    mutating method of :class:`FrozenDict`/:class:`FrozenList`. Callers
    that need a mutable view take :func:`thaw` (or ``copy.deepcopy``)
    first — mutating in place would corrupt the store and every other
    consumer sharing the instance."""


def _blocked(name: str):
    def method(self, *args, **kwargs):
        raise FrozenObjectError(
            f"{type(self).__name__}.{name}(): object is frozen (shared "
            "copy-on-write state); thaw() it for a mutable copy"
        )

    method.__name__ = name
    return method


class FrozenDict(dict):
    """A dict whose mutators raise. Still a real ``dict`` for
    ``isinstance``, iteration, equality, and ``json.dumps``. Deep copies
    are plain mutable dicts."""

    __slots__ = ()

    __setitem__ = _blocked("__setitem__")
    __delitem__ = _blocked("__delitem__")
    clear = _blocked("clear")
    pop = _blocked("pop")
    popitem = _blocked("popitem")
    setdefault = _blocked("setdefault")
    update = _blocked("update")
    __ior__ = _blocked("__ior__")

    def __deepcopy__(self, memo):
        return {copy.deepcopy(k, memo): copy.deepcopy(v, memo) for k, v in self.items()}

    def __reduce__(self):
        return (FrozenDict, (dict(self),))


class FrozenList(list):
    """A list whose mutators raise; deep copies are plain lists."""

    __slots__ = ()

    __setitem__ = _blocked("__setitem__")
    __delitem__ = _blocked("__delitem__")
    __iadd__ = _blocked("__iadd__")
    __imul__ = _blocked("__imul__")
    append = _blocked("append")
    extend = _blocked("extend")
    insert = _blocked("insert")
    pop = _blocked("pop")
    remove = _blocked("remove")
    clear = _blocked("clear")
    sort = _blocked("sort")
    reverse = _blocked("reverse")

    def __deepcopy__(self, memo):
        return [copy.deepcopy(v, memo) for v in self]

    def __reduce__(self):
        return (FrozenList, (list(self),))


def _guarded_setattr(self, name: str, value: Any) -> None:
    if getattr(self, _FROZEN_ATTR, False):
        raise FrozenObjectError(
            f"cannot set {type(self).__name__}.{name}: object is frozen "
            "(shared copy-on-write state); thaw() it for a mutable copy"
        )
    object.__setattr__(self, name, value)


def _guarded_delattr(self, name: str) -> None:
    if getattr(self, _FROZEN_ATTR, False):
        raise FrozenObjectError(
            f"cannot delete {type(self).__name__}.{name}: object is frozen"
        )
    object.__delattr__(self, name)


def _deepcopy_thawed(self, memo):
    """deepcopy of a (possibly frozen) guarded dataclass: an ordinary
    MUTABLE deep copy — the frozen flag does not propagate, and frozen
    containers deep-copy to plain dict/list via their own hooks."""
    cls = type(self)
    new = object.__new__(cls)
    memo[id(self)] = new
    for k, v in self.__dict__.items():
        if k == _FROZEN_ATTR:
            continue
        object.__setattr__(new, k, copy.deepcopy(v, memo))
    return new


_guarded_classes: Set[type] = set()


def _ensure_guarded(cls: type) -> None:
    """Install the frozen-aware ``__setattr__``/``__deepcopy__`` on a
    dataclass type, once. Unfrozen instances pay one flag check per
    attribute write; frozen instances raise."""
    if cls in _guarded_classes:
        return
    if "__setattr__" not in cls.__dict__:
        cls.__setattr__ = _guarded_setattr  # type: ignore[assignment]
    if "__delattr__" not in cls.__dict__:
        cls.__delattr__ = _guarded_delattr  # type: ignore[assignment]
    if "__deepcopy__" not in cls.__dict__:
        cls.__deepcopy__ = _deepcopy_thawed  # type: ignore[attr-defined]
    _guarded_classes.add(cls)


def is_frozen(obj: Any) -> bool:
    if isinstance(obj, (FrozenDict, FrozenList)):
        return True
    return bool(getattr(obj, _FROZEN_ATTR, False))


def freeze(obj: Any) -> Any:
    """Freeze ``obj`` in place (dataclasses) / by wrapping (containers).
    Scalars, enums, and already-frozen values pass through. Returns the
    frozen value — for containers that is a NEW FrozenDict/FrozenList
    wrapping frozen children; for dataclasses it is ``obj`` itself with
    its fields rewritten to frozen values."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        if getattr(obj, _FROZEN_ATTR, False):
            return obj
        _ensure_guarded(type(obj))
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            fv = freeze(v)
            if fv is not v:
                object.__setattr__(obj, f.name, fv)
        object.__setattr__(obj, _FROZEN_ATTR, True)
        return obj
    if isinstance(obj, FrozenDict) or isinstance(obj, FrozenList):
        return obj
    if isinstance(obj, dict):
        return FrozenDict({k: freeze(v) for k, v in obj.items()})
    if isinstance(obj, list):
        return FrozenList([freeze(v) for v in obj])
    if isinstance(obj, tuple):
        return tuple(freeze(v) for v in obj)
    return obj


def thaw(obj: Any) -> Any:
    """A mutable deep copy of a frozen object; non-frozen objects are
    returned AS IS (no copy) — the typed-client ``get()`` boundary uses
    this so local (frozen) reads copy exactly once and remote (already
    private) reads copy never."""
    if is_frozen(obj):
        return copy.deepcopy(obj)
    return obj
