"""Shared spec/status helpers — the ``helper/helpers.go`` equivalent
(SURVEY.md C8): replica naming, condition bookkeeping, terminal-state
queries, and the cluster-endpoints map (the TF_CONFIG ``cluster`` section's
TPU-native descendant, consumed by the trainer to wire JAX coordination —
SURVEY.md §2 'Distributed communication backend').
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from tfk8s_tpu.api.types import (
    Condition,
    JobConditionType,
    ReplicaType,
    ServeCondition,
    ServeConditionType,
    TPUJob,
    TPUJobStatus,
    TPUServeStatus,
)

# Stable ordering for process-id assignment: chief is always process 0.
REPLICA_ORDER = [
    ReplicaType.CHIEF,
    ReplicaType.WORKER,
    ReplicaType.PS,
    ReplicaType.EVALUATOR,
]

DEFAULT_PORT = 8471  # coordination/service port per task


def replica_name(job_name: str, rtype: ReplicaType, index: int) -> str:
    """Deterministic per-task name, e.g. ``mnist-worker-0`` — the analogue of
    the reference's label/name scheme in pkg/trainer/labels.go (C19)."""
    return f"{job_name}-{rtype.value.lower()}-{index}"


def sorted_replica_types(job: TPUJob) -> List[ReplicaType]:
    return [rt for rt in REPLICA_ORDER if rt in job.spec.replica_specs]


def total_replicas(job: TPUJob) -> int:
    return sum(rs.replicas or 0 for rs in job.spec.replica_specs.values())


def expected_pod_names(job: TPUJob) -> List[str]:
    names = []
    for rt in sorted_replica_types(job):
        for i in range(job.spec.replica_specs[rt].replicas or 0):
            names.append(replica_name(job.metadata.name, rt, i))
    return names


def process_index(job: TPUJob, rtype: ReplicaType, index: int) -> int:
    """Global process id of a task: replica sets in REPLICA_ORDER, tasks in
    index order. Chief (or Worker 0 when no chief) is process 0 — the JAX
    coordinator."""
    pid = 0
    for rt in sorted_replica_types(job):
        if rt == rtype:
            return pid + index
        pid += job.spec.replica_specs[rt].replicas or 0
    raise KeyError(f"replica type {rtype} not in job {job.metadata.name}")


def cluster_endpoints(job: TPUJob, port: int = DEFAULT_PORT) -> Dict[str, List[str]]:
    """Role -> list of ``host:port`` endpoints, one per task; hostnames are
    the per-task service names the trainer creates. This is the structural
    equivalent of TF_CONFIG's ``cluster`` map (k8s-operator.md:6) that the
    reference's users previously built by hand (k8s-operator.md:4)."""
    out: Dict[str, List[str]] = {}
    ns = job.metadata.namespace
    for rt in sorted_replica_types(job):
        n = job.spec.replica_specs[rt].replicas or 0
        out[rt.value.lower()] = [
            f"{replica_name(job.metadata.name, rt, i)}.{ns}:{port}" for i in range(n)
        ]
    return out


def coordinator_address(job: TPUJob, port: int = DEFAULT_PORT) -> str:
    """Address of process 0 — ``jax.distributed.initialize``'s coordinator."""
    for rt in sorted_replica_types(job):
        if (job.spec.replica_specs[rt].replicas or 0) > 0:
            return f"{replica_name(job.metadata.name, rt, 0)}.{job.metadata.namespace}:{port}"
    raise ValueError(f"job {job.metadata.name} has no replicas")


# ---------------------------------------------------------------------------
# Conditions (level-triggered status bookkeeping)
# ---------------------------------------------------------------------------


def get_condition(status: TPUJobStatus, ctype: JobConditionType) -> Optional[Condition]:
    for c in status.conditions:
        if c.type == ctype:
            return c
    return None


def has_condition(status: TPUJobStatus, ctype: JobConditionType) -> bool:
    c = get_condition(status, ctype)
    return c is not None and c.status


def set_condition(
    status: TPUJobStatus, ctype: JobConditionType, reason: str = "", message: str = ""
) -> bool:
    """Set condition ``ctype`` true (clearing mutually-exclusive run-state
    conditions). Returns True iff the status changed — callers use this to
    skip no-op status writes (the update-filter pattern,
    k8s-operator.md:142-150)."""
    exclusive = {
        JobConditionType.RUNNING,
        JobConditionType.RESTARTING,
        JobConditionType.SUSPENDED,
        JobConditionType.SUCCEEDED,
        JobConditionType.FAILED,
    }
    changed = False
    existing = get_condition(status, ctype)
    if (
        existing is not None
        and existing.status
        and existing.reason == reason
        and existing.message == message
    ):
        return False
    if ctype in exclusive:
        for c in status.conditions:
            if c.type in exclusive and c.type != ctype and c.status:
                c.status = False
                c.last_transition_time = time.time()
                changed = True
    if existing is None:
        status.conditions.append(
            Condition(type=ctype, status=True, reason=reason, message=message)
        )
        changed = True
    else:
        existing.status = True
        existing.reason = reason
        existing.message = message
        existing.last_transition_time = time.time()
        changed = True
    return changed


# -- TPUServe conditions (same level-triggered bookkeeping, but serve
#    conditions are NOT mutually exclusive: Available and Progressing can
#    both be true mid-rollout, deployment-style) ---------------------------


def get_serve_condition(
    status: TPUServeStatus, ctype: ServeConditionType
) -> Optional[ServeCondition]:
    for c in status.conditions:
        if c.type == ctype:
            return c
    return None


def serve_condition_is(status: TPUServeStatus, ctype: ServeConditionType) -> bool:
    c = get_serve_condition(status, ctype)
    return c is not None and c.status


def set_serve_condition(
    status: TPUServeStatus,
    ctype: ServeConditionType,
    value: bool = True,
    reason: str = "",
    message: str = "",
) -> bool:
    """Set condition ``ctype`` to ``value``; returns True iff anything
    changed (callers skip no-op status writes on False)."""
    existing = get_serve_condition(status, ctype)
    if (
        existing is not None
        and existing.status == value
        and existing.reason == reason
        and existing.message == message
    ):
        return False
    if existing is None:
        status.conditions.append(
            ServeCondition(type=ctype, status=value, reason=reason, message=message)
        )
    else:
        existing.status = value
        existing.reason = reason
        existing.message = message
        existing.last_transition_time = time.time()
    return True


def is_succeeded(status: TPUJobStatus) -> bool:
    return has_condition(status, JobConditionType.SUCCEEDED)


def is_failed(status: TPUJobStatus) -> bool:
    return has_condition(status, JobConditionType.FAILED)


def is_finished(status: TPUJobStatus) -> bool:
    return is_succeeded(status) or is_failed(status)
