"""Text corpus → tokenized record shards (the text half of the loop:
train/load a BPE tokenizer, pack the token stream into fixed-length
rows, write TFRecord-framed shards the trainer's files input mode
reads).

CLI::

    python -m tfk8s_tpu.data.corpus \
        --input 'corpus/*.txt' --out-dir shards --seq-len 128 \
        --vocab-size 2048 --num-shards 4 --tokenizer-dir tok

Trains a byte-level BPE tokenizer on the corpus when ``--tokenizer-dir``
is empty or absent, else loads it (HF vocab.json/merges.txt layout —
a real GPT-2 vocabulary works unchanged). Documents separated by EOS;
the stream is chunked into ``seq_len``-token rows (the trainer's causal
LM shift happens inside the task), remainder dropped; rows round-robin
across ``--num-shards`` files (>= one file per training host restores
per-host file IO — data/recordio.shard_files)."""

from __future__ import annotations

import argparse
import glob
import os
from typing import Iterator, List

import numpy as np

from tfk8s_tpu.data.recordio import RecordWriter
from tfk8s_tpu.data.example import encode
from tfk8s_tpu.data.tokenizer import BPETokenizer, train_bpe

PAD, EOS = "<|pad|>", "<|endoftext|>"


def _read_texts(patterns: List[str]) -> List[str]:
    paths = sorted({p for pat in patterns for p in glob.glob(pat)})
    if not paths:
        raise FileNotFoundError(f"no files match {patterns}")
    texts = []
    for p in paths:
        # with-block per file: handles close deterministically instead
        # of leaking until GC (ADVICE r5)
        with open(p, encoding="utf-8") as f:
            texts.append(f.read())
    return texts


def get_tokenizer(
    texts: List[str], tokenizer_dir: str, vocab_size: int
) -> BPETokenizer:
    if tokenizer_dir and os.path.exists(
        os.path.join(tokenizer_dir, "vocab.json")
    ):
        return BPETokenizer.load(tokenizer_dir)
    tok = train_bpe(texts, vocab_size=vocab_size, specials=[PAD, EOS])
    if tokenizer_dir:
        tok.save(tokenizer_dir)
    return tok


def pack_rows(
    tok: BPETokenizer, texts: List[str], seq_len: int
) -> Iterator[np.ndarray]:
    """One flat token stream, documents separated by EOS, chunked into
    ``seq_len`` rows (remainder dropped — same convention as GPT-2
    pretraining packing)."""
    eos = tok.vocab.get(EOS)
    stream: List[int] = []
    for text in texts:
        stream.extend(tok.encode(text))
        if eos is not None:
            stream.append(eos)
    for lo in range(0, len(stream) - seq_len + 1, seq_len):
        yield np.asarray(stream[lo : lo + seq_len], np.int32)


def write_shards(
    rows: Iterator[np.ndarray], out_dir: str, num_shards: int
) -> List[str]:
    """Writes to temp names, renaming into ``part-*.rio`` only on
    success — a failed/invalid packing must not leave partial shards
    behind that a later run's ``part-*.rio`` glob would feed a host."""
    os.makedirs(out_dir, exist_ok=True)
    paths = [
        os.path.join(out_dir, f"part-{i:04d}.rio") for i in range(num_shards)
    ]
    tmps = [p + ".tmp" for p in paths]
    writers = [RecordWriter(p) for p in tmps]
    n = 0
    ok = False
    try:
        for row in rows:
            writers[n % num_shards].write(encode({"input": row}))
            n += 1
        if n < num_shards:
            raise ValueError(
                f"corpus packed into only {n} rows for {num_shards} shards — "
                "use fewer shards, a shorter seq_len, or more text"
            )
        ok = True
    finally:
        for w in writers:
            w.close()
        if ok:
            for t, p in zip(tmps, paths):
                os.replace(t, p)
        else:
            for t in tmps:
                try:
                    os.unlink(t)
                except OSError:
                    pass
    return paths


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--input", nargs="+", required=True,
                    help="text file paths/globs")
    ap.add_argument("--out-dir", required=True)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--vocab-size", type=int, default=2048)
    ap.add_argument("--num-shards", type=int, default=4)
    ap.add_argument("--tokenizer-dir", default="",
                    help="load (if populated) or save the tokenizer here")
    args = ap.parse_args(argv)

    texts = _read_texts(args.input)
    tok = get_tokenizer(texts, args.tokenizer_dir, args.vocab_size)
    paths = write_shards(
        pack_rows(tok, texts, args.seq_len), args.out_dir, args.num_shards
    )
    total = sum(os.path.getsize(p) for p in paths)
    print(
        f"tokenized {len(texts)} file(s) with vocab {tok.vocab_size} -> "
        f"{len(paths)} shard(s), {total / 1e6:.2f} MB at {args.out_dir}"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
