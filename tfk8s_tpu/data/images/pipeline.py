"""ImageDataset: record shards of compressed images -> augmented
float32 batches, decoded on a bounded worker pool.

A ``RecordDataset`` whose decode stage (``_decode_records``) fans each
batch's images out over a ``ThreadPoolExecutor`` — PIL's libjpeg/zlib
loops release the GIL, so W workers buy close to W-way decode
parallelism without processes. Augmentation is seeded per
``(dataset seed, epoch, record index)``: position-independent, so a
resumed run (``iterator(start_batch=...)`` fast-forward) replays the
IDENTICAL pixel stream the uninterrupted run would have produced, and
any worker-pool scheduling order yields the same batch.

Observability (the PR-1 obs layer): pass the process's ``Metrics``
registry to :func:`set_metrics` (the operator server wires its own in
``cmd/server.py``) and the pipeline exports

- ``tfk8s_images_decoded_total{mode=train|eval}`` — images decoded
- ``tfk8s_image_decode_errors_total`` — records that failed to decode
- ``tfk8s_image_decode_seconds`` — per-batch decode+augment wall time
- ``tfk8s_image_decode_queue_depth`` — staged batches in the prefetch
  queue (the input-starvation early-warning: a queue pinned at 0 means
  the decode pool, not the trainer, is the bottleneck)
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

import numpy as np

from tfk8s_tpu.data.dataset import RecordDataset
from tfk8s_tpu.data.images import schema
from tfk8s_tpu.data.images.decode import ImageDecodeError, open_image
from tfk8s_tpu.data.images.transforms import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    eval_transform,
    train_transform,
)

# decouples the augmentation rng stream from the shuffle stream (which
# folds [seed, epoch] in RecordDataset._epoch_order)
_AUG_SALT = 0x1A6E5EED

_metrics = None
_metrics_lock = threading.Lock()


def set_metrics(registry) -> None:
    """Install the process's obs ``Metrics`` registry (utils/logging) as
    the sink for the pipeline's decode metrics. None disables."""
    global _metrics
    with _metrics_lock:
        _metrics = registry
        if registry is not None:
            registry.describe(
                "tfk8s_images_decoded_total",
                "Images decoded by the input pipeline",
            )
            registry.describe(
                "tfk8s_image_decode_seconds",
                "Wall time of one batch decode+augment",
            )
            registry.describe(
                "tfk8s_image_decode_queue_depth",
                "Decoded batches staged in the prefetch queue",
            )
            registry.describe(
                "tfk8s_image_decode_errors_total",
                "Records that failed image decode (corrupt or wrong schema)",
            )


def get_metrics():
    return _metrics


def default_workers() -> int:
    """Decode pool width: every core up to 8 — past that, JPEG decode on
    one host is usually no longer the binding constraint and the threads
    just contend with the trainer's own host work."""
    return max(min(os.cpu_count() or 1, 8), 1)


class ImageDataset(RecordDataset):
    """Shard-assigned, shuffled, batched IMAGE input: each record is an
    image Example (``schema.py``); batches come out as
    ``{"image": float32 [B, size, size, 3], "label": int32 [B]}`` —
    exactly the host-batch schema ``models/resnet.py`` and
    ``models/vit.py`` train on.

    ``train=True`` applies the seeded training augmentation
    (random-resized-crop + flip + normalize); ``train=False`` the
    deterministic eval view (resize + center-crop). All RecordDataset
    semantics (per-host file/record sharding, seeded epoch shuffle,
    resume fast-forward) carry over unchanged.
    """

    def __init__(
        self,
        files: Sequence[str],
        batch_size: int,
        image_size: int,
        train: bool = True,
        workers: Optional[int] = None,
        host_index: int = 0,
        num_hosts: int = 1,
        seed: int = 0,
        shuffle: Optional[bool] = None,
        drop_remainder: bool = True,
        verify_crc: bool = True,
        shard_by: str = "auto",
        do_normalize: bool = True,
        min_scale: float = 0.08,
    ):
        super().__init__(
            files,
            batch_size,
            host_index=host_index,
            num_hosts=num_hosts,
            seed=seed,
            # eval wants the stable unshuffled order unless told otherwise
            shuffle=train if shuffle is None else shuffle,
            decode=schema.decode_image_example,  # per-record, pre-pixels
            drop_remainder=drop_remainder,
            verify_crc=verify_crc,
            shard_by=shard_by,
        )
        if image_size < 1:
            raise ValueError(f"image_size must be >= 1, got {image_size}")
        self.image_size = image_size
        self.train = train
        self.do_normalize = do_normalize
        self.min_scale = min_scale  # RRC area floor (transforms.py)
        self.workers = workers or default_workers()
        self.images_decoded = 0  # cumulative (windowed-rate source)
        self.decoded_bytes = 0  # decoded float32 bytes produced
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pool_lock = threading.Lock()

    # -- decode stage -------------------------------------------------------

    def _ensure_pool(self) -> ThreadPoolExecutor:
        with self._pool_lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self.workers,
                    thread_name_prefix="img-decode",
                )
            return self._pool

    def _decode_one(
        self, record: bytes, record_id: int, epoch: int
    ) -> Dict[str, np.ndarray]:
        try:
            ex = self.decode(record)
            img = open_image(ex.encoded)
            if self.train:
                rng = np.random.default_rng(
                    np.random.SeedSequence(
                        [self.seed, _AUG_SALT, epoch, record_id]
                    )
                )
                pixels = train_transform(
                    img, rng, self.image_size, self.do_normalize,
                    min_scale=self.min_scale,
                )
            else:
                pixels = eval_transform(
                    img, self.image_size, self.do_normalize
                )
        except (ImageDecodeError, schema.ImageSchemaError) as exc:
            m = get_metrics()
            if m is not None:
                m.inc("tfk8s_image_decode_errors_total")
            raise ImageDecodeError(
                f"record {record_id} of shard set {self.files}: {exc}"
            ) from exc
        return {
            "image": pixels,
            "label": np.int32(ex.label),
        }

    def _decode_records(
        self, records: List[bytes], record_ids: List[int], epoch: int
    ) -> List[Dict[str, np.ndarray]]:
        t0 = time.perf_counter()
        if len(records) == 1 or self.workers == 1:
            out = [
                self._decode_one(r, rid, epoch)
                for r, rid in zip(records, record_ids)
            ]
        else:
            pool = self._ensure_pool()
            out = list(
                pool.map(
                    self._decode_one,
                    records,
                    record_ids,
                    [epoch] * len(records),
                )
            )
        self.images_decoded += len(out)
        self.decoded_bytes += sum(ex["image"].nbytes for ex in out)
        m = get_metrics()
        if m is not None:
            mode = "train" if self.train else "eval"
            m.inc(
                "tfk8s_images_decoded_total", float(len(out)),
                labels={"mode": mode},
            )
            m.observe(
                "tfk8s_image_decode_seconds", time.perf_counter() - t0,
                labels={"mode": mode},
            )
        return out

    # -- lifecycle ----------------------------------------------------------

    def iterator(self, prefetch: int = 2, start_batch: int = 0):
        it = super().iterator(prefetch, start_batch)
        if prefetch > 0:
            return _QueueDepthIterator(it)
        return it

    def close(self) -> None:
        """Shut the decode pool down (joins idle workers — no leaked
        threads after the run; the e2e tests assert this)."""
        with self._pool_lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=True)

    def __del__(self):  # best-effort: a dropped dataset must not pin threads
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter may be tearing down
            pass


class _QueueDepthIterator:
    """Prefetch-iterator wrapper exporting the staged-batch count as the
    ``tfk8s_image_decode_queue_depth`` gauge on every dequeue."""

    def __init__(self, inner):
        self._inner = inner

    def __iter__(self):
        return self

    def __next__(self):
        item = next(self._inner)
        m = get_metrics()
        if m is not None:
            q = getattr(self._inner, "_q", None)
            if q is not None:
                m.set_gauge(
                    "tfk8s_image_decode_queue_depth", float(q.qsize())
                )
        return item

    def close(self) -> None:
        self._inner.close()
