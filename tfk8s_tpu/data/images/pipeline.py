"""ImageDataset: record shards of compressed images -> augmented
float32 batches, decoded on a bounded worker pool.

A ``RecordDataset`` whose decode stage (``_decode_records``) fans each
batch's images out over a ``ThreadPoolExecutor`` and assembles the
result IN PLACE: the batch ``[B, size, size, 3]`` float32 (and the
``[B]`` int32 labels) is preallocated once per batch and every worker
writes its slot directly — no per-image array, no downstream
``np.stack`` copy of the full batch on the hot path.

Two decode backends per image (``decode.image_backend``,
``TFK8S_IMAGE_BACKEND=native|pil|auto``):

- native — the libjpeg core: the seeded crop box is drawn FIRST from
  the record's header-stamped geometry (crop parameters are
  backend-independent, so the per-(seed, epoch, record) rng contract
  and resume determinism survive a backend switch), then one fused C
  call decodes at the largest DCT-domain downscale that still covers
  the crop (``transforms.choose_scale``), crops, resizes, flips and
  normalizes straight into the batch slot;
- pil — the reference path (PIL's libjpeg/zlib loops release the GIL,
  so W workers buy close to W-way decode parallelism without
  processes). PNG records — and any bytes the native core rejects —
  take this path even under the native backend, with the SAME
  already-drawn crop.

Observability (the PR-1 obs layer): pass the process's ``Metrics``
registry to :func:`set_metrics` (the operator server wires its own in
``cmd/server.py``) and the pipeline exports

- ``tfk8s_images_decoded_total{mode, backend}`` — images decoded
- ``tfk8s_image_decode_errors_total{mode}`` — records that failed
- ``tfk8s_image_decode_seconds{mode, backend}`` — per-batch
  decode+augment wall time
- ``tfk8s_image_decode_queue_depth{mode}`` — staged batches in the
  prefetch queue, labeled per mode so concurrent train and evaluator
  datasets stop clobbering each other's gauge (the input-starvation
  early-warning: a queue pinned at 0 means the decode pool, not the
  trainer, is the bottleneck)
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

import numpy as np

from tfk8s_tpu.data.dataset import RecordDataset
from tfk8s_tpu.data.images import _native_decode, schema
from tfk8s_tpu.data.images.decode import (
    ImageDecodeError,
    image_size,
    open_image,
    resolve_backend,
)
from tfk8s_tpu.data.images.transforms import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    apply_crop,
    choose_scale,
    eval_crop_box,
    eval_transform,
    normalize_affine,
    train_crop_params,
)

# decouples the augmentation rng stream from the shuffle stream (which
# folds [seed, epoch] in RecordDataset._epoch_order)
_AUG_SALT = 0x1A6E5EED

_metrics = None
_metrics_lock = threading.Lock()


def set_metrics(registry) -> None:
    """Install the process's obs ``Metrics`` registry (utils/logging) as
    the sink for the pipeline's decode metrics. None disables."""
    global _metrics
    with _metrics_lock:
        _metrics = registry
        if registry is not None:
            registry.describe(
                "tfk8s_images_decoded_total",
                "Images decoded by the input pipeline",
            )
            registry.describe(
                "tfk8s_image_decode_seconds",
                "Wall time of one batch decode+augment",
            )
            registry.describe(
                "tfk8s_image_decode_queue_depth",
                "Decoded batches staged in the prefetch queue",
            )
            registry.describe(
                "tfk8s_image_decode_errors_total",
                "Records that failed image decode (corrupt or wrong schema)",
            )


def get_metrics():
    return _metrics


def default_workers() -> int:
    """Decode pool width: every core up to 8 — past that, JPEG decode on
    one host is usually no longer the binding constraint and the threads
    just contend with the trainer's own host work."""
    return max(min(os.cpu_count() or 1, 8), 1)


class ImageDataset(RecordDataset):
    """Shard-assigned, shuffled, batched IMAGE input: each record is an
    image Example (``schema.py``); batches come out as
    ``{"image": float32 [B, size, size, 3], "label": int32 [B]}`` —
    exactly the host-batch schema ``models/resnet.py`` and
    ``models/vit.py`` train on.

    ``train=True`` applies the seeded training augmentation
    (random-resized-crop + flip + normalize); ``train=False`` the
    deterministic eval view (resize + center-crop). All RecordDataset
    semantics (per-host file/record sharding, seeded epoch shuffle,
    resume fast-forward) carry over unchanged.

    ``backend`` picks the decoder (None/"auto" = env-resolved;
    ``TFK8S_IMAGE_BACKEND``); ``scaled_decode`` gates the native
    DCT-domain scaled decode (None = env ``TFK8S_IMAGE_SCALED_DECODE``,
    default on — off forces full-scale IDCT, the bench's on/off rows).
    """

    def __init__(
        self,
        files: Sequence[str],
        batch_size: int,
        image_size: int,
        train: bool = True,
        workers: Optional[int] = None,
        host_index: int = 0,
        num_hosts: int = 1,
        seed: int = 0,
        shuffle: Optional[bool] = None,
        drop_remainder: bool = True,
        verify_crc: bool = True,
        shard_by: str = "auto",
        do_normalize: bool = True,
        min_scale: float = 0.08,
        backend: Optional[str] = None,
        scaled_decode: Optional[bool] = None,
    ):
        super().__init__(
            files,
            batch_size,
            host_index=host_index,
            num_hosts=num_hosts,
            seed=seed,
            # eval wants the stable unshuffled order unless told otherwise
            shuffle=train if shuffle is None else shuffle,
            decode=schema.decode_image_example,  # per-record, pre-pixels
            drop_remainder=drop_remainder,
            verify_crc=verify_crc,
            shard_by=shard_by,
        )
        if image_size < 1:
            raise ValueError(f"image_size must be >= 1, got {image_size}")
        self.image_size = image_size
        self.train = train
        self.do_normalize = do_normalize
        self.min_scale = min_scale  # RRC area floor (transforms.py)
        self.workers = workers or default_workers()
        self.backend = resolve_backend(backend)
        if scaled_decode is None:
            scaled_decode = os.environ.get(
                "TFK8S_IMAGE_SCALED_DECODE", "1"
            ) != "0"
        self.scaled_decode = bool(scaled_decode)
        self.images_decoded = 0  # cumulative (windowed-rate source)
        self.decoded_bytes = 0  # decoded float32 bytes produced
        self.native_decoded = 0  # slots served by the fused native call
        # the per-channel affine the fused native kernel applies — the
        # SAME cached constants the PIL path normalizes with
        # (transforms.normalize_affine), so the backends cannot drift;
        # identity when do_normalize=False -> raw 0..255 float pixels
        if do_normalize:
            self._chan_scale, self._chan_bias = normalize_affine(
                IMAGENET_MEAN, IMAGENET_STD
            )
        else:
            self._chan_scale = np.ones(3, np.float32)
            self._chan_bias = np.zeros(3, np.float32)
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pool_lock = threading.Lock()

    # -- decode stage -------------------------------------------------------

    def _ensure_pool(self) -> ThreadPoolExecutor:
        with self._pool_lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self.workers,
                    thread_name_prefix="img-decode",
                )
            return self._pool

    def _rng_for(self, record_id: int, epoch: int) -> np.random.Generator:
        return np.random.default_rng(
            np.random.SeedSequence([self.seed, _AUG_SALT, epoch, record_id])
        )

    def _decode_into(
        self,
        dst: np.ndarray,
        record: bytes,
        record_id: int,
        epoch: int,
    ) -> tuple:
        """Decode + augment one record INTO ``dst`` (a [size, size, 3]
        float32 batch slot); returns (label, native_served). The crop
        parameters are drawn before any pixel materializes, from the
        header-stamped geometry, so they are identical under either
        backend — the native call and the PIL fallback realize the SAME
        crop."""
        size = self.image_size
        try:
            ex = self.decode(record)
            fmt = ex.format or schema.sniff_format(ex.encoded)
            if self.backend == "native" and fmt == "jpeg":
                h, w, _c = image_size(
                    ex.encoded, stamped=(ex.height, ex.width, ex.channels)
                )
                if self.train:
                    top, left, ch, cw, flip = train_crop_params(
                        self._rng_for(record_id, epoch), h, w,
                        self.min_scale,
                    )
                else:
                    top, left, ch, cw = eval_crop_box(h, w, size)
                    flip = False
                s = choose_scale(ch, cw, size) if self.scaled_decode else 8
                if _native_decode.decode_rrc_into(
                    ex.encoded, (top, left, ch, cw), size, flip, s,
                    self._chan_scale, self._chan_bias, dst, (h, w),
                ):
                    return ex.label, True
                # the core refused this one image (corrupt-for-native,
                # stamp/geometry mismatch): SAME crop through PIL — the
                # rng stream is already consumed and must not re-draw
                img = open_image(ex.encoded)
                aw, ah = img.size
                if (h, w) != (ah, aw):
                    # the crop was drawn from a LYING stamp; whether it
                    # overflows the real frame (PIL would crash on the
                    # box) or lands inside a larger one (silently
                    # mis-positioned, backend-divergent crops), the draw
                    # is invalid — name the corruption (fail-loudly)
                    raise ImageDecodeError(
                        f"header-stamped geometry {h}x{w} disagrees with "
                        f"the decoded frame {ah}x{aw} — re-pack the shard"
                    )
                apply_crop(
                    img, (top, left, ch, cw), size, flip,
                    self.do_normalize, out=dst,
                )
                return ex.label, False
            img = open_image(ex.encoded)
            w, h = img.size
            if ex.height > 0 and ex.width > 0 and (
                (ex.height, ex.width) != (h, w)
            ):
                # the PIL backend must refuse a lying stamp exactly like
                # the native one — otherwise the same shard trains
                # silently under pil and raises under native, and the
                # backend-independent crop contract quietly breaks
                raise ImageDecodeError(
                    f"header-stamped geometry {ex.height}x{ex.width} "
                    f"disagrees with the decoded frame {h}x{w} — re-pack "
                    "the shard"
                )
            if self.train:
                # geometry from the decoded object (free here, and
                # header-equal, so the draw matches the native path)
                top, left, ch, cw, flip = train_crop_params(
                    self._rng_for(record_id, epoch), h, w, self.min_scale
                )
                apply_crop(
                    img, (top, left, ch, cw), size, flip,
                    self.do_normalize, out=dst,
                )
            else:
                eval_transform(img, size, self.do_normalize, out=dst)
        except (ImageDecodeError, schema.ImageSchemaError) as exc:
            m = get_metrics()
            if m is not None:
                m.inc(
                    "tfk8s_image_decode_errors_total",
                    labels={"mode": "train" if self.train else "eval"},
                )
            raise ImageDecodeError(
                f"record {record_id} of shard set {self.files}: {exc}"
            ) from exc
        return ex.label, False

    def _decode_records(
        self, records: List[bytes], record_ids: List[int], epoch: int
    ) -> Dict[str, np.ndarray]:
        """The decode stage, assembling IN PLACE: one preallocated
        [B, size, size, 3] float32 batch, every worker writing its slot
        directly (``RecordDataset._load`` passes an assembled dict
        through untouched — no np.stack copy)."""
        t0 = time.perf_counter()
        n = len(records)
        size = self.image_size
        images = np.empty((n, size, size, 3), np.float32)
        labels = np.empty((n,), np.int32)

        def one(i: int) -> int:
            label, native = self._decode_into(
                images[i], records[i], record_ids[i], epoch
            )
            labels[i] = label
            return 1 if native else 0

        if n == 1 or self.workers == 1:
            native_n = sum(one(i) for i in range(n))
        else:
            native_n = sum(self._ensure_pool().map(one, range(n)))
        self.images_decoded += n
        self.decoded_bytes += images.nbytes
        self.native_decoded += native_n
        m = get_metrics()
        if m is not None:
            mode = "train" if self.train else "eval"
            # decoded_total counts the backend that ACTUALLY served each
            # slot — a native dataset whose images fell back to PIL (PNG
            # shards, bytes the core refuses) must show up as pil, or
            # /metrics would hide exactly the bandwidth regression the
            # label exists to expose
            if native_n:
                m.inc(
                    "tfk8s_images_decoded_total", float(native_n),
                    labels={"mode": mode, "backend": "native"},
                )
            if n - native_n:
                m.inc(
                    "tfk8s_images_decoded_total", float(n - native_n),
                    labels={"mode": mode, "backend": "pil"},
                )
            # batch wall time is one observation; labeled by the
            # CONFIGURED backend (the batch may mix per-image paths)
            m.observe(
                "tfk8s_image_decode_seconds", time.perf_counter() - t0,
                labels={"mode": mode, "backend": self.backend},
            )
        return {"image": images, "label": labels}

    # -- lifecycle ----------------------------------------------------------

    def iterator(self, prefetch: int = 2, start_batch: int = 0):
        it = super().iterator(prefetch, start_batch)
        if prefetch > 0:
            return _QueueDepthIterator(it, "train" if self.train else "eval")
        return it

    def close(self) -> None:
        """Shut the decode pool down (joins idle workers — no leaked
        threads after the run; the e2e tests assert this)."""
        with self._pool_lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=True)

    def __del__(self):  # best-effort: a dropped dataset must not pin threads
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter may be tearing down
            pass


class _QueueDepthIterator:
    """Prefetch-iterator wrapper exporting the staged-batch count as the
    ``tfk8s_image_decode_queue_depth{mode}`` gauge on every dequeue —
    mode-labeled so a train pipeline and a concurrent evaluator each
    own their series instead of clobbering one shared gauge."""

    def __init__(self, inner, mode: str):
        self._inner = inner
        self._mode = mode

    def __iter__(self):
        return self

    def __next__(self):
        item = next(self._inner)
        m = get_metrics()
        if m is not None:
            q = getattr(self._inner, "_q", None)
            if q is not None:
                m.set_gauge(
                    "tfk8s_image_decode_queue_depth", float(q.qsize()),
                    labels={"mode": self._mode},
                )
        return item

    def close(self) -> None:
        self._inner.close()
