"""Compressed image bytes -> HWC uint8 RGB arrays (and back, for the
packer/bench/tests).

PIL-backed: the decode hot loop holds the GIL only for the Python glue —
libjpeg/zlib run with it released, which is what lets the
``pipeline.ImageDataset`` worker pool scale past one core. A native
libjpeg-turbo core via the ``native/recordio.cc`` g++ lazy-build pattern
is the designated fast path if PIL decode ever becomes the measured
input ceiling (see ROADMAP.md); this module is the seam it would slot
into — callers depend on ``decode_image``/``open_image`` only.

PIL is baked into the training image but gated here anyway: control
plane code paths (operator, apiserver) must import cleanly on hosts
without it.
"""

from __future__ import annotations

import io
from typing import Optional, Tuple

import numpy as np

try:  # gate, don't hard-require: the control plane never decodes
    from PIL import Image as _PILImage
except Exception:  # noqa: BLE001 — any import failure means "no PIL"
    _PILImage = None


class ImageDecodeError(ValueError):
    """Bytes that do not decode as an image (corrupt or wrong schema)."""


def _require_pil():
    if _PILImage is None:
        raise ImageDecodeError(
            "image decode needs Pillow, which is not importable here — "
            "install it in the training image (control-plane hosts don't "
            "need it)"
        )
    return _PILImage


def open_image(encoded: bytes):
    """Compressed bytes -> PIL RGB image (the transform stages crop on
    the PIL object BEFORE materializing pixels — cheaper than decoding
    to a full array first)."""
    Image = _require_pil()
    try:
        img = Image.open(io.BytesIO(encoded))
        img.load()
    except Exception as exc:  # noqa: BLE001 — PIL raises a zoo of types
        raise ImageDecodeError(f"undecodable image bytes: {exc}") from exc
    if img.mode != "RGB":
        img = img.convert("RGB")
    return img


def image_size(encoded: bytes) -> Tuple[int, int, int]:
    """(height, width, channels) from the container HEADER only — no
    full decode (the packer stamps geometry into every record)."""
    Image = _require_pil()
    try:
        with Image.open(io.BytesIO(encoded)) as img:
            w, h = img.size
            bands = len(img.getbands())
    except Exception as exc:  # noqa: BLE001
        raise ImageDecodeError(f"unreadable image header: {exc}") from exc
    return h, w, bands


def decode_image(encoded: bytes) -> np.ndarray:
    """Compressed bytes -> HWC uint8 RGB array."""
    return np.asarray(open_image(encoded), dtype=np.uint8)


def encode_jpeg(array: np.ndarray, quality: int = 90) -> bytes:
    """HWC uint8 RGB -> JPEG bytes (packer/bench/test helper)."""
    Image = _require_pil()
    buf = io.BytesIO()
    Image.fromarray(np.asarray(array, np.uint8), "RGB").save(
        buf, format="JPEG", quality=quality
    )
    return buf.getvalue()


def encode_png(array: np.ndarray) -> bytes:
    """HWC uint8 RGB -> PNG bytes (lossless — the golden-decode tests
    pin exact pixels through this path)."""
    Image = _require_pil()
    buf = io.BytesIO()
    Image.fromarray(np.asarray(array, np.uint8), "RGB").save(
        buf, format="PNG"
    )
    return buf.getvalue()


def have_decoder() -> bool:
    return _PILImage is not None
