"""Compressed image bytes -> HWC uint8 RGB arrays (and back, for the
packer/bench/tests), behind a backend dispatch.

Two decode backends, selected by ``TFK8S_IMAGE_BACKEND``:

- ``native`` — the libjpeg core (``data/native/imagecore.cc``, built
  lazily by ``_native_decode.py``): JPEG decode with DCT-domain scaling
  and the fused decode+crop+resize+normalize hot path the
  ``pipeline.ImageDataset`` workers use. JPEG only; PNG and anything
  the core rejects falls through to PIL per image.
- ``pil``    — the PIL path (libjpeg/zlib with the GIL released), the
  reference implementation every native capability is tested against.
- ``auto``   (default) — native when the core builds, else PIL with ONE
  loud line naming the measured cost (~2.4x per decode worker at
  224px). ``TFK8S_PURE_PY=1`` forces PIL quietly — the single switch
  that disables ALL native codepaths (recordio and image decode), and a
  deliberate choice the logs don't second-guess.

Callers depend on ``decode_image``/``open_image``/``image_size`` only;
the transform/pipeline stack reaches the fused native entrypoints
through ``_native_decode`` directly.

PIL is baked into the training image but gated here anyway: control
plane code paths (operator, apiserver) must import cleanly on hosts
without it.
"""

from __future__ import annotations

import io
import os
from typing import Optional, Tuple

import numpy as np

from tfk8s_tpu.data.images.schema import sniff_format

try:  # gate, don't hard-require: the control plane never decodes
    from PIL import Image as _PILImage
except Exception:  # noqa: BLE001 — any import failure means "no PIL"
    _PILImage = None


class ImageDecodeError(ValueError):
    """Bytes that do not decode as an image (corrupt or wrong schema)."""


def _require_pil():
    if _PILImage is None:
        raise ImageDecodeError(
            "image decode needs Pillow, which is not importable here — "
            "install it in the training image (control-plane hosts don't "
            "need it)"
        )
    return _PILImage


def resolve_backend(choice: Optional[str]) -> str:
    """The ONE place the backend-fallback policy lives — callers pass a
    request (an ``ImageDataset(backend=...)`` argument, or None/"auto"
    to read ``TFK8S_IMAGE_BACKEND``) and get the backend that will run:
    ``"native"`` or ``"pil"``. Policy: an explicit ``pil`` — or
    ``TFK8S_PURE_PY=1``, the single switch disabling ALL native
    codepaths — forces PIL quietly (deliberate choices aren't
    second-guessed); ``native``/``auto`` take the native core when it
    loads, else PIL — loudly once, because losing the native core is an
    input-bandwidth regression, not a detail."""
    if choice is None or choice == "auto":
        choice = os.environ.get(
            "TFK8S_IMAGE_BACKEND", "auto"
        ).strip().lower()
    if choice not in ("auto", "native", "pil"):
        raise ValueError(
            f"image backend {choice!r} is not one of native|pil|auto "
            "(TFK8S_IMAGE_BACKEND)"
        )
    if choice == "pil":
        return "pil"
    if os.environ.get("TFK8S_PURE_PY") == "1":
        return "pil"
    from tfk8s_tpu.data.images import _native_decode

    if _native_decode.load() is not None:
        return "native"
    _native_decode.warn_fallback_once(
        "backend 'native' requested" if choice == "native"
        else "no toolchain or libjpeg to build it"
    )
    return "pil"


def image_backend() -> str:
    """The env-resolved decode backend for this process (see
    :func:`resolve_backend`)."""
    return resolve_backend(None)


def open_image(encoded: bytes):
    """Compressed bytes -> PIL RGB image (the PIL transform path crops
    on the PIL object BEFORE materializing pixels — cheaper than
    decoding to a full array first)."""
    Image = _require_pil()
    try:
        img = Image.open(io.BytesIO(encoded))
        img.load()
    except Exception as exc:  # noqa: BLE001 — PIL raises a zoo of types
        raise ImageDecodeError(f"undecodable image bytes: {exc}") from exc
    if img.mode != "RGB":
        img = img.convert("RGB")
    return img


def image_size(
    encoded: bytes,
    stamped: Optional[Tuple[int, int, int]] = None,
) -> Tuple[int, int, int]:
    """(height, width, channels), cheapest source first: the packer's
    header-stamped geometry when the caller already decoded the Example
    (``stamped=(ex.height, ex.width, ex.channels)`` — no second header
    parse per record on the hot path), else the container HEADER only —
    never a full decode."""
    if stamped is not None and all(int(v) > 0 for v in stamped):
        return int(stamped[0]), int(stamped[1]), int(stamped[2])
    if _PILImage is None and sniff_format(encoded) == "jpeg":
        # PIL-less rig with the native core: the C header parse serves
        from tfk8s_tpu.data.images import _native_decode

        info = _native_decode.jpeg_info(encoded)
        if info is not None:
            return info
    Image = _require_pil()
    try:
        with Image.open(io.BytesIO(encoded)) as img:
            w, h = img.size
            bands = len(img.getbands())
    except Exception as exc:  # noqa: BLE001
        raise ImageDecodeError(f"unreadable image header: {exc}") from exc
    return h, w, bands


def decode_image(encoded: bytes) -> np.ndarray:
    """Compressed bytes -> HWC uint8 RGB array, through the resolved
    backend. The native core serves JPEG; PNG — and any bytes the core
    rejects — falls through to PIL, whose error text names the
    corruption."""
    if sniff_format(encoded) == "jpeg" and image_backend() == "native":
        from tfk8s_tpu.data.images import _native_decode

        out = _native_decode.decode_jpeg(encoded)
        if out is not None:
            return out
    return np.asarray(open_image(encoded), dtype=np.uint8)


def encode_jpeg(array: np.ndarray, quality: int = 90) -> bytes:
    """HWC uint8 RGB -> JPEG bytes (packer/bench/test helper)."""
    Image = _require_pil()
    buf = io.BytesIO()
    Image.fromarray(np.asarray(array, np.uint8), "RGB").save(
        buf, format="JPEG", quality=quality
    )
    return buf.getvalue()


def encode_png(array: np.ndarray) -> bytes:
    """HWC uint8 RGB -> PNG bytes (lossless — the golden-decode tests
    pin exact pixels through this path)."""
    Image = _require_pil()
    buf = io.BytesIO()
    Image.fromarray(np.asarray(array, np.uint8), "RGB").save(
        buf, format="PNG"
    )
    return buf.getvalue()


def have_decoder() -> bool:
    return _PILImage is not None
