"""Image tree -> recordio shards (the image half of the loop; the text
half is ``data/corpus.py``).

CLI::

    # pack an ImageNet-style tree (one subdirectory per class)
    python -m tfk8s_tpu.data.images.pack \
        --input /data/imagenet/train --out-dir shards --num-shards 64

    # or generate a synthetic labeled JPEG set (demos, tests, bench)
    python -m tfk8s_tpu.data.images.pack \
        --synthetic 512 --classes 8 --image-size 64 --out-dir shards \
        --num-shards 4

Class labels are the sorted subdirectory order, written to
``labels.json`` next to the shards so training and evaluation agree on
the index mapping. Images are packed as their ORIGINAL compressed bytes
(no re-encode — packing is IO-bound, and generation loss is forever);
geometry is parsed from each header and stamped into the record. Write
>= one shard per training host to keep per-host file IO
(``data/recordio.shard_files``).

The synthetic mode draws class-conditional template images plus noise —
the same learnable-task construction as ``models/resnet.make_batch_fn``
— then JPEG-encodes them, so a files-mode ResNet can demonstrably
CONVERGE on packed shards end to end.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Iterator, List, Tuple

import numpy as np

from tfk8s_tpu.data.images import decode as imgdecode
from tfk8s_tpu.data.images import schema

_IMAGE_EXTS = (".jpg", ".jpeg", ".png")


def iter_class_tree(root: str) -> Tuple[List[str], Iterator[Tuple[str, int]]]:
    """(class names, iterator of (image path, label)) over a
    one-subdir-per-class tree, both in sorted order."""
    classes = sorted(
        d for d in os.listdir(root)
        if os.path.isdir(os.path.join(root, d))
    )
    if not classes:
        raise FileNotFoundError(f"no class subdirectories under {root}")

    def gen():
        for label, cls in enumerate(classes):
            cdir = os.path.join(root, cls)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(_IMAGE_EXTS):
                    yield os.path.join(cdir, fname), label

    return classes, gen()


def pack_tree(
    root: str, out_dir: str, num_shards: int, limit_per_class: int = 0
) -> Tuple[List[str], int]:
    """Pack a class tree into shards; returns (shard paths, n packed)."""
    classes, items = iter_class_tree(root)
    counts = [0] * len(classes)
    packed = [0]

    def records():
        for path, label in items:
            if limit_per_class and counts[label] >= limit_per_class:
                continue
            with open(path, "rb") as f:
                raw = f.read()
            try:
                shape = imgdecode.image_size(raw)
            except imgdecode.ImageDecodeError as exc:
                raise imgdecode.ImageDecodeError(
                    f"{path}: {exc}"
                ) from exc
            counts[label] += 1
            packed[0] += 1
            yield schema.encode_image_example(raw, label, shape=shape)

    paths = schema.write_image_shards(records(), out_dir, num_shards)
    with open(os.path.join(out_dir, "labels.json"), "w") as f:
        json.dump({cls: i for i, cls in enumerate(classes)}, f, indent=1)
    return paths, packed[0]


def synthetic_records(
    n: int, classes: int, image_size: int, seed: int, quality: int
) -> Iterator[bytes]:
    """Class-template-plus-noise uint8 images, JPEG-encoded. Labels
    cycle so every shard sees every class.

    Templates are LOW-FREQUENCY color fields (a 4x4 random grid
    bilinearly upsampled), not per-pixel noise: any random-resized crop
    of a smooth field still carries the class's color structure, so the
    task stays learnable UNDER the training augmentation — and smooth
    content is also what JPEG preserves (iid-noise templates die twice:
    once to quantization, once to cropping)."""
    from tfk8s_tpu.data.images.transforms import _bilinear

    from PIL import Image  # packer host == training host; PIL present

    rng = np.random.default_rng(seed)
    temps = np.stack(
        [
            np.asarray(
                Image.fromarray(
                    rng.integers(0, 256, size=(4, 4, 3)).astype(np.uint8),
                    "RGB",
                ).resize((image_size, image_size), _bilinear()),
                dtype=np.float32,
            )
            for _ in range(classes)
        ]
    )
    for i in range(n):
        label = i % classes
        noise = rng.normal(0.0, 16.0, (image_size, image_size, 3))
        arr = np.clip(temps[label] + noise, 0, 255)
        raw = imgdecode.encode_jpeg(arr.astype(np.uint8), quality=quality)
        yield schema.encode_image_example(
            raw, label, shape=(image_size, image_size, 3)
        )


def pack_synthetic(
    out_dir: str,
    n: int,
    classes: int,
    image_size: int,
    num_shards: int,
    seed: int = 0,
    quality: int = 90,
) -> List[str]:
    paths = schema.write_image_shards(
        synthetic_records(n, classes, image_size, seed, quality),
        out_dir,
        num_shards,
    )
    with open(os.path.join(out_dir, "labels.json"), "w") as f:
        json.dump({f"class{i:03d}": i for i in range(classes)}, f, indent=1)
    return paths


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument(
        "--input", help="class-per-subdirectory image tree to pack"
    )
    src.add_argument(
        "--synthetic", type=int, metavar="N",
        help="generate N synthetic labeled JPEGs instead of reading a tree",
    )
    ap.add_argument("--out-dir", required=True)
    ap.add_argument("--num-shards", type=int, default=4)
    ap.add_argument(
        "--limit-per-class", type=int, default=0,
        help="cap images per class (0 = all; subsetting for smoke runs)",
    )
    ap.add_argument("--classes", type=int, default=8, help="synthetic only")
    ap.add_argument(
        "--image-size", type=int, default=64, help="synthetic only"
    )
    ap.add_argument("--seed", type=int, default=0, help="synthetic only")
    ap.add_argument(
        "--quality", type=int, default=90, help="synthetic JPEG quality"
    )
    args = ap.parse_args(argv)

    if args.synthetic is not None:
        paths = pack_synthetic(
            args.out_dir, args.synthetic, args.classes, args.image_size,
            args.num_shards, seed=args.seed, quality=args.quality,
        )
        n = args.synthetic
    else:
        paths, n = pack_tree(
            args.input, args.out_dir, args.num_shards,
            limit_per_class=args.limit_per_class,
        )
    total = sum(os.path.getsize(p) for p in paths)
    print(
        f"packed {n} images into {len(paths)} shards "
        f"({total / 1e6:.1f} MB) under {args.out_dir}"
    )


if __name__ == "__main__":
    main()
