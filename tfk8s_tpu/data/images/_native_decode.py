"""Lazy g++ build + ctypes binding for the native image-decode core
(``data/native/imagecore.cc``: libjpeg via the system ``jpeglib.h``,
linked ``-ljpeg``).

Same discipline as ``data/_native.py`` (the recordio core): the shared
object is compiled on first use into a cache directory keyed by the
source hash (``$TFK8S_NATIVE_CACHE``, else ``~/.cache/tfk8s-tpu``), so
rebuilds happen exactly when the source changes and concurrent builders
race benignly (atomic rename). Rigs without a toolchain or without
``jpeglib.h``/``libjpeg`` — or ``TFK8S_PURE_PY=1``, the single switch
that disables ALL native codepaths — fall back to the PIL decoder in
``decode.py``; every capability has both paths and the tests assert
they agree (exact pixels for PNG-through-PIL, bounded tolerance for
JPEG — IDCT implementations legitimately differ).

The binder exposes the C core at two levels:

- :func:`decode_jpeg` / :func:`decode_jpeg_scaled` / :func:`jpeg_info`
  — array in, array out (tests, :func:`decode.decode_image`);
- :func:`decode_rrc_into` — the fused training hot path: scaled decode
  + crop + bilinear resize + flip + normalize written straight into a
  caller-provided float32 batch slot, one C call per image. The decode
  scratch frame is thread-local and reused, so a steady-state decode
  worker allocates nothing per image.
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
from typing import Optional, Tuple

import numpy as np

from tfk8s_tpu.data._native import build_cached, dlopen_checked

log = logging.getLogger("tfk8s.data.images.native")

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native", "imagecore.cc",
)

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_fallback_warned = False

_i64 = ctypes.c_int64
_pi64 = ctypes.POINTER(_i64)
_pu8 = ctypes.POINTER(ctypes.c_uint8)
_pf32 = ctypes.POINTER(ctypes.c_float)


def _build() -> Optional[str]:
    # the shared hash-keyed build (data/_native.build_cached); a FAILED
    # build with g++ present is most often a missing jpeglib.h —
    # build_cached logs the compiler's own words either way
    return build_cached(
        _SRC, "imagecore", log,
        "image-decode core (missing jpeglib.h / libjpeg-dev?)",
        "the PIL decoder (~2-4x slower per decode worker)",
        extra_flags=("-ljpeg",),
    )


def load() -> Optional[ctypes.CDLL]:
    """The bound native library, or None (toolchain/libjpeg missing, or
    disabled). Build + bind happen once per process and the result is
    latched; the ``TFK8S_PURE_PY=1`` opt-out is checked on EVERY call so
    flipping it (tests, operator toggles) takes effect immediately."""
    global _lib, _tried
    if os.environ.get("TFK8S_PURE_PY") == "1":
        return None
    if _tried:
        return _lib
    with _lock:
        if _tried:
            return _lib
        path = _build()
        if path is None:
            _tried = True
            return None
        lib = dlopen_checked(
            path, log, "image-decode core",
            "the PIL decoder (~2-4x slower per decode worker)",
        )
        if lib is None:
            _tried = True
            return None
        lib.img_info.restype = _i64
        lib.img_info.argtypes = [ctypes.c_char_p, _i64, _pi64, _pi64, _pi64]
        lib.img_decode.restype = _i64
        lib.img_decode.argtypes = [
            ctypes.c_char_p, _i64, _pu8, _i64, _pi64, _pi64
        ]
        lib.img_decode_scaled.restype = _i64
        lib.img_decode_scaled.argtypes = [
            ctypes.c_char_p, _i64, _i64, _pu8, _i64, _pi64, _pi64
        ]
        lib.img_decode_rrc.restype = _i64
        lib.img_decode_rrc.argtypes = [
            ctypes.c_char_p, _i64,            # data, n
            _i64, _i64, _i64, _i64,           # top, left, crop_h, crop_w
            _i64, _i64,                       # full_h, full_w (the stamp)
            _i64, ctypes.c_int32, _i64,       # target, flip, scale_num
            _pf32, _pf32,                     # chan_scale, chan_bias
            _pu8, _i64,                       # scratch, scratch_cap
            _pf32,                            # out
        ]
        _lib = lib
        _tried = True
        return _lib


def available() -> bool:
    return load() is not None


def warn_fallback_once(reason: str) -> None:
    """One loud line the first time image decode runs without the native
    core it expected — an input-bandwidth regression, not a detail
    (measured: the fused native path delivers ~2.4x the PIL decode
    worker's img/s at 224px, more on multi-megapixel sources via
    DCT-scaled decode). Deliberate opt-outs (``TFK8S_PURE_PY=1``,
    ``TFK8S_IMAGE_BACKEND=pil``) stay quiet — the operator chose them."""
    global _fallback_warned
    if _fallback_warned:
        return
    with _lock:
        if _fallback_warned:
            return
        _fallback_warned = True
    log.warning(
        "image decode: native core unavailable (%s) — PIL decoder in use "
        "(~2.4x slower per decode worker at 224px; more on large sources, "
        "which lose DCT-scaled decode). Install g++ + libjpeg-dev (or see "
        "the build warning above) to restore decode bandwidth.",
        reason,
    )


def scaled_dim(dim: int, scale_num: int) -> int:
    """libjpeg's output size for one side at ``scale_num/8``:
    ``ceil(dim * scale_num / 8)`` (jdiv_round_up)."""
    return (dim * scale_num + 7) // 8


def jpeg_info(encoded: bytes) -> Optional[Tuple[int, int, int]]:
    """(height, width, source components) from the JPEG header, or None
    when the native core is unavailable or rejects the bytes."""
    lib = load()
    if lib is None:
        return None
    h, w, c = _i64(), _i64(), _i64()
    if lib.img_info(encoded, len(encoded), h, w, c) != 0:
        return None
    return h.value, w.value, c.value


def decode_jpeg_scaled(
    encoded: bytes, scale_num: int = 8
) -> Optional[np.ndarray]:
    """JPEG bytes -> HWC uint8 RGB at ``scale_num/8`` scale, or None
    (native core unavailable, or bytes it cannot decode — the caller
    retries through PIL, whose error text names the corruption)."""
    lib = load()
    if lib is None:
        return None
    info = jpeg_info(encoded)
    if info is None:
        return None
    h, w = scaled_dim(info[0], scale_num), scaled_dim(info[1], scale_num)
    out = np.empty((h, w, 3), np.uint8)
    oh, ow = _i64(), _i64()
    rc = lib.img_decode_scaled(
        encoded, len(encoded), scale_num,
        out.ctypes.data_as(_pu8), out.nbytes, oh, ow,
    )
    if rc != 0:
        return None
    return out[: oh.value, : ow.value]


def decode_jpeg(encoded: bytes) -> Optional[np.ndarray]:
    """JPEG bytes -> full-scale HWC uint8 RGB, or None (see
    :func:`decode_jpeg_scaled`)."""
    return decode_jpeg_scaled(encoded, 8)


# per-decode-worker scratch frame, grown to the largest scaled frame the
# worker has seen — steady state decodes allocate nothing
_scratch = threading.local()


def _scratch_buf(nbytes: int) -> np.ndarray:
    buf = getattr(_scratch, "buf", None)
    if buf is None or buf.nbytes < nbytes:
        buf = np.empty(nbytes, np.uint8)
        _scratch.buf = buf
    return buf


def decode_rrc_into(
    encoded: bytes,
    box: Tuple[int, int, int, int],
    target: int,
    flip: bool,
    scale_num: int,
    chan_scale: np.ndarray,
    chan_bias: np.ndarray,
    dst: np.ndarray,
    frame: Tuple[int, int],
) -> bool:
    """The fused hot path: decode ``encoded`` at ``scale_num/8``, crop
    ``box`` (top, left, h, w in FULL-resolution coordinates — drawn by
    the caller from header-stamped geometry so crop parameters stay
    backend-independent), bilinear-resize to ``target``, mirror when
    ``flip``, and write ``pix * chan_scale[c] + chan_bias[c]`` float32
    into ``dst`` (a C-contiguous [target, target, 3] float32 view, e.g.
    one slot of the preallocated batch). ``frame`` is the full-scale
    (height, width) — the header stamp; it sizes the scratch frame and
    the C side verifies it against the real frame (a lying stamp comes
    back as a refusal, never an overflow). Returns False when the
    native path cannot serve the image (library absent, corrupt bytes,
    geometry mismatch) — the caller falls back to PIL."""
    lib = load()
    if lib is None:
        return False
    # the pointer handoff is unchecked past here: a wrong dtype or a
    # strided view would be SILENT pixel corruption, and an undersized
    # buffer a heap overwrite — the C kernel writes target*target*3
    # floats unconditionally
    if (
        dst.dtype != np.float32
        or not dst.flags.c_contiguous
        or dst.shape != (target, target, 3)
    ):
        raise ValueError(
            f"dst must be C-contiguous float32 [{target}, {target}, 3], "
            f"got {dst.dtype} {dst.shape} "
            f"(contiguous={dst.flags.c_contiguous})"
        )
    for name, arr in (("chan_scale", chan_scale), ("chan_bias", chan_bias)):
        if arr.dtype != np.float32 or not arr.flags.c_contiguous or arr.size != 3:
            raise ValueError(f"{name} must be 3 C-contiguous float32 values")
    h, w = frame
    need = scaled_dim(h, scale_num) * scaled_dim(w, scale_num) * 3
    scratch = _scratch_buf(need)
    top, left, ch, cw = box
    rc = lib.img_decode_rrc(
        encoded, len(encoded),
        top, left, ch, cw,
        h, w,
        target, 1 if flip else 0, scale_num,
        chan_scale.ctypes.data_as(_pf32),
        chan_bias.ctypes.data_as(_pf32),
        scratch.ctypes.data_as(_pu8), scratch.nbytes,
        dst.ctypes.data_as(_pf32),
    )
    return rc == 0
