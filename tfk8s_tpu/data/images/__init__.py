"""Image data plane: real JPEG/PNG records, decode + augmentation.

The north star trains "ResNet-50 ImageNet" (BASELINE.json) — this
package closes the gap between the recordio substrate (which sustains
GB/s but had never fed a real image) and the vision models (which
trained on synthetic template-plus-noise tensors):

- ``schema``   — the image Example layout (encoded JPEG/PNG bytes +
  label + shape metadata) on the ``data/example.py`` codec, plus the
  shard writer that packs ImageNet-style trees into recordio shards;
- ``decode``   — compressed bytes -> HWC uint8 RGB behind a backend
  dispatch (``TFK8S_IMAGE_BACKEND=native|pil|auto``): the native
  libjpeg core (``native/imagecore.cc``, lazy-built by
  ``_native_decode.py`` on the recordio.cc g++ pattern) serves JPEG
  with DCT-scaled decode; PIL is the reference path and the fallback
  when the toolchain, libjpeg, or the format support is absent;
- ``transforms`` — random-resized-crop / horizontal-flip / per-channel
  normalize for training, resize + center-crop for eval, all
  seed-deterministic for resume;
- ``pipeline`` — ``ImageDataset``: a ``RecordDataset`` whose decode
  stage fans out over a bounded worker pool, exporting
  ``tfk8s_images_decoded_total`` / decode-seconds / queue-depth through
  the obs metrics registry;
- ``pack``     — the CLI (``python -m tfk8s_tpu.data.images.pack``)
  packing a class-per-subdir image tree (or a synthetic JPEG set) into
  training shards.
"""

from tfk8s_tpu.data.images.decode import (  # noqa: F401
    ImageDecodeError,
    decode_image,
    encode_jpeg,
    encode_png,
    image_backend,
    image_size,
)
from tfk8s_tpu.data.images.pipeline import (  # noqa: F401
    ImageDataset,
    get_metrics,
    set_metrics,
)
from tfk8s_tpu.data.images.schema import (  # noqa: F401
    ImageExample,
    ImageSchemaError,
    decode_image_example,
    encode_image_example,
    is_image_example,
    write_image_shards,
)
from tfk8s_tpu.data.images.transforms import (  # noqa: F401
    IMAGENET_MEAN,
    IMAGENET_STD,
    eval_transform,
    train_transform,
)

__all__ = [
    "IMAGENET_MEAN",
    "IMAGENET_STD",
    "ImageDataset",
    "ImageDecodeError",
    "ImageExample",
    "ImageSchemaError",
    "decode_image",
    "decode_image_example",
    "encode_image_example",
    "encode_jpeg",
    "encode_png",
    "eval_transform",
    "get_metrics",
    "image_backend",
    "image_size",
    "is_image_example",
    "set_metrics",
    "train_transform",
    "write_image_shards",
]
