"""Image transforms: the standard ImageNet training recipe (random
resized crop + horizontal flip + per-channel normalize) and its
deterministic eval counterpart (resize shorter side + center crop).

Seed discipline: every random choice draws from the ``np.random
.Generator`` the caller passes — no module/global state — so the
pipeline can derive one generator per (seed, epoch, record) and a
resumed run replays the IDENTICAL augmentation stream (the same
property the record shuffle in ``data/dataset.py`` has).

Backend split: the CROP PARAMETERS (:func:`train_crop_params`,
:func:`eval_crop_box`) are computed from the record's header-stamped
geometry first, in full-resolution coordinates, consuming a fixed rng
draw sequence — so they are identical whichever decoder materializes
the pixels, and resume determinism survives a backend switch mid-fleet.
The decode backend then picks the cheapest way to realize the crop:

- PIL: :func:`apply_crop` resizes on the PIL object with ``box=`` (a
  224 crop of a 500x375 JPEG touches ~1/3 of the pixels a
  decode-then-crop pipeline would);
- native: :func:`choose_scale` picks the largest DCT-domain downscale
  (``scale_num/8``) whose decoded frame still covers the crop's resize
  target, and the fused C kernel does the rest
  (``_native_decode.decode_rrc_into``).
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple, Union

import numpy as np

# ImageNet per-channel statistics (the constants every pretrained-vision
# pipeline normalizes with)
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)

# eval resizes the shorter side to size * (256/224) before the center
# crop — the canonical 256-resize/224-crop ratio, kept exact for any
# target size
_EVAL_RESIZE_RATIO = 256 / 224

# DCT-domain scales the pipeline will ask libjpeg for: powers of two
# only — libjpeg-turbo has SIMD IDCT at 1/8, 2/8, 4/8, 8/8; a "cheaper"
# 6/8 decode runs the scalar 6x6 IDCT and measures SLOWER than a
# full-scale SIMD decode
_SIMD_SCALES = (1, 2, 4, 8)


def _as_pil(img):
    from tfk8s_tpu.data.images.decode import _require_pil

    Image = _require_pil()
    if isinstance(img, np.ndarray):
        return Image.fromarray(np.asarray(img, np.uint8), "RGB")
    return img


def _bilinear():
    from tfk8s_tpu.data.images.decode import _require_pil

    Image = _require_pil()
    # Pillow >= 9.1 moved resample filters to Image.Resampling
    return getattr(Image, "Resampling", Image).BILINEAR


def sample_crop(
    rng: np.random.Generator,
    height: int,
    width: int,
    scale: Tuple[float, float] = (0.08, 1.0),
    ratio: Tuple[float, float] = (3 / 4, 4 / 3),
    attempts: int = 10,
) -> Tuple[int, int, int, int]:
    """The random-resized-crop box (top, left, h, w): area uniform in
    ``scale`` x image area, aspect log-uniform in ``ratio``; after
    ``attempts`` rejections fall back to the largest in-ratio center
    crop (torchvision's exact fallback, so tail-shaped images don't
    bias toward tiny crops)."""
    area = height * width
    log_ratio = (math.log(ratio[0]), math.log(ratio[1]))
    for _ in range(attempts):
        target = area * rng.uniform(scale[0], scale[1])
        aspect = math.exp(rng.uniform(*log_ratio))
        w = int(round(math.sqrt(target * aspect)))
        h = int(round(math.sqrt(target / aspect)))
        if 0 < w <= width and 0 < h <= height:
            top = int(rng.integers(0, height - h + 1))
            left = int(rng.integers(0, width - w + 1))
            return top, left, h, w
    in_ratio = width / height
    if in_ratio < ratio[0]:
        w, h = width, int(round(width / ratio[0]))
    elif in_ratio > ratio[1]:
        w, h = int(round(height * ratio[1])), height
    else:
        w, h = width, height
    return (height - h) // 2, (width - w) // 2, h, w


def train_crop_params(
    rng: np.random.Generator,
    height: int,
    width: int,
    min_scale: float = 0.08,
) -> Tuple[int, int, int, int, bool]:
    """The full training draw for one image — RRC box (top, left, h, w)
    plus the horizontal-flip coin — from geometry alone, BEFORE any
    pixel is decoded. Consumes exactly the same rng draws regardless of
    which backend later materializes the crop, so the per-(seed, epoch,
    record) stream is backend-independent and a resumed run replays it
    identically."""
    top, left, ch, cw = sample_crop(rng, height, width, scale=(min_scale, 1.0))
    flip = bool(rng.integers(0, 2))
    return top, left, ch, cw, flip


def eval_crop_box(height: int, width: int, size: int) -> Tuple[int, int, int, int]:
    """The deterministic eval view's crop as a SOURCE-coordinate box
    (top, left, h, w): resize-shorter-side-then-center-crop is, in
    source coordinates, a centered square of side
    ``min(h, w) * size / (size * 256/224)`` — the form the native
    scaled-decode path consumes (crop box first, cheapest covering
    scale second)."""
    short = max(int(round(size * _EVAL_RESIZE_RATIO)), size)
    side = int(round(min(height, width) * size / short))
    side = max(min(side, height, width), 1)
    return (height - side) // 2, (width - side) // 2, side, side


def choose_scale(crop_h: int, crop_w: int, target: int) -> int:
    """The largest DCT-domain downscale (smallest ``scale_num``, denom
    8) whose decoded frame still COVERS the crop's resize target — i.e.
    the scaled crop stays >= ``target`` px on both sides, so the
    follow-on bilinear resize never upscales (quality) and the IDCT
    does the least work (speed). A crop already smaller than the target
    decodes at full scale. Scales are restricted to the SIMD set
    {1, 2, 4, 8}."""
    for s in _SIMD_SCALES:
        if crop_h * s >= 8 * target and crop_w * s >= 8 * target:
            return s
    return 8


@functools.lru_cache(maxsize=8)
def normalize_affine(
    mean: Tuple[float, float, float] = IMAGENET_MEAN,
    std: Tuple[float, float, float] = IMAGENET_STD,
) -> Tuple[np.ndarray, np.ndarray]:
    """Normalization as ONE fused per-channel multiply-add:
    ``(p/255 - mean)/std == p * scale + bias`` with
    ``scale = 1/(255*std)``, ``bias = -mean/std`` — float32,
    C-contiguous, cached. THE single source of the constants both
    backends apply (the PIL path through :func:`_affine_to`, the native
    path handed to the fused C kernel) — so they cannot drift apart.
    Treat the returned arrays as read-only (they are shared)."""
    std32 = np.asarray(std, np.float32)
    scale = np.ascontiguousarray(1.0 / (255.0 * std32))
    bias = np.ascontiguousarray(-np.asarray(mean, np.float32) / std32)
    return scale, bias


def normalize(
    pixels: np.ndarray,
    mean: Tuple[float, float, float] = IMAGENET_MEAN,
    std: Tuple[float, float, float] = IMAGENET_STD,
) -> np.ndarray:
    """uint8 HWC -> float32 HWC, scaled to [0,1] then per-channel
    standardized."""
    return _affine_to(pixels, True, mean, std, None)


def _affine_to(
    pixels: np.ndarray,
    do_normalize: bool,
    mean: Tuple[float, float, float],
    std: Tuple[float, float, float],
    out: Optional[np.ndarray],
) -> np.ndarray:
    """uint8 -> float32 as one fused per-channel affine written into
    ``out`` when given (a preallocated batch slot — no per-image array,
    no later stack copy): normalize is ``p/255/std - mean/std``; the
    raw-float contract (``do_normalize=False``) is ``p * 1 + 0``."""
    pixels = np.asarray(pixels)
    if pixels.dtype != np.uint8:
        # keep float32 math whatever arrives (the old contract)
        pixels = pixels.astype(np.float32, copy=False)
    if do_normalize:
        scale, bias = normalize_affine(tuple(mean), tuple(std))
        out = np.multiply(pixels, scale, out=out)
        out += bias
        return out
    if out is None:
        return np.asarray(pixels, np.float32)
    out[...] = pixels
    return out


def apply_crop(
    img: Union[np.ndarray, "object"],
    box: Tuple[int, int, int, int],
    size: int,
    flip: bool = False,
    do_normalize: bool = True,
    mean: Tuple[float, float, float] = IMAGENET_MEAN,
    std: Tuple[float, float, float] = IMAGENET_STD,
    out: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Materialize an already-drawn crop through PIL: resize ``box``
    (top, left, h, w) to ``size`` x ``size``, mirror when ``flip``,
    float32(+normalize) into ``out`` when given. The PIL half of the
    backend split — pixel-identical to the historical
    ``train_transform`` for the same draws."""
    pil = _as_pil(img)
    top, left, ch, cw = box
    pil = pil.resize(
        (size, size), _bilinear(), box=(left, top, left + cw, top + ch)
    )
    arr = np.asarray(pil, np.uint8)
    if flip:
        arr = arr[:, ::-1]
    return _affine_to(arr, do_normalize, mean, std, out)


def train_transform(
    img: Union[np.ndarray, "object"],
    rng: np.random.Generator,
    size: int,
    do_normalize: bool = True,
    min_scale: float = 0.08,
    out: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Random-resized-crop to ``size`` + horizontal flip (p=0.5) +
    normalize -> float32 [size, size, 3], written into ``out`` when
    given. Draws via :func:`train_crop_params` (fixed rng consumption),
    materializes via :func:`apply_crop`.

    ``min_scale`` is the crop-area floor: 0.08 is the ImageNet
    standard (224px natural images, ~1.3M samples); small/synthetic
    datasets usually want a gentler 0.3-0.6 — an 8%-area crop of a
    28px image is an 8px blob, and a toy task trained on those stops
    converging (regularization outweighing signal)."""
    pil = _as_pil(img)
    w, h = pil.size
    top, left, ch, cw, flip = train_crop_params(rng, h, w, min_scale)
    return apply_crop(
        pil, (top, left, ch, cw), size, flip, do_normalize, out=out
    )


def eval_transform(
    img: Union[np.ndarray, "object"],
    size: int,
    do_normalize: bool = True,
    out: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Deterministic eval view: shorter side to ``size * 256/224``,
    center crop ``size`` -> float32 [size, size, 3]."""
    pil = _as_pil(img)
    w, h = pil.size
    short = max(int(round(size * _EVAL_RESIZE_RATIO)), size)
    if w <= h:
        rw, rh = short, max(int(round(h * short / w)), short)
    else:
        rw, rh = max(int(round(w * short / h)), short), short
    pil = pil.resize((rw, rh), _bilinear())
    left, top = (rw - size) // 2, (rh - size) // 2
    pil = pil.crop((left, top, left + size, top + size))
    arr = np.asarray(pil, np.uint8)
    return _affine_to(arr, do_normalize, IMAGENET_MEAN, IMAGENET_STD, out)
