"""Image transforms: the standard ImageNet training recipe (random
resized crop + horizontal flip + per-channel normalize) and its
deterministic eval counterpart (resize shorter side + center crop).

Seed discipline: every random choice draws from the ``np.random
.Generator`` the caller passes — no module/global state — so the
pipeline can derive one generator per (seed, epoch, record) and a
resumed run replays the IDENTICAL augmentation stream (the same
property the record shuffle in ``data/dataset.py`` has). Crops happen
on the PIL object before pixels materialize: cropping a 500x375 JPEG to
a 224 training crop touches ~1/3 of the pixels a decode-then-crop
pipeline would.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple, Union

import numpy as np

# ImageNet per-channel statistics (the constants every pretrained-vision
# pipeline normalizes with)
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)

# eval resizes the shorter side to size * (256/224) before the center
# crop — the canonical 256-resize/224-crop ratio, kept exact for any
# target size
_EVAL_RESIZE_RATIO = 256 / 224


def _as_pil(img):
    from tfk8s_tpu.data.images.decode import _require_pil

    Image = _require_pil()
    if isinstance(img, np.ndarray):
        return Image.fromarray(np.asarray(img, np.uint8), "RGB")
    return img


def _bilinear():
    from tfk8s_tpu.data.images.decode import _require_pil

    Image = _require_pil()
    # Pillow >= 9.1 moved resample filters to Image.Resampling
    return getattr(Image, "Resampling", Image).BILINEAR


def sample_crop(
    rng: np.random.Generator,
    height: int,
    width: int,
    scale: Tuple[float, float] = (0.08, 1.0),
    ratio: Tuple[float, float] = (3 / 4, 4 / 3),
    attempts: int = 10,
) -> Tuple[int, int, int, int]:
    """The random-resized-crop box (top, left, h, w): area uniform in
    ``scale`` x image area, aspect log-uniform in ``ratio``; after
    ``attempts`` rejections fall back to the largest in-ratio center
    crop (torchvision's exact fallback, so tail-shaped images don't
    bias toward tiny crops)."""
    area = height * width
    log_ratio = (math.log(ratio[0]), math.log(ratio[1]))
    for _ in range(attempts):
        target = area * rng.uniform(scale[0], scale[1])
        aspect = math.exp(rng.uniform(*log_ratio))
        w = int(round(math.sqrt(target * aspect)))
        h = int(round(math.sqrt(target / aspect)))
        if 0 < w <= width and 0 < h <= height:
            top = int(rng.integers(0, height - h + 1))
            left = int(rng.integers(0, width - w + 1))
            return top, left, h, w
    in_ratio = width / height
    if in_ratio < ratio[0]:
        w, h = width, int(round(width / ratio[0]))
    elif in_ratio > ratio[1]:
        w, h = int(round(height * ratio[1])), height
    else:
        w, h = width, height
    return (height - h) // 2, (width - w) // 2, h, w


def normalize(
    pixels: np.ndarray,
    mean: Tuple[float, float, float] = IMAGENET_MEAN,
    std: Tuple[float, float, float] = IMAGENET_STD,
) -> np.ndarray:
    """uint8 HWC -> float32 HWC, scaled to [0,1] then per-channel
    standardized."""
    out = np.asarray(pixels, np.float32) / 255.0
    out -= np.asarray(mean, np.float32)
    out /= np.asarray(std, np.float32)
    return out


def train_transform(
    img: Union[np.ndarray, "object"],
    rng: np.random.Generator,
    size: int,
    do_normalize: bool = True,
    min_scale: float = 0.08,
) -> np.ndarray:
    """Random-resized-crop to ``size`` + horizontal flip (p=0.5) +
    normalize -> float32 [size, size, 3]. Consumes exactly the same
    rng draws regardless of image geometry (crop box, then one flip
    draw), so the stream stays aligned across datasets.

    ``min_scale`` is the crop-area floor: 0.08 is the ImageNet
    standard (224px natural images, ~1.3M samples); small/synthetic
    datasets usually want a gentler 0.3-0.6 — an 8%-area crop of a
    28px image is an 8px blob, and a toy task trained on those stops
    converging (regularization outweighing signal)."""
    pil = _as_pil(img)
    w, h = pil.size
    top, left, ch, cw = sample_crop(rng, h, w, scale=(min_scale, 1.0))
    flip = bool(rng.integers(0, 2))
    pil = pil.resize(
        (size, size), _bilinear(), box=(left, top, left + cw, top + ch)
    )
    out = np.asarray(pil, np.uint8)
    if flip:
        out = out[:, ::-1]
    return normalize(out) if do_normalize else np.asarray(out, np.float32)


def eval_transform(
    img: Union[np.ndarray, "object"],
    size: int,
    do_normalize: bool = True,
) -> np.ndarray:
    """Deterministic eval view: shorter side to ``size * 256/224``,
    center crop ``size`` -> float32 [size, size, 3]."""
    pil = _as_pil(img)
    w, h = pil.size
    short = max(int(round(size * _EVAL_RESIZE_RATIO)), size)
    if w <= h:
        rw, rh = short, max(int(round(h * short / w)), short)
    else:
        rw, rh = max(int(round(w * short / h)), short), short
    pil = pil.resize((rw, rh), _bilinear())
    left, top = (rw - size) // 2, (rh - size) // 2
    pil = pil.crop((left, top, left + size, top + size))
    out = np.asarray(pil, np.uint8)
    return normalize(out) if do_normalize else np.asarray(out, np.float32)
