"""The image Example schema: one classification sample per record.

Rides the ``data/example.py`` array codec (so image shards share the
recordio framing, CRC discipline, native bulk reader, and per-host
sharding every other record type gets) instead of inventing a second
container. The compressed image travels as a uint8 byte array — decode
happens in the input pipeline (``pipeline.ImageDataset``), never at
pack time, so shards stay at JPEG size (~25x smaller than decoded
float32) and the decode cost lands on the training hosts where it
parallelizes.

Keys (the wire names mirror tf.Example's ``image/*`` convention so a
reader coming from the reference ecosystem finds the same fields):

- ``image/encoded``  uint8[n]  — the compressed JPEG/PNG bytes
- ``image/format``   uint8[m]  — ascii format tag (``jpeg`` | ``png``)
- ``image/label``    int32     — class index
- ``image/height|width|channels`` int32 — decoded geometry, parsed from
  the header at pack time (-1 when unknown); readers can size buffers
  and reject corrupt records before paying a full decode
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from tfk8s_tpu.data import example as example_codec
from tfk8s_tpu.data.recordio import RecordWriter

KEY_ENCODED = "image/encoded"
KEY_FORMAT = "image/format"
KEY_LABEL = "image/label"
KEY_HEIGHT = "image/height"
KEY_WIDTH = "image/width"
KEY_CHANNELS = "image/channels"

_JPEG_MAGIC = b"\xff\xd8\xff"
_PNG_MAGIC = b"\x89PNG\r\n\x1a\n"


class ImageSchemaError(ValueError):
    """A record that is not a well-formed image Example."""


@dataclasses.dataclass
class ImageExample:
    """One decoded-from-the-wire image sample (still compressed)."""

    encoded: bytes
    label: int
    format: str = ""
    height: int = -1
    width: int = -1
    channels: int = -1


def sniff_format(encoded: bytes) -> str:
    """Container format from magic bytes ('' when unrecognized)."""
    if encoded[:3] == _JPEG_MAGIC:
        return "jpeg"
    if encoded[:8] == _PNG_MAGIC:
        return "png"
    return ""


def encode_image_example(
    encoded: bytes,
    label: int,
    fmt: Optional[str] = None,
    shape: Optional[Tuple[int, int, int]] = None,
) -> bytes:
    """One image sample -> record bytes (pair with ``RecordWriter``).
    ``fmt=None`` sniffs the container from magic bytes; unrecognized
    bytes are rejected — a shard of garbage must fail at PACK time, not
    as a decode error on step 40k of a training run."""
    if fmt is None:
        fmt = sniff_format(encoded)
        if not fmt:
            raise ImageSchemaError(
                f"unrecognized image container (first bytes "
                f"{bytes(encoded[:4])!r}); pass fmt= explicitly for "
                "formats without magic-byte sniffing"
            )
    h, w, c = shape if shape is not None else (-1, -1, -1)
    return example_codec.encode(
        {
            KEY_ENCODED: np.frombuffer(bytes(encoded), np.uint8),
            KEY_FORMAT: np.frombuffer(fmt.encode(), np.uint8),
            KEY_LABEL: np.int32(label),
            KEY_HEIGHT: np.int32(h),
            KEY_WIDTH: np.int32(w),
            KEY_CHANNELS: np.int32(c),
        }
    )


def is_image_example(example: Dict[str, np.ndarray]) -> bool:
    return KEY_ENCODED in example and KEY_LABEL in example


def decode_image_example(data: bytes) -> ImageExample:
    """Record bytes -> :class:`ImageExample` (compressed bytes + label +
    metadata). Raises :class:`ImageSchemaError` on any record that is
    not an image Example — the pipeline turns a wrong-schema shard into
    one clear message instead of a shape error deep inside jit."""
    try:
        ex = example_codec.decode(data)
    except example_codec.ExampleDecodeError as exc:
        raise ImageSchemaError(f"corrupt record: {exc}") from exc
    if not is_image_example(ex):
        raise ImageSchemaError(
            f"record keys {sorted(ex.keys())} are not the image schema "
            f"({KEY_ENCODED!r} + {KEY_LABEL!r}); was this shard packed "
            "by data/corpus.py instead of data/images/pack.py?"
        )

    def scalar(key: str, default: int = -1) -> int:
        if key not in ex:
            return default
        return int(np.asarray(ex[key]).reshape(()))

    return ImageExample(
        encoded=ex[KEY_ENCODED].tobytes(),
        label=scalar(KEY_LABEL),
        format=ex.get(KEY_FORMAT, np.zeros(0, np.uint8)).tobytes().decode(
            "ascii", errors="replace"
        ),
        height=scalar(KEY_HEIGHT),
        width=scalar(KEY_WIDTH),
        channels=scalar(KEY_CHANNELS),
    )


def write_image_shards(
    records: Iterable[bytes],
    out_dir: str,
    num_shards: int,
    prefix: str = "images",
) -> List[str]:
    """Round-robin encoded records across ``num_shards`` recordio files
    (``{prefix}-00000.rio`` ...). Writes temp names, renaming into place
    only after every record landed — a failed packing must not leave
    partial shards behind for a later run's glob to feed a host. Write
    >= one shard per training host to keep the 1/hosts file-IO property
    (``data/recordio.shard_files``)."""
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    os.makedirs(out_dir, exist_ok=True)
    paths = [
        os.path.join(out_dir, f"{prefix}-{i:05d}.rio")
        for i in range(num_shards)
    ]
    tmp = [p + ".tmp" for p in paths]
    writers = [RecordWriter(p) for p in tmp]
    n = 0
    try:
        for n, rec in enumerate(records, start=1):
            writers[(n - 1) % num_shards].write(rec)
        for w in writers:
            w.close()
        if n < num_shards:
            raise ValueError(
                f"only {n} images for {num_shards} shards — every shard "
                "must hold at least one record (fewer shards, more data)"
            )
        for t, p in zip(tmp, paths):
            os.replace(t, p)
    finally:
        for w in writers:
            # a records-iterator failure must not leak open shard
            # handles (same class of leak corpus._read_texts had);
            # close() flushes, which is fine — the tmp files die next
            try:
                w.close()
            except Exception:  # noqa: BLE001 — cleanup must reach remove
                pass
        for t in tmp:
            if os.path.exists(t):
                os.remove(t)
    return paths
