"""Binary codec for training examples: a ``{name: np.ndarray}`` dict to
one record's bytes and back. The framework-native equivalent of the
reference ecosystem's tf.Example payload inside TFRecord frames — but
array-shaped (dtype + shape preserved exactly), so decoded batches stack
straight into ``TrainTask`` host batches with no feature-spec parsing.

Layout (all little-endian):
``magic 'TFX1' | u16 n_entries`` then per entry
``u16 keylen | key utf8 | u8 dtypelen | dtype str | u8 ndim |
i64 shape[ndim] | u64 nbytes | raw array bytes (C order)``.
"""

from __future__ import annotations

import struct
from typing import Dict

import numpy as np

_MAGIC = b"TFX1"


class ExampleDecodeError(ValueError):
    pass


def encode(example: Dict[str, np.ndarray]) -> bytes:
    parts = [_MAGIC, struct.pack("<H", len(example))]
    for key in sorted(example):
        # NOT ascontiguousarray: that promotes 0-d arrays to 1-d, which
        # would silently change a scalar label's decoded shape
        arr = np.asarray(example[key], order="C")
        kb = key.encode()
        db = arr.dtype.str.encode()  # e.g. '<i4' — endian + kind + size
        raw = arr.tobytes()
        parts.append(struct.pack("<H", len(kb)))
        parts.append(kb)
        parts.append(struct.pack("<B", len(db)))
        parts.append(db)
        parts.append(struct.pack("<B", arr.ndim))
        parts.append(struct.pack(f"<{arr.ndim}q", *arr.shape))
        parts.append(struct.pack("<Q", len(raw)))
        parts.append(raw)
    return b"".join(parts)


def decode(data: bytes) -> Dict[str, np.ndarray]:
    if data[:4] != _MAGIC:
        raise ExampleDecodeError(f"bad magic {data[:4]!r}")
    (n,) = struct.unpack_from("<H", data, 4)
    pos = 6
    out: Dict[str, np.ndarray] = {}
    try:
        for _ in range(n):
            (klen,) = struct.unpack_from("<H", data, pos)
            pos += 2
            key = data[pos : pos + klen].decode()
            pos += klen
            (dlen,) = struct.unpack_from("<B", data, pos)
            pos += 1
            dtype = np.dtype(data[pos : pos + dlen].decode())
            pos += dlen
            (ndim,) = struct.unpack_from("<B", data, pos)
            pos += 1
            shape = struct.unpack_from(f"<{ndim}q", data, pos)
            pos += 8 * ndim
            (nbytes,) = struct.unpack_from("<Q", data, pos)
            pos += 8
            raw = data[pos : pos + nbytes]
            if len(raw) != nbytes:
                raise ExampleDecodeError("truncated array payload")
            pos += nbytes
            out[key] = np.frombuffer(raw, dtype).reshape(shape).copy()
    except struct.error as exc:
        raise ExampleDecodeError(f"truncated example: {exc}") from exc
    except (TypeError, ValueError) as exc:
        # garbled dtype strings (np.dtype -> TypeError) and shape/nbytes
        # mismatches (reshape -> ValueError) are corruption too — callers
        # catch the module's typed error, not numpy's
        if isinstance(exc, ExampleDecodeError):
            raise
        raise ExampleDecodeError(f"corrupt example metadata: {exc}") from exc
    return out
