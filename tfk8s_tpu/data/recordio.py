"""Record-file IO: TFRecord-framed shards with crc32c integrity.

The reference ecosystem trains from TFRecord shards divided per task
(``/root/reference/k8s-operator.md:6`` — each WORKER reads its own input
division); this module is that container for the TPU framework. Two
interchangeable backends:

- **native** (default): the C++ core in ``native/recordio.cc`` via
  ctypes — single-pass index of a multi-GB shard and bulk CRC-verified
  reads with zero Python-per-record cost;
- **pure Python**: identical framing and CRC semantics, used when no
  toolchain is available (``TFK8S_PURE_PY=1`` forces it; the tests run
  both and assert byte-identical behavior).

Wire framing per record (TFRecord-compatible):
``uint64le length | uint32le masked_crc(length) | data |
uint32le masked_crc(data)`` with crc32c (Castagnoli) and the standard
mask ``rot_right15(crc) + 0xa282ead8``.
"""

from __future__ import annotations

import ctypes
import logging
import os
import struct
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

from tfk8s_tpu.data import _native

log = logging.getLogger("tfk8s.data.recordio")

_MASK_DELTA = 0xA282EAD8

_fallback_warned = False
_fallback_lock = threading.Lock()


def _warn_fallback_once() -> None:
    """One loud line the first time a shard is read without the native
    core: 852 -> 7 MB/s is an input-bandwidth outage, not a detail
    (VERDICT r4 weak #3). Deliberate opt-out (TFK8S_PURE_PY=1) stays
    quiet — the operator chose it."""
    global _fallback_warned
    if _fallback_warned or os.environ.get("TFK8S_PURE_PY") == "1":
        return
    with _fallback_lock:
        if _fallback_warned:
            return
        _fallback_warned = True
        log.warning(
            "recordio: native reader unavailable — pure-Python codec in "
            "use (~120x slower; measured 852 vs 7 MB/s). Install g++ (or "
            "see the build warning above) to restore input bandwidth."
        )

# -- crc32c (pure-Python fallback; the native lib serves the fast path) --

_TABLE: List[int] = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (0x82F63B78 ^ (_c >> 1)) if (_c & 1) else (_c >> 1)
    _TABLE.append(_c)


def crc32c(data: bytes) -> int:
    lib = _native.load()
    if lib is not None:
        return int(lib.rio_crc32c(data, len(data)))
    crc = 0xFFFFFFFF
    for b in data:
        crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    c = crc32c(data)
    return (((c >> 15) | (c << 17)) + _MASK_DELTA) & 0xFFFFFFFF


class RecordIOError(IOError):
    """Framing or checksum violation in a record file."""


class RecordWriter:
    """Append-only writer. Buffers frames and flushes through the native
    bulk writer when available (one fwrite loop in C), else writes the
    same bytes from Python. Context-manager; ``write`` takes raw bytes —
    pair with ``example.encode`` for array dicts."""

    def __init__(self, path: str, flush_every: int = 256):
        self.path = path
        self._pending: List[bytes] = []
        self._flush_every = flush_every
        self._closed = False
        # truncate: a writer owns its shard (matches TF writer semantics)
        open(path, "wb").close()

    def write(self, data: bytes) -> None:
        if self._closed:
            raise ValueError("writer is closed")
        self._pending.append(bytes(data))
        if len(self._pending) >= self._flush_every:
            self.flush()

    def flush(self) -> None:
        if not self._pending:
            return
        recs = self._pending
        # retry safety: on ANY failure, roll the file back to its
        # pre-flush size AND keep the records buffered — a retried
        # flush() then neither drops records nor appends duplicates of a
        # partial write
        pre_size = os.path.getsize(self.path)
        try:
            lib = _native.load()
            if lib is not None:
                blob = b"".join(recs)
                lens = (ctypes.c_int64 * len(recs))(*[len(r) for r in recs])
                buf = (ctypes.c_uint8 * len(blob)).from_buffer_copy(blob)
                rc = lib.rio_write(self.path.encode(), len(recs), buf, lens)
                if rc != 0:
                    raise RecordIOError(
                        f"native write failed rc={rc}: {self.path}"
                    )
            else:
                with open(self.path, "ab") as f:
                    for r in recs:
                        hdr = struct.pack("<Q", len(r))
                        f.write(hdr)
                        f.write(struct.pack("<I", masked_crc32c(hdr)))
                        f.write(r)
                        f.write(struct.pack("<I", masked_crc32c(r)))
        except BaseException:
            try:
                with open(self.path, "rb+") as f:
                    f.truncate(pre_size)
            except OSError:
                pass  # the original error is the one to surface
            raise
        self._pending = []

    def close(self) -> None:
        self.flush()
        self._closed = True

    def __enter__(self) -> "RecordWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _index_py(path: str) -> Tuple[List[int], List[int]]:
    offsets, lengths = [], []
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        while True:
            hdr = f.read(12)
            if not hdr:
                break
            if len(hdr) != 12:
                raise RecordIOError(f"truncated frame header: {path}")
            (length,) = struct.unpack("<Q", hdr[:8])
            (want,) = struct.unpack("<I", hdr[8:])
            if masked_crc32c(hdr[:8]) != want:
                raise RecordIOError(
                    f"header crc mismatch at record {len(offsets)}: {path}"
                )
            off = f.tell()
            if off + length + 4 > size:
                raise RecordIOError(
                    f"truncated record {len(offsets)} body: {path}"
                )
            offsets.append(off)
            lengths.append(length)
            f.seek(length + 4, os.SEEK_CUR)
    return offsets, lengths


def _index_native(lib, path: str) -> Tuple[List[int], List[int]]:
    po = ctypes.POINTER(ctypes.c_int64)()
    pl = ctypes.POINTER(ctypes.c_int64)()
    n = lib.rio_index(path.encode(), ctypes.byref(po), ctypes.byref(pl))
    if n < 0:
        reason = {-1: "open failed", -2: "truncated frame",
                  -3: "header crc mismatch",
                  -5: "out of memory growing the index"}.get(n, f"rc={n}")
        raise RecordIOError(f"index failed ({reason}): {path}")
    try:
        return list(po[:n]), list(pl[:n])
    finally:
        lib.rio_free(po)
        lib.rio_free(pl)


class RecordFile:
    """An indexed record shard with random access by record number.
    Indexing verifies every header CRC up front; reads verify data CRCs
    (``verify=False`` to skip on trusted storage)."""

    def __init__(self, path: str):
        self.path = path
        lib = _native.load()
        if lib is not None:
            self.offsets, self.lengths = _index_native(lib, path)
        else:
            _warn_fallback_once()
            self.offsets, self.lengths = _index_py(path)

    def __len__(self) -> int:
        return len(self.offsets)

    def read(self, indices: Sequence[int], verify: bool = True) -> List[bytes]:
        offs = [self.offsets[i] for i in indices]
        lens = [self.lengths[i] for i in indices]
        lib = _native.load()
        if lib is not None:
            total = sum(lens)
            out = (ctypes.c_uint8 * total)()
            bad = ctypes.c_int64(-1)
            rc = lib.rio_read(
                self.path.encode(), len(offs),
                (ctypes.c_int64 * len(offs))(*offs),
                (ctypes.c_int64 * len(lens))(*lens),
                out, 1 if verify else 0, ctypes.byref(bad),
            )
            if rc == -4:
                raise RecordIOError(
                    f"data crc mismatch at record {indices[bad.value]}: "
                    f"{self.path}"
                )
            if rc != 0:
                raise RecordIOError(f"native read failed rc={rc}: {self.path}")
            # slice through a memoryview: one copy per record, not an
            # extra whole-blob copy first (bulk reads can be GBs)
            view = memoryview(out)
            res, pos = [], 0
            for ln in lens:
                res.append(bytes(view[pos : pos + ln]))
                pos += ln
            return res
        res = []
        with open(self.path, "rb") as f:
            for idx, off, ln in zip(indices, offs, lens):
                f.seek(off)
                data = f.read(ln)
                tail = f.read(4)
                if len(data) != ln or len(tail) != 4:
                    raise RecordIOError(f"short read at record {idx}: {self.path}")
                if verify and struct.unpack("<I", tail)[0] != masked_crc32c(data):
                    raise RecordIOError(
                        f"data crc mismatch at record {idx}: {self.path}"
                    )
                res.append(data)
        return res

    def __iter__(self) -> Iterator[bytes]:
        for i in range(len(self)):
            yield self.read([i])[0]


def shard_files(
    files: Sequence[str], shard_index: int, num_shards: int
) -> List[str]:
    """Deterministic per-host file assignment: round-robin over the
    SORTED file list (every host computes the same division from the
    same inputs — no coordination). Shards are disjoint and cover the
    list. Fails loudly when a host would get zero files: silent empty
    input starves that host's data-parallel shard."""
    if not 0 <= shard_index < num_shards:
        raise ValueError(f"shard_index {shard_index} not in [0, {num_shards})")
    ordered = sorted(files)
    if len(ordered) < num_shards:
        raise ValueError(
            f"{len(ordered)} record files cannot feed {num_shards} hosts — "
            "write at least one file per host"
        )
    return ordered[shard_index::num_shards]
