// Native image-decode core: JPEG bytes -> HWC RGB via the system
// libjpeg (libjpeg-turbo on this image), exposed over a plain C ABI for
// ctypes — the sibling of recordio.cc for the image data plane.
//
// Three layers, cheapest-sufficient wins:
//
//   img_info           header geometry, no IDCT
//   img_decode[_scaled] full frame -> HWC uint8 RGB, optional DCT-domain
//                      scaling (decode at scale_num/8 — a 2048px source
//                      bound for a 224px crop decodes at 1/8 IDCT cost)
//   img_decode_rrc     the training hot path, fused: scaled decode ->
//                      crop -> bilinear resize to target -> optional
//                      hflip -> per-channel affine (normalize) written
//                      STRAIGHT into the caller's float32 batch slot —
//                      no intermediate PIL object, no per-image array,
//                      no stack copy (the tf.data/DALI fused-decode
//                      shape)
//
// The crop box arrives in FULL-RESOLUTION coordinates (the Python side
// draws it from header-stamped geometry, so crop parameters — and the
// seeded rng stream — stay backend-independent) and is mapped onto the
// scaled frame here. scale_num is chosen by the caller; the pipeline
// restricts itself to {1, 2, 4, 8} because libjpeg-turbo has SIMD IDCT
// only at those scales — a 6/8 "cheaper" decode measures SLOWER than a
// full-scale SIMD decode.
//
// The Python binder (tfk8s_tpu/data/images/_native_decode.py)
// lazy-builds this with `g++ ... -ljpeg` and falls back to PIL when the
// toolchain or jpeglib.h is absent; every capability keeps both paths
// and the tests assert they agree (exact pixels for PNG-through-PIL,
// bounded tolerance for JPEG — IDCT implementations legitimately
// differ).
//
// Error discipline: libjpeg's default error handler calls exit(); a
// corrupt record must instead surface as a negative return the binder
// can turn into a per-image PIL retry or a typed decode error.

#include <csetjmp>
#include <cstdint>
#include <cstdlib>
#include <cstring>

#include <cstdio>  // jpeglib.h needs FILE declared before inclusion
extern "C" {
#include <jpeglib.h>
}

namespace {

struct ErrorTrap {
  jpeg_error_mgr mgr;
  jmp_buf env;
};

void on_error(j_common_ptr cinfo) {
  // longjmp out instead of the library's exit(); the message is not
  // propagated — the binder retries the image through PIL, whose error
  // text names the corruption for the operator
  longjmp(reinterpret_cast<ErrorTrap*>(cinfo->err)->env, 1);
}

void on_message(j_common_ptr, int) {}  // swallow warnings (stderr spam)

constexpr int64_t kBadArgs = -1;      // null/empty input or bad scale/box
constexpr int64_t kBadImage = -2;     // libjpeg rejected the bytes
constexpr int64_t kShortBuffer = -3;  // out smaller than the decoded frame

// Shared decode body: header read + DCT scaling + RGB rows into `out`.
// Writes the SCALED frame dims to out_h/out_w and (when non-null) the
// full-resolution dims to full_h/full_w. `max_rows >= 0` stops after
// that many scanlines (the fused crop path never IDCTs rows below its
// crop bottom); the frame is aborted, not finished, when cut short.
int64_t decode_impl(const uint8_t* data, int64_t n, int64_t scale_num,
                    uint8_t* out, int64_t cap, int64_t* out_h,
                    int64_t* out_w, int64_t* full_h = nullptr,
                    int64_t* full_w = nullptr, int64_t max_rows = -1) {
  if (!data || n <= 0 || !out || scale_num < 1 || scale_num > 8)
    return kBadArgs;
  jpeg_decompress_struct cinfo;
  ErrorTrap trap;
  cinfo.err = jpeg_std_error(&trap.mgr);
  trap.mgr.error_exit = on_error;
  trap.mgr.emit_message = on_message;
  if (setjmp(trap.env)) {
    jpeg_destroy_decompress(&cinfo);
    return kBadImage;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(data),
               (unsigned long)n);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return kBadImage;
  }
  cinfo.scale_num = (unsigned)scale_num;
  cinfo.scale_denom = 8;
  // RGB out regardless of source space (grayscale/YCbCr convert in the
  // library; CMYK errors out -> the binder's PIL retry handles it)
  cinfo.out_color_space = JCS_RGB;
  jpeg_calc_output_dimensions(&cinfo);
  const int64_t h = cinfo.output_height, w = cinfo.output_width;
  if (h * w * 3 > cap) {
    jpeg_destroy_decompress(&cinfo);
    return kShortBuffer;
  }
  jpeg_start_decompress(&cinfo);
  const int64_t stride = (int64_t)cinfo.output_width *
                         cinfo.output_components;  // 3 after JCS_RGB
  const int64_t stop =
      (max_rows >= 0 && max_rows < h) ? max_rows : h;
  while ((int64_t)cinfo.output_scanline < stop) {
    JSAMPROW row = out + (int64_t)cinfo.output_scanline * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  if (out_h) *out_h = h;
  if (out_w) *out_w = w;
  if (full_h) *full_h = cinfo.image_height;
  if (full_w) *full_w = cinfo.image_width;
  if (stop < h)
    jpeg_abort_decompress(&cinfo);  // cut short: abort, don't finish
  else
    jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

double clampd(double v, double lo, double hi) {
  return v < lo ? lo : (v > hi ? hi : v);
}

// Grow-on-demand per-thread workspace: the fused path's resample taps
// and row strip live here, so a steady-state decode worker allocates
// NOTHING per image (matching the Python side's thread-local scratch
// frame). Freed at thread exit by the destructor.
struct ThreadBuf {
  void* p = nullptr;
  size_t cap = 0;
  ~ThreadBuf() { free(p); }
  void* get(size_t n) {
    if (cap < n) {
      free(p);
      p = malloc(n);
      cap = p ? n : 0;
    }
    return p;
  }
};

thread_local ThreadBuf tl_taps;   // x0/x1 indices + wx weights
thread_local ThreadBuf tl_strip;  // one vertically-blended source row

}  // namespace

extern "C" {

// Header-only geometry (no IDCT): 0 on success, writes (h, w, comps) of
// the FULL-SCALE image. comps is the source component count (1 gray,
// 3 color) — the decode functions always emit 3-channel RGB.
int64_t img_info(const uint8_t* data, int64_t n, int64_t* h, int64_t* w,
                 int64_t* comps) {
  if (!data || n <= 0) return kBadArgs;
  jpeg_decompress_struct cinfo;
  ErrorTrap trap;
  cinfo.err = jpeg_std_error(&trap.mgr);
  trap.mgr.error_exit = on_error;
  trap.mgr.emit_message = on_message;
  if (setjmp(trap.env)) {
    jpeg_destroy_decompress(&cinfo);
    return kBadImage;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(data),
               (unsigned long)n);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return kBadImage;
  }
  if (h) *h = cinfo.image_height;
  if (w) *w = cinfo.image_width;
  if (comps) *comps = cinfo.num_components;
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Full-scale decode: JPEG bytes -> HWC uint8 RGB into `out` (`cap`
// bytes, >= h*w*3). Writes the decoded (h, w). Returns 0, or -1 bad
// args / -2 undecodable / -3 short buffer.
int64_t img_decode(const uint8_t* data, int64_t n, uint8_t* out,
                   int64_t cap, int64_t* out_h, int64_t* out_w) {
  return decode_impl(data, n, 8, out, cap, out_h, out_w);
}

// DCT-scaled decode at scale_num/8 (scale_num in 1..8): the output
// frame is ceil(dim * scale_num / 8) per side — the caller picks the
// largest downscale whose frame still covers its crop/resize target
// and skips the rest of the IDCT work. Same contract as img_decode.
int64_t img_decode_scaled(const uint8_t* data, int64_t n,
                          int64_t scale_num, uint8_t* out, int64_t cap,
                          int64_t* out_h, int64_t* out_w) {
  return decode_impl(data, n, scale_num, out, cap, out_h, out_w);
}

// The fused training path: decode at scale_num/8 into `scratch`
// (caller-owned, reused across calls; sized >= the scaled frame or -3
// comes back), map the full-resolution crop box (top, left, crop_h,
// crop_w) onto the scaled frame, bilinear-resize it to target x target,
// optionally mirror horizontally, and write float32
// `pix * chan_scale[c] + chan_bias[c]` into `out` (target*target*3
// floats, HWC) — one call per image, zero intermediate buffers beyond
// the scratch frame. Identity scale/bias (1, 0) yields raw 0..255
// float pixels (the do_normalize=False contract).
//
// (full_h, full_w) is the caller's full-resolution geometry (the
// record's header stamp — already in hand, so the hot path does not
// pay a second header parse); the decode verifies it against the real
// frame and returns kBadImage on a lying stamp.
int64_t img_decode_rrc(const uint8_t* data, int64_t n, int64_t top,
                       int64_t left, int64_t crop_h, int64_t crop_w,
                       int64_t full_h, int64_t full_w,
                       int64_t target, int32_t flip, int64_t scale_num,
                       const float* chan_scale, const float* chan_bias,
                       uint8_t* scratch, int64_t scratch_cap,
                       float* out) {
  if (!out || !chan_scale || !chan_bias || target < 1 || crop_h < 1 ||
      crop_w < 1 || top < 0 || left < 0 || full_h < 1 || full_w < 1)
    return kBadArgs;
  // scaled-frame geometry from the caller's stamp: dims are
  // jdiv_round_up(dim * scale_num / 8), so the crop bottom row — the
  // last scanline the decode has to produce — is known up front
  const int64_t fh = full_h, fw = full_w;
  if (top + crop_h > fh || left + crop_w > fw) return kBadArgs;
  const int64_t sh = (fh * scale_num + 7) / 8;
  const int64_t sw = (fw * scale_num + 7) / 8;
  if (sh * sw * 3 > scratch_cap) return kShortBuffer;
  // map the box onto the scaled frame by the ACTUAL ratio (ceil'd dims,
  // so sh/fh is not exactly scale_num/8)
  const double ry = (double)sh / (double)fh;
  const double rx = (double)sw / (double)fw;
  const double ctop = (double)top * ry, cleft = (double)left * rx;
  // >= 1 px even for degenerate boxes on tiny scaled frames
  const double ch = clampd((double)crop_h * ry, 1.0, (double)sh);
  const double cw = clampd((double)crop_w * rx, 1.0, (double)sw);
  // decode through the crop bottom PLUS the resample filter's support
  // (ch/target rows when downscaling) — the support-scaled taps below
  // the box must see real pixels
  const int64_t last_row = (int64_t)clampd(
      ctop + ch + ch / (double)target + 1.0, 1.0, (double)sh);

  int64_t dh = 0, dw = 0;
  int64_t rc = decode_impl(data, n, scale_num, scratch, scratch_cap, &dh,
                           &dw, nullptr, nullptr, /*max_rows=*/last_row);
  if (rc != 0) return rc;
  if (dh != sh || dw != sw) return kBadImage;  // the stamp lied

  // separable, SUPPORT-SCALED bilinear (PIL's BILINEAR): on downscale
  // the triangle filter widens by the scale factor, so every source
  // pixel in the footprint contributes — a plain 2-tap bilinear
  // point-samples and ALIASES at factors > ~1.5x (measured mean
  // |native-PIL| 0.23 normalized units on a 1.56x downscale; with
  // support scaling both backends agree to IDCT tolerance). Upscale
  // keeps support 1 — identical to classic bilinear. Per output row
  // the row taps blend VERTICALLY into a contiguous float strip
  // (sequential uint8 loads — the loop the compiler vectorizes), then
  // the column taps sample that strip.
  const float s0 = chan_scale[0], s1 = chan_scale[1], s2 = chan_scale[2];
  const float b0 = chan_bias[0], b1 = chan_bias[1], b2 = chan_bias[2];
  const double xscale = cw / (double)target > 1.0 ? cw / (double)target : 1.0;
  const double yscale = ch / (double)target > 1.0 ? ch / (double)target : 1.0;
  // max taps per output pixel on each axis (PIL: ceil(support*2) + 1)
  const int64_t xk = (int64_t)(xscale * 2.0) + 2;
  const int64_t yk = (int64_t)(yscale * 2.0) + 2;
  // workspace: per-column (start, count) + weights, plus per-row
  // weights (computed per output row, reused across the strip)
  uint8_t* taps = (uint8_t*)tl_taps.get(
      2 * target * sizeof(int64_t) + target * xk * sizeof(float) +
      yk * sizeof(float));
  if (!taps) return kBadArgs;
  int64_t* xmin = (int64_t*)taps;
  int64_t* xcnt = xmin + target;
  float* xw = (float*)(xcnt + target);
  float* yw = xw + target * xk;

  // triangle-filter coefficients for one output position (PIL's
  // precompute_coeffs, filter support 1.0 scaled by `scale`): source
  // taps [lo, lo+cnt) with normalized weights into w[]
  auto coeffs = [](double center, double scale, int64_t limit, float* w,
                   int64_t kmax, int64_t* lo_out) -> int64_t {
    const double support = scale;  // bilinear support = 1.0, scaled
    int64_t lo = (int64_t)(center - support + 0.5);
    if (lo < 0) lo = 0;
    int64_t hi = (int64_t)(center + support + 0.5);
    if (hi > limit) hi = limit;
    int64_t cnt = hi - lo;
    if (cnt < 1) {  // degenerate: nearest source pixel
      lo = (int64_t)clampd(center, 0.0, (double)(limit - 1));
      cnt = 1;
    }
    if (cnt > kmax) cnt = kmax;
    double total = 0.0;
    for (int64_t i = 0; i < cnt; ++i) {
      double t = ((double)(lo + i) + 0.5 - center) / scale;
      double v = t < 0 ? 1.0 + t : 1.0 - t;  // triangle(t), |t| <= 1
      if (v < 0) v = 0;
      w[i] = (float)v;
      total += v;
    }
    if (total > 0)
      for (int64_t i = 0; i < cnt; ++i) w[i] = (float)(w[i] / total);
    *lo_out = lo;
    return cnt;
  };

  for (int64_t x = 0; x < target; ++x) {
    const double center = cleft + ((double)x + 0.5) * cw / (double)target;
    xcnt[x] = coeffs(center, xscale, sw, xw + x * xk, xk, &xmin[x]);
  }
  const int64_t xlo = xmin[0];
  const int64_t xhi = xmin[target - 1] + xcnt[target - 1];  // exclusive
  const int64_t span = (xhi - xlo) * 3;
  float* strip = (float*)tl_strip.get(span * sizeof(float));
  if (!strip) return kBadArgs;

  for (int64_t y = 0; y < target; ++y) {
    const double center = ctop + ((double)y + 0.5) * ch / (double)target;
    int64_t ylo = 0;
    int64_t ycnt = coeffs(center, yscale, last_row, yw, yk, &ylo);
    // vertical pass: weighted blend of the row taps into the strip
    {
      const uint8_t* r = scratch + (ylo * sw + xlo) * 3;
      const float w = yw[0];
      for (int64_t i = 0; i < span; ++i) strip[i] = w * (float)r[i];
    }
    for (int64_t t = 1; t < ycnt; ++t) {
      const uint8_t* r = scratch + ((ylo + t) * sw + xlo) * 3;
      const float w = yw[t];
      for (int64_t i = 0; i < span; ++i) strip[i] += w * (float)r[i];
    }
    // horizontal pass: per-column taps over the blended strip
    float* orow = out + y * target * 3;
    for (int64_t x = 0; x < target; ++x) {
      const float* w = xw + x * xk;
      const float* src = strip + (xmin[x] - xlo) * 3;
      float acc0 = 0, acc1 = 0, acc2 = 0;
      for (int64_t t = 0; t < xcnt[x]; ++t) {
        acc0 += w[t] * src[t * 3];
        acc1 += w[t] * src[t * 3 + 1];
        acc2 += w[t] * src[t * 3 + 2];
      }
      float* o = orow + (flip ? (target - 1 - x) : x) * 3;
      o[0] = acc0 * s0 + b0;
      o[1] = acc1 * s1 + b1;
      o[2] = acc2 * s2 + b2;
    }
  }
  return 0;
}

}  // extern "C"
