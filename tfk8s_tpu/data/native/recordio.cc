// Native record-IO core: TFRecord-framed sequential files with crc32c
// (Castagnoli) integrity, exposed over a plain C ABI for ctypes.
//
// Framing (TFRecord wire format — the reference ecosystem's on-disk
// training-data container, k8s-operator.md:6's per-task input files):
//
//   uint64le  data_length
//   uint32le  masked_crc32c(data_length bytes)
//   bytes     data[data_length]
//   uint32le  masked_crc32c(data)
//
// masked_crc(c) = ((c >> 15) | (c << 17)) + 0xa282ead8  (mod 2^32)
//
// The hot path a Python loop can't serve: indexing a multi-GB shard
// (sequential scan, header-CRC verified) and bulk record reads with
// data-CRC verification — both single-pass, zero Python per record.
// The Python side (tfk8s_tpu/data/recordio.py) carries a pure-Python
// fallback with identical semantics for rigs without a toolchain.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

// crc32c, reflected polynomial 0x82F63B78, byte-at-a-time table.
uint32_t kTable[256];
bool table_init = [] {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k)
      c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
    kTable[i] = c;
  }
  return true;
}();

uint32_t crc32c_sw(const uint8_t* p, size_t n, uint32_t crc) {
  crc = ~crc;
  for (size_t i = 0; i < n; ++i)
    crc = kTable[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

#if defined(__x86_64__)
// The SSE4.2 crc32 instruction computes exactly CRC-32C (Castagnoli) —
// 8 bytes per instruction vs 1 byte per table lookup (~10x). Compiled
// with a per-function target attribute and dispatched at runtime so the
// shared object still loads on pre-SSE4.2 CPUs.
__attribute__((target("sse4.2")))
uint32_t crc32c_hw(const uint8_t* p, size_t n, uint32_t crc) {
  uint64_t c = ~crc;
  while (n >= 8) {
    uint64_t v;
    __builtin_memcpy(&v, p, 8);  // unaligned-safe
    c = __builtin_ia32_crc32di(c, v);
    p += 8;
    n -= 8;
  }
  uint32_t c32 = (uint32_t)c;
  while (n--) c32 = __builtin_ia32_crc32qi(c32, *p++);
  return ~c32;
}

const bool kHaveHwCrc = [] {
  __builtin_cpu_init();
  return (bool)__builtin_cpu_supports("sse4.2");
}();
#else
const bool kHaveHwCrc = false;
uint32_t crc32c_hw(const uint8_t* p, size_t n, uint32_t crc) {
  return crc32c_sw(p, n, crc);
}
#endif

uint32_t crc32c(const uint8_t* p, size_t n, uint32_t crc = 0) {
  return kHaveHwCrc ? crc32c_hw(p, n, crc) : crc32c_sw(p, n, crc);
}

uint32_t masked(uint32_t c) {
  return ((c >> 15) | (c << 17)) + 0xa282ead8u;
}

uint64_t le64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 7; i >= 0; --i) v = (v << 8) | p[i];
  return v;
}

uint32_t le32(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

void put_le(uint8_t* p, uint64_t v, int n) {
  for (int i = 0; i < n; ++i) p[i] = (uint8_t)(v >> (8 * i));
}

}  // namespace

extern "C" {

// Exposed so the Python writer can use the fast CRC when native is up.
uint32_t rio_crc32c(const uint8_t* data, int64_t n) {
  return crc32c(data, (size_t)n);
}

uint32_t rio_masked_crc32c(const uint8_t* data, int64_t n) {
  return masked(crc32c(data, (size_t)n));
}

// Scan a record file, verifying every header CRC. On success returns the
// record count and malloc'd arrays (caller frees via rio_free) of each
// record's DATA offset and length. Negative return = error:
//   -1 open failed, -2 truncated frame, -3 header CRC mismatch,
//   -5 out of memory growing the index.
int64_t rio_index(const char* path, int64_t** offsets, int64_t** lengths) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  // file size up front: fseek past EOF SUCCEEDS (and ftell then reports
  // the past-EOF position), so truncation must be checked against the
  // real size, not the stream position
#if defined(_WIN32)
  _fseeki64(f, 0, SEEK_END);
  const int64_t fsize = _ftelli64(f);
  _fseeki64(f, 0, SEEK_SET);
#else
  fseeko(f, 0, SEEK_END);
  const int64_t fsize = (int64_t)ftello(f);
  fseeko(f, 0, SEEK_SET);
#endif
  int64_t cap = 1024, n = 0;
  int64_t* offs = (int64_t*)malloc(cap * sizeof(int64_t));
  int64_t* lens = (int64_t*)malloc(cap * sizeof(int64_t));
  if (!offs || !lens) {
    free(offs);
    free(lens);
    fclose(f);
    return -5;
  }
  uint8_t hdr[12];
  int64_t rc = 0;
  for (;;) {
    size_t got = fread(hdr, 1, 12, f);
    if (got == 0) break;  // clean EOF
    if (got != 12) { rc = -2; break; }
    uint64_t len = le64(hdr);
    if (masked(crc32c(hdr, 8)) != le32(hdr + 8)) { rc = -3; break; }
    int64_t off;
#if defined(_WIN32)
    off = _ftelli64(f);
#else
    off = ftello(f);
#endif
    if (off + (int64_t)len + 4 > fsize) { rc = -2; break; }  // truncated body
    if (n == cap) {
      cap *= 2;
      // checked growth: a failed realloc returns NULL and LEAVES the old
      // block valid — assigning unchecked would both leak it and crash on
      // the next store
      int64_t* no = (int64_t*)realloc(offs, cap * sizeof(int64_t));
      if (!no) { rc = -5; break; }
      offs = no;
      int64_t* nl = (int64_t*)realloc(lens, cap * sizeof(int64_t));
      if (!nl) { rc = -5; break; }
      lens = nl;
    }
    offs[n] = off;
    lens[n] = (int64_t)len;
    ++n;
    // skip data + its 4-byte CRC without reading it (index is O(records))
#if defined(_WIN32)
    if (_fseeki64(f, (int64_t)len + 4, SEEK_CUR) != 0) { rc = -2; break; }
#else
    if (fseeko(f, (off_t)len + 4, SEEK_CUR) != 0) { rc = -2; break; }
#endif
  }
  fclose(f);
  if (rc < 0) {
    free(offs);
    free(lens);
    return rc;
  }
  *offsets = offs;
  *lengths = lens;
  return n;
}

void rio_free(void* p) { free(p); }

// Read `count` records (data offsets/lengths from rio_index) into `out`,
// packed back to back; the caller sizes `out` as sum(lengths). Each
// record's trailing data CRC is verified when verify != 0. Returns 0 on
// success; -1 open, -2 short read, -4 data CRC mismatch at record i
// (encoded as -(4 + i*10)... keep simple: returns -4 and writes the
// failing record index into *bad_index when non-null).
int64_t rio_read(const char* path, int64_t count, const int64_t* offsets,
                 const int64_t* lengths, uint8_t* out, int verify,
                 int64_t* bad_index) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  uint8_t tail[4];
  uint8_t* dst = out;
  for (int64_t i = 0; i < count; ++i) {
#if defined(_WIN32)
    if (_fseeki64(f, offsets[i], SEEK_SET) != 0) { fclose(f); return -2; }
#else
    if (fseeko(f, (off_t)offsets[i], SEEK_SET) != 0) { fclose(f); return -2; }
#endif
    if (fread(dst, 1, (size_t)lengths[i], f) != (size_t)lengths[i]) {
      fclose(f);
      return -2;
    }
    if (verify) {
      if (fread(tail, 1, 4, f) != 4) { fclose(f); return -2; }
      if (masked(crc32c(dst, (size_t)lengths[i])) != le32(tail)) {
        if (bad_index) *bad_index = i;
        fclose(f);
        return -4;
      }
    }
    dst += lengths[i];
  }
  fclose(f);
  return 0;
}

// Append `count` records to `path` (created if absent) in TFRecord
// framing. Data is packed back to back in `data` with per-record
// `lengths`. Returns 0 or -1 (open) / -2 (short write).
int64_t rio_write(const char* path, int64_t count, const uint8_t* data,
                  const int64_t* lengths) {
  FILE* f = fopen(path, "ab");
  if (!f) return -1;
  uint8_t hdr[12], tail[4];
  const uint8_t* src = data;
  for (int64_t i = 0; i < count; ++i) {
    put_le(hdr, (uint64_t)lengths[i], 8);
    put_le(hdr + 8, masked(crc32c(hdr, 8)), 4);
    put_le(tail, masked(crc32c(src, (size_t)lengths[i])), 4);
    if (fwrite(hdr, 1, 12, f) != 12 ||
        fwrite(src, 1, (size_t)lengths[i], f) != (size_t)lengths[i] ||
        fwrite(tail, 1, 4, f) != 4) {
      fclose(f);
      return -2;
    }
    src += lengths[i];
  }
  if (fclose(f) != 0) return -2;
  return 0;
}

}  // extern "C"
