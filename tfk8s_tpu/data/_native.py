"""Lazy g++ build + ctypes binding for the native record-IO core
(``native/recordio.cc``).

The shared object is compiled on first use into a cache directory keyed
by the source hash (``$TFK8S_NATIVE_CACHE``, else
``~/.cache/tfk8s-tpu``), so rebuilds happen exactly when the source
changes and concurrent builders race benignly (atomic rename). Rigs
without a toolchain — or ``TFK8S_PURE_PY=1`` — fall back to the
pure-Python codec in ``recordio.py``; every capability has both paths
and the tests assert they agree."""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
import threading
from typing import Optional

log = logging.getLogger("tfk8s.data.native")

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "native", "recordio.cc")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

# TFK8S_NATIVE_SANITIZE=asan|ubsan builds the native cores with the
# matching sanitizer (separate cache key, so sanitized and plain .so
# files coexist). -O1 overrides the base -O3 for usable stack traces.
# NOTE an asan .so usually cannot be dlopen'd into an un-instrumented
# python without LD_PRELOAD=libasan.so — load() degrades to the pure
# fallback in that case (skip, not fail); tools/sanitize_smoke.py is
# the subprocess driver that sets the preload up properly.
_SANITIZE_FLAGS = {
    "asan": ("-fsanitize=address", "-fno-omit-frame-pointer", "-g", "-O1"),
    "ubsan": ("-fsanitize=undefined", "-fno-sanitize-recover=undefined",
              "-g", "-O1"),
}


def sanitize_mode() -> Optional[str]:
    """The validated TFK8S_NATIVE_SANITIZE value, or None. An unknown
    value warns once and is ignored rather than silently building an
    unsanitized core under a sanitizer-suggesting name."""
    mode = os.environ.get("TFK8S_NATIVE_SANITIZE", "").strip().lower()
    if not mode:
        return None
    if mode not in _SANITIZE_FLAGS:
        log.warning(
            "TFK8S_NATIVE_SANITIZE=%r is not one of %s; building plain",
            mode, "/".join(sorted(_SANITIZE_FLAGS)),
        )
        return None
    return mode


def _cache_dir() -> str:
    d = os.environ.get("TFK8S_NATIVE_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "tfk8s-tpu"
    )
    os.makedirs(d, exist_ok=True)
    return d


def build_cached(
    src_path: str,
    prefix: str,
    build_log: logging.Logger,
    what: str,
    fallback: str,
    extra_flags: tuple = (),
) -> Optional[str]:
    """The ONE hash-keyed lazy g++ build every native binder shares
    (recordio here, the image-decode core in
    ``data/images/_native_decode.py``): compile ``src_path`` into the
    cache as ``<prefix>-<srchash>.so`` and return its path, or None when
    the toolchain is absent (quiet — the caller logs the consequence on
    first use) or the build fails (loud, with the compiler's own words —
    the silent version of this class of failure cost 120x input
    bandwidth with empty logs). ``what``/``fallback`` name the core and
    its degraded path in the warnings."""
    src = open(src_path, "rb").read()
    tag = hashlib.sha256(src).hexdigest()[:16]
    mode = sanitize_mode()
    sanitize_flags: tuple = ()
    if mode is not None:
        prefix = f"{prefix}-{mode}"
        sanitize_flags = _SANITIZE_FLAGS[mode]
    out = os.path.join(_cache_dir(), f"{prefix}-{tag}.so")
    if os.path.exists(out):
        return out
    # build to a temp name, rename into place: concurrent processes
    # (pytest-xdist, multi-host launch on a shared home) each build their
    # own temp and the last rename wins with identical bytes
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_cache_dir())
    os.close(fd)
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", src_path,
        "-o", tmp, *sanitize_flags, *extra_flags,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
        return out
    except FileNotFoundError:
        # no toolchain at all — the legitimate quiet-fallback case
        # (laptops, minimal containers)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    except subprocess.CalledProcessError as e:
        # a PRESENT g++ that fails is a broken build, not a missing
        # toolchain
        build_log.warning(
            "native %s build FAILED (g++ rc=%s); falling back to %s. "
            "stderr:\n%s",
            what, e.returncode, fallback,
            (e.stderr or b"").decode(errors="replace")[-2000:],
        )
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    except (subprocess.SubprocessError, OSError) as e:
        build_log.warning(
            "native %s build errored (%s: %s); falling back to %s",
            what, type(e).__name__, e, fallback,
        )
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def dlopen_checked(
    path: str, build_log: logging.Logger, what: str, fallback: str
) -> Optional[ctypes.CDLL]:
    """ctypes.CDLL with the OSError path downgraded to a warning + None
    (fallback), shared by both native binders. The common way to get
    here: a sanitized .so whose runtime (libasan) is not preloaded into
    this process — a configuration to degrade from, not to crash on."""
    try:
        return ctypes.CDLL(path)
    except OSError as e:
        build_log.warning(
            "native %s built but failed to load (%s); falling back to %s",
            what, e, fallback,
        )
        return None


def _build() -> Optional[str]:
    return build_cached(
        _SRC, "recordio", log, "recordio core",
        "the pure-Python codec (~120x slower reads)",
    )


def load() -> Optional[ctypes.CDLL]:
    """The bound native library, or None (toolchain missing / disabled).
    Build + bind happen once per process; the result is latched."""
    global _lib, _tried
    if _tried:
        return _lib
    with _lock:
        if _tried:
            return _lib
        if os.environ.get("TFK8S_PURE_PY") == "1":
            _tried = True
            return None
        path = _build()
        if path is None:
            _tried = True
            return None
        lib = dlopen_checked(
            path, log, "recordio core",
            "the pure-Python codec (~120x slower reads)",
        )
        if lib is None:
            _tried = True
            return None
        i64, u32 = ctypes.c_int64, ctypes.c_uint32
        pi64 = ctypes.POINTER(i64)
        pu8 = ctypes.POINTER(ctypes.c_uint8)
        lib.rio_crc32c.restype = u32
        lib.rio_crc32c.argtypes = [ctypes.c_char_p, i64]
        lib.rio_masked_crc32c.restype = u32
        lib.rio_masked_crc32c.argtypes = [ctypes.c_char_p, i64]
        lib.rio_index.restype = i64
        lib.rio_index.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(pi64), ctypes.POINTER(pi64)
        ]
        lib.rio_free.restype = None
        lib.rio_free.argtypes = [ctypes.c_void_p]
        lib.rio_read.restype = i64
        lib.rio_read.argtypes = [
            ctypes.c_char_p, i64, pi64, pi64, pu8, ctypes.c_int, pi64
        ]
        lib.rio_write.restype = i64
        lib.rio_write.argtypes = [ctypes.c_char_p, i64, pu8, pi64]
        _lib = lib
        _tried = True
        return _lib
