"""File-backed input pipeline: TFRecord-framed shards (crc32c), a native
C++ reader core with a pure-Python fallback, per-host file sharding, and
a prefetching batched dataset (see ``recordio``, ``example``,
``dataset``)."""

from tfk8s_tpu.data.dataset import RecordDataset
from tfk8s_tpu.data.example import decode, encode
from tfk8s_tpu.data.recordio import (
    RecordFile,
    RecordIOError,
    RecordWriter,
    crc32c,
    masked_crc32c,
    shard_files,
)

__all__ = [
    "RecordDataset",
    "RecordFile",
    "RecordIOError",
    "RecordWriter",
    "crc32c",
    "decode",
    "encode",
    "masked_crc32c",
    "shard_files",
]
