"""RecordDataset: shard-assigned, shuffled, batched record input with
background prefetch — the framework's file-backed input pipeline.

Mirrors the reference ecosystem's per-task input division
(``/root/reference/k8s-operator.md:6``: each WORKER reads its own slice
of the input files): a host constructs the dataset with its
``(host_index, num_hosts)`` and, in the default file-sharded mode, reads
ONLY its round-robin share of the sorted shard list — host input
bandwidth and memory scale 1/hosts, the same property the synthetic
per-host path in ``runtime/train.py`` has. When the file list cannot
cover the hosts, ``shard_by="records"`` stripes the record sequence
instead (disjoint per host, but every host index-scans all files — the
1/hosts IO property applies to file mode only).

Epoch order is a seeded permutation over the host's records (seed folded
with the epoch number, so every epoch reshuffles deterministically and a
restarted host replays the identical stream). Decoding happens on a
background thread into a bounded queue, overlapping file IO + CRC +
decode with device compute — same discipline as ``fit``'s prefetcher.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from tfk8s_tpu.data import example as example_codec
from tfk8s_tpu.data.recordio import RecordFile, shard_files


class RecordDataset:
    def __init__(
        self,
        files: Sequence[str],
        batch_size: int,
        host_index: int = 0,
        num_hosts: int = 1,
        seed: int = 0,
        shuffle: bool = True,
        decode: Callable[[bytes], Dict[str, np.ndarray]] = example_codec.decode,
        drop_remainder: bool = True,
        verify_crc: bool = True,
        shard_by: str = "auto",
    ):
        """``shard_by`` controls the per-host input division:

        - ``"files"``: round-robin over the sorted file list (each host
          opens ONLY its share — host IO scales 1/hosts; needs at least
          one file per host);
        - ``"records"``: every host indexes all files but owns the
          record stripe ``host_index::num_hosts`` (any file count feeds
          any host count; the index pass touches every file per host);
        - ``"auto"`` (default): files when the list covers the hosts,
          records otherwise.
        """
        # dedupe up front: overlapping globs in the input spec must not
        # double-index records (which would overlap host stripes AND
        # double-weight the duplicated shard per epoch)
        unique = sorted(set(files))
        if shard_by == "auto":
            shard_by = "files" if len(unique) >= num_hosts else "records"
        if shard_by not in ("files", "records"):
            raise ValueError(f"unknown shard_by {shard_by!r}")
        self.shard_by = shard_by
        if shard_by == "files":
            self.files = shard_files(unique, host_index, num_hosts)
        else:
            if not 0 <= host_index < num_hosts:
                raise ValueError(
                    f"host_index {host_index} not in [0, {num_hosts})"
                )
            self.files = unique
        self.batch_size = batch_size
        self.seed = seed
        self.shuffle = shuffle
        self.decode = decode
        self.drop_remainder = drop_remainder
        self.verify_crc = verify_crc
        self.bytes_read = 0  # cumulative payload bytes (input-rate metric)
        self._shards = [RecordFile(p) for p in self.files]
        # global record addressing: (shard_idx, record_idx) pairs
        self._addr: List[Tuple[int, int]] = [
            (si, ri)
            for si, sh in enumerate(self._shards)
            for ri in range(len(sh))
        ]
        if shard_by == "records":
            # deterministic disjoint stripe per host over the full
            # record sequence (file order then record order)
            total = len(self._addr)
            self._addr = self._addr[host_index::num_hosts]
            if not self._addr:
                raise ValueError(
                    f"host {host_index}'s record stripe is empty: "
                    f"{total} records across {self.files} cannot feed "
                    f"{num_hosts} hosts"
                )
        if not self._addr:
            raise ValueError(f"no records in shard set {self.files}")
        if drop_remainder and len(self._addr) < batch_size:
            fix = (
                "write more records or use fewer hosts"
                if shard_by == "records"  # stripe size is total/hosts
                else "write more records or rebalance files across hosts"
            )
            raise ValueError(
                f"shard set {self.files} holds {len(self._addr)} records "
                f"for this host — fewer than one batch of {batch_size} "
                f"(drop_remainder) — {fix}"
            )

    def __len__(self) -> int:
        return len(self._addr)

    def _epoch_order(self, epoch: int) -> np.ndarray:
        idx = np.arange(len(self._addr))
        if self.shuffle:
            np.random.default_rng(
                np.random.SeedSequence([self.seed, epoch])
            ).shuffle(idx)
        return idx

    def batches_per_epoch(self) -> int:
        n = len(self._addr)
        if self.drop_remainder:
            return n // self.batch_size
        return -(-n // self.batch_size)

    def batches(self, epoch: int, start_batch: int = 0):
        """Yield stacked host batches for one epoch, in the seeded order,
        starting at batch ``start_batch`` (skipped batches are index
        arithmetic — no file reads). Reads are grouped per shard file
        within each batch (one native bulk read per file touched)."""
        order = self._epoch_order(epoch)
        n = len(order)
        stop = n - (n % self.batch_size) if self.drop_remainder else n
        for lo in range(start_batch * self.batch_size, stop, self.batch_size):
            take = order[lo : lo + self.batch_size]
            yield self._load(take, epoch)

    def _load(self, take: np.ndarray, epoch: int = 0) -> Dict[str, np.ndarray]:
        # group indices by shard, bulk-read each, then restore batch order
        by_shard: Dict[int, List[int]] = {}
        slots: List[Tuple[int, int]] = []  # (shard, position-in-group)
        for g in take:
            si, ri = self._addr[int(g)]
            grp = by_shard.setdefault(si, [])
            slots.append((si, len(grp)))
            grp.append(ri)
        raw: Dict[int, List[bytes]] = {
            si: self._shards[si].read(ris, verify=self.verify_crc)
            for si, ris in by_shard.items()
        }
        # input-bandwidth accounting: consumers (the trainer's windowed
        # progress report) difference this to surface read MB/s — an
        # operator alert can then SEE input starvation (e.g. the ~120x
        # pure-Python codec fallback) instead of inferring it from step
        # time
        self.bytes_read += sum(sum(len(r) for r in rs) for rs in raw.values())
        examples = self._decode_records(
            [raw[si][pos] for si, pos in slots],
            [int(g) for g in take],
            epoch,
        )
        if isinstance(examples, dict):
            # the decode stage assembled the batch itself (images
            # pipeline: workers write a preallocated [B, ...] batch in
            # place — stacking again would re-copy the whole batch)
            return examples
        keys = examples[0].keys()
        for ex in examples[1:]:
            if ex.keys() != keys:
                raise ValueError(
                    f"inconsistent example keys: {sorted(keys)} vs "
                    f"{sorted(ex.keys())}"
                )
        out = {}
        for k in keys:
            vals = [ex[k] for ex in examples]
            shapes = {np.shape(v) for v in vals}
            if len(shapes) > 1:
                hint = (
                    " — these look like IMAGE records (data/images); set "
                    "input_format='image' (TFK8S_INPUT_FORMAT=image) so "
                    "they decode instead of batching raw bytes"
                    if k.startswith("image/")
                    else ""
                )
                raise ValueError(
                    f"records disagree on {k!r} shape ({sorted(shapes)[:4]}"
                    f"...): ragged examples cannot stack into a batch{hint}"
                )
            out[k] = np.stack(vals)
        return out

    def _decode_records(
        self, records: List[bytes], record_ids: List[int], epoch: int
    ) -> List[Dict[str, np.ndarray]]:
        """Record payloads -> example dicts, in batch order. The decode
        STAGE of the pipeline, overridable by datasets whose decode is
        expensive enough to parallelize (images.ImageDataset runs this
        over a worker pool). ``record_ids`` are the dataset-global
        record indices and ``epoch`` the shuffle epoch — together the
        position-independent identity a subclass needs to seed
        per-record augmentation deterministically across resume.

        A subclass may instead return the ASSEMBLED batch (a dict of
        stacked arrays) and ``_load`` passes it through untouched —
        the preallocated-batch fast path (one less full-batch copy)."""
        return [self.decode(r) for r in records]

    def close(self) -> None:
        """Release any decode resources (worker pools). The base
        dataset holds none — a no-op so every consumer can close
        unconditionally."""

    def iterator(self, prefetch: int = 2, start_batch: int = 0):
        """An endless batch iterator cycling epochs. ``prefetch > 0``
        runs a background producer thread keeping that many decoded
        batches staged; ``prefetch=0`` is synchronous (for consumers
        that bring their own overlap). ``start_batch`` fast-forwards to
        that global batch index (epoch = index // batches_per_epoch)
        without reading the skipped records — checkpoint resume lands on
        the exact batch the restarted step would have seen. ``.close()``
        it (or let it be GC'd) to stop any producer."""
        if prefetch <= 0:
            return _SyncIterator(self, start_batch)
        return _PrefetchIterator(self, prefetch, start_batch)

    def as_batch_fn(self, prefetch: int = 0):
        """Adapter to ``TrainTask.make_batch(np_rng, batch_size)``: the
        rng argument is ignored — order comes from the dataset's own
        seeded epoch permutation (restart-reproducible, unlike consuming
        a shared rng whose position depends on call history).

        Default is the SYNCHRONOUS iterator: ``Trainer.fit`` already
        wraps ``make_batch`` in its background ``_BatchPrefetcher``
        (runtime/train.py), and stacking a second producer thread under
        it would double-buffer the same batches and leak a thread after
        fit returns. Pass ``prefetch>0`` only for consumers with no
        prefetcher of their own."""
        it = self.iterator(prefetch)

        def make_batch(_rng, batch_size: int) -> Dict[str, np.ndarray]:
            if batch_size != self.batch_size:
                raise ValueError(
                    f"dataset built for batch_size={self.batch_size}, "
                    f"asked for {batch_size}"
                )
            return next(it)

        make_batch.close = it.close  # type: ignore[attr-defined]
        return make_batch


class _SyncIterator:
    """Endless epoch-cycling batch iterator, no threads."""

    def __init__(self, ds: RecordDataset, start_batch: int = 0):
        self._ds = ds
        bpe = ds.batches_per_epoch()
        self._epoch = start_batch // bpe
        self._gen = ds.batches(self._epoch, start_batch % bpe)
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        try:
            return next(self._gen)
        except StopIteration:
            self._epoch += 1
            self._gen = self._ds.batches(self._epoch)
            return next(self._gen)

    def close(self) -> None:
        self._closed = True


class _PrefetchIterator:
    def __init__(self, ds: RecordDataset, prefetch: int, start_batch: int = 0):
        self._ds = ds
        self._start_batch = start_batch
        self._q: "queue.Queue" = queue.Queue(maxsize=max(prefetch, 1))
        self._stop = threading.Event()
        self._exc: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._produce, name="record-prefetch", daemon=True
        )
        self._thread.start()

    def _produce(self) -> None:
        bpe = self._ds.batches_per_epoch()
        epoch, within = self._start_batch // bpe, self._start_batch % bpe
        try:
            while not self._stop.is_set():
                for batch in self._ds.batches(epoch, within):
                    while not self._stop.is_set():
                        try:
                            self._q.put(batch, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if self._stop.is_set():
                        return
                epoch += 1
                within = 0
        except BaseException as exc:  # surface IO/decode errors to consumer
            self._exc = exc
            self._stop.set()

    def __iter__(self):
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        while True:
            try:
                return self._q.get(timeout=0.1)
            except queue.Empty:
                if self._exc is not None:
                    raise self._exc
                if self._stop.is_set():
                    raise StopIteration
                if not self._thread.is_alive():
                    raise RuntimeError("record-prefetch thread died silently")

    def close(self) -> None:
        self._stop.set()

    def __del__(self):  # best-effort producer shutdown
        self._stop.set()
