"""Self-contained byte-level BPE tokenizer (GPT-2 style).

Closes the text loop around the serving stack (VERDICT r4 next #10): the
HF interop imports GPT-2 *weights* (models/gpt.py), but turning text into
the record shards the input pipeline feeds (data/recordio.py) — and
decoded ids back into text — needed a tokenizer. This one is hermetic:

- **byte-level**: text is mapped through the GPT-2 byte→unicode table
  (a format constant: the 256 byte values relabelled onto printable
  code points so merges files stay visually editable), so ANY input
  round-trips losslessly — no unknown-token loss;
- **trainable**: :func:`train_bpe` learns a merge list from a corpus
  (classic pair-frequency BPE over pre-tokenized words), so the loop
  works with zero downloads;
- **HF-format vocab**: ``save``/``load`` write ``vocab.json`` +
  ``merges.txt`` in the layout Hugging Face tokenizers use, so a real
  GPT-2 vocabulary dropped into the same directory loads unchanged
  (pairing with ``gpt.load_hf_gpt2`` weights).

Pre-tokenization approximates GPT-2's regex with stdlib ``re`` (the
original uses ``\\p{L}``/``\\p{N}`` classes from the third-party
``regex`` module): contractions, letter runs, digit runs, punctuation
runs, and space-prefixed words. For tokenizers TRAINED here the choice
is self-consistent; byte-level fallback keeps encode total either way.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, Iterable, List, Sequence, Tuple

# stdlib-re approximation of the GPT-2 split pattern
_PRETOKEN = re.compile(
    r"'s|'t|'re|'ve|'m|'ll|'d"
    r"| ?[A-Za-zÀ-ɏ]+"
    r"| ?[0-9]+"
    r"| ?[^\sA-Za-z0-9À-ɏ]+"
    r"|\s+(?!\S)|\s+"
)


def bytes_to_unicode() -> Dict[int, str]:
    """The GPT-2 byte→printable-unicode relabelling (format constant):
    printable ASCII and two Latin-1 ranges map to themselves; the
    remaining 68 byte values map to 256+n."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("¡"), ord("¬") + 1))
        + list(range(ord("®"), ord("ÿ") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


_BYTE_ENC = bytes_to_unicode()
_BYTE_DEC = {v: k for k, v in _BYTE_ENC.items()}


def _to_byte_chars(text: str) -> str:
    return "".join(_BYTE_ENC[b] for b in text.encode("utf-8"))


class VocabMismatchError(KeyError):
    """A merge-produced piece has no vocab id — vocab.json and
    merges.txt are from different tokenizers. Subclasses KeyError so
    pre-existing callers catching the bare KeyError keep working."""

    def __str__(self) -> str:  # KeyError repr()s its arg; keep the prose
        return self.args[0] if self.args else ""


def _apply_merge(symbols: Sequence[str], pair: Tuple[str, str]) -> List[str]:
    """One left-to-right pass replacing adjacent ``pair`` occurrences with
    their concatenation — the ONE merge-application used by both encoding
    (_bpe) and training (train_bpe), so their semantics cannot drift."""
    merged: List[str] = []
    i = 0
    while i < len(symbols):
        if i < len(symbols) - 1 and (symbols[i], symbols[i + 1]) == pair:
            merged.append(symbols[i] + symbols[i + 1])
            i += 2
        else:
            merged.append(symbols[i])
            i += 1
    return merged


class BPETokenizer:
    """Encode/decode with a (vocab, merges) pair. ``vocab`` maps token
    string (in byte-char space) → id; ``merges`` is the ordered merge
    list, earlier = higher priority."""

    def __init__(
        self,
        vocab: Dict[str, int],
        merges: Sequence[Tuple[str, str]],
        specials: Sequence[str] = (),
    ):
        self.vocab = dict(vocab)
        self.inv_vocab = {i: t for t, i in self.vocab.items()}
        self.ranks = {tuple(m): i for i, m in enumerate(merges)}
        self.merges = [tuple(m) for m in merges]
        self.specials = list(specials)
        # longest-first alternation so overlapping specials resolve to
        # the longest match (HF AddedToken behavior); None when there
        # are no specials to split out
        self._special_re = (
            re.compile(
                "|".join(
                    re.escape(s)
                    for s in sorted(self.specials, key=len, reverse=True)
                )
            )
            if self.specials
            else None
        )
        self._cache: Dict[str, List[str]] = {}

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    def _bpe(self, word: str) -> List[str]:
        if word in self._cache:
            return self._cache[word]
        parts = list(word)
        while len(parts) > 1:
            pairs = {(parts[i], parts[i + 1]) for i in range(len(parts) - 1)}
            best = min(
                pairs, key=lambda p: self.ranks.get(p, float("inf"))
            )
            if best not in self.ranks:
                break
            parts = _apply_merge(parts, best)
        if len(self._cache) < 65536:  # bound the per-process cache
            self._cache[word] = parts
        return parts

    def _lookup(self, piece: str) -> int:
        try:
            return self.vocab[piece]
        except KeyError:
            raise VocabMismatchError(
                f"BPE piece {piece!r} is missing from the vocab "
                f"({len(self.vocab)} entries) although the merge list "
                "produced it — vocab.json and merges.txt are almost "
                "certainly from DIFFERENT tokenizers; re-export the pair "
                "together"
            ) from None

    def encode(self, text: str) -> List[int]:
        """Text -> ids. Special tokens appearing IN the text (e.g.
        ``<|endoftext|>`` as a document separator) encode atomically to
        their reserved ids instead of being BPE-split — matching HF
        added-token behavior, so callers other than corpus.py (which
        appends the EOS id directly) get the same stream."""
        ids: List[int] = []
        for chunk, special in self._split_specials(text):
            if special:
                ids.append(self._lookup(chunk))
                continue
            for tok in _PRETOKEN.findall(chunk):
                for piece in self._bpe(_to_byte_chars(tok)):
                    ids.append(self._lookup(piece))
        return ids

    def _split_specials(self, text: str) -> List[Tuple[str, bool]]:
        """Split ``text`` into (chunk, is_special) runs; specials match
        longest-first and never cross BPE pre-tokenization."""
        if self._special_re is None:
            return [(text, False)]
        out: List[Tuple[str, bool]] = []
        pos = 0
        for m in self._special_re.finditer(text):
            if m.start() > pos:
                out.append((text[pos : m.start()], False))
            out.append((m.group(), True))
            pos = m.end()
        if pos < len(text):
            out.append((text[pos:], False))
        return out

    def decode(self, ids: Iterable[int]) -> str:
        chars = "".join(self.inv_vocab[int(i)] for i in ids)
        data = bytes(_BYTE_DEC[c] for c in chars)
        return data.decode("utf-8", errors="replace")

    # -- HF-compatible persistence -----------------------------------------

    def save(self, directory: str) -> None:
        os.makedirs(directory, exist_ok=True)
        with open(os.path.join(directory, "vocab.json"), "w") as f:
            json.dump(self.vocab, f, ensure_ascii=False)
        with open(os.path.join(directory, "merges.txt"), "w") as f:
            f.write("#version: 0.2\n")
            for a, b in self.merges:
                f.write(f"{a} {b}\n")
        # The HF layout has no positional-specials manifest; persist ours
        # so ARBITRARY special shapes (e.g. "[PAD]") survive a save/load
        # round trip with atomic encoding intact. Written even when
        # EMPTY: an explicit [] tells load() "no specials" — otherwise
        # its <|...|>-shape fallback could mint a phantom special out of
        # a vocab piece that merely LOOKS like one (a corpus containing
        # the literal text), silently changing the reloaded id stream.
        with open(os.path.join(directory, "special_tokens.json"), "w") as f:
            json.dump(self.specials, f, ensure_ascii=False)

    @classmethod
    def load(cls, directory: str) -> "BPETokenizer":
        with open(os.path.join(directory, "vocab.json")) as f:
            vocab = json.load(f)
        merges: List[Tuple[str, str]] = []
        with open(os.path.join(directory, "merges.txt")) as f:
            for line in f:
                line = line.rstrip("\n")
                if not line or line.startswith("#"):
                    continue
                a, _, b = line.partition(" ")
                merges.append((a, b))
        # specials: the save()-written manifest when present (possibly
        # an explicit empty list); else recover reserved tokens by their
        # ``<|...|>`` shape (plain HF directories / pre-manifest saves)
        # so a reloaded tokenizer still encodes them atomically
        manifest = os.path.join(directory, "special_tokens.json")
        if os.path.exists(manifest):
            with open(manifest) as f:
                specials = json.load(f)
        else:
            specials = [
                t for t in vocab if t.startswith("<|") and t.endswith("|>")
            ]
        return cls(vocab, merges, specials=specials)


def train_bpe(
    texts: Iterable[str],
    vocab_size: int,
    specials: Sequence[str] = (),
) -> BPETokenizer:
    """Classic BPE training: count pre-tokenized words, then greedily
    merge the most frequent adjacent symbol pair until ``vocab_size`` is
    reached (256 byte-level symbols + specials + merges). Deterministic:
    frequency ties break lexicographically."""
    base = [_BYTE_ENC[b] for b in range(256)]
    n_reserved = len(base) + len(specials)
    if vocab_size < n_reserved:
        raise ValueError(
            f"vocab_size {vocab_size} < {n_reserved} "
            "(256 byte symbols + specials)"
        )
    words: Dict[Tuple[str, ...], int] = {}
    for text in texts:
        for tok in _PRETOKEN.findall(text):
            key = tuple(_to_byte_chars(tok))
            words[key] = words.get(key, 0) + 1

    merges: List[Tuple[str, str]] = []
    vocab_tokens = set(base)
    while len(vocab_tokens) + len(specials) < vocab_size:
        pair_counts: Dict[Tuple[str, str], int] = {}
        for word, cnt in words.items():
            for i in range(len(word) - 1):
                p = (word[i], word[i + 1])
                pair_counts[p] = pair_counts.get(p, 0) + cnt
        if not pair_counts:
            break
        best = max(pair_counts, key=lambda p: (pair_counts[p], p))
        merges.append(best)
        vocab_tokens.add(best[0] + best[1])
        new_words: Dict[Tuple[str, ...], int] = {}
        for word, cnt in words.items():
            key = tuple(_apply_merge(word, best))
            new_words[key] = new_words.get(key, 0) + cnt
        words = new_words

    # id order: specials first (stable ids for PAD/EOS regardless of
    # corpus), then base bytes, then merges in creation order
    vocab: Dict[str, int] = {}
    for s in specials:
        vocab[s] = len(vocab)
    for t in base:
        vocab[t] = len(vocab)
    for a, b in merges:
        tok = a + b
        if tok not in vocab:
            vocab[tok] = len(vocab)
    return BPETokenizer(vocab, merges, specials=specials)
