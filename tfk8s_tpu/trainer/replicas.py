"""Replica materialization: TPUJob -> per-task pods + services — the
``pkg/trainer/replicas.go`` equivalent (SURVEY.md C18).

The reference renders each task a pod whose env carries the hand-built TF
cluster spec (``{cluster:{ps:[...],worker:[...]}, job, task_index}`` —
k8s-operator.md:4,6; SURVEY.md §3.3). The TPU-native contract replaces
TF_CONFIG with JAX distributed-coordination env (SURVEY.md §2 'Distributed
communication backend'):

- ``TFK8S_COORDINATOR_ADDRESS`` — process 0's service address, consumed by
  ``jax.distributed.initialize``;
- ``TFK8S_PROCESS_ID`` / ``TFK8S_NUM_PROCESSES`` — this task's global rank;
- ``TFK8S_MESH`` — the logical mesh axes the data plane builds;
- ``TFK8S_SLICE_ID`` / ``TFK8S_HOST_INDEX`` — placement within the gang
  (multislice jobs see their slice for DCN-aware layouts);
- ``TFK8S_CLUSTER_SPEC`` — full role->endpoints map, kept for API parity
  with the reference's cluster spec.

Placement rides ``node_selector`` (slice + host), written by the gang
allocator's assignment so the scheduler/kubelet puts each process on the
host whose chips it will attach to (SURVEY.md §3.3 device boundary).
"""

from __future__ import annotations

import json
from typing import List

from tfk8s_tpu.api import helpers
from tfk8s_tpu.api.types import (
    ContainerSpec,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodSpec,
    ReplicaType,
    RestartPolicy,
    Service,
    ServicePort,
    ServiceSpec,
    TPUJob,
)
from tfk8s_tpu.trainer import labels as L
from tfk8s_tpu.trainer.gang import GangAssignment
from tfk8s_tpu.utils.topology import GKE_ACCELERATOR

CHECKPOINT_DIR_ANNOTATION = "tfk8s.dev/checkpoint-dir"


def owner_ref(job: TPUJob) -> OwnerReference:
    return OwnerReference(kind=job.kind, name=job.metadata.name, uid=job.metadata.uid)


def coordination_env(
    job: TPUJob, rtype: ReplicaType, index: int, assignment: GangAssignment
) -> dict:
    pid = helpers.process_index(job, rtype, index)
    slice_id, host_index = assignment.host_of(pid)
    env = {
        "TFK8S_JOB_NAME": job.metadata.name,
        "TFK8S_NAMESPACE": job.metadata.namespace,
        "TFK8S_REPLICA_TYPE": rtype.value,
        "TFK8S_REPLICA_INDEX": str(index),
        "TFK8S_PROCESS_ID": str(pid),
        "TFK8S_NUM_PROCESSES": str(helpers.total_replicas(job)),
        "TFK8S_COORDINATOR_ADDRESS": helpers.coordinator_address(job),
        "TFK8S_CLUSTER_SPEC": json.dumps(helpers.cluster_endpoints(job)),
        "TFK8S_ACCELERATOR": job.spec.tpu.accelerator,
        "TFK8S_TOPOLOGY": job.spec.tpu.topology,
        "TFK8S_NUM_SLICES": str(max(job.spec.tpu.num_slices, 1)),
        "TFK8S_SLICE_ID": slice_id,
        "TFK8S_HOST_INDEX": str(host_index),
        # restarts + preemptions: either one means "this incarnation is a
        # re-launch; restore from checkpoint" (launcher resume contract)
        "TFK8S_GANG_RESTARTS": str(
            job.status.gang_restarts + job.status.preemptions
        ),
        # elastic world identity: bumped by the controller on every gang
        # resize, so a relaunched process knows its world was re-formed
        # (launcher resume contract) and stale-world pods are
        # identifiable during the resize drain
        "TFK8S_WORLD_VERSION": str(job.status.world_version),
    }
    if job.spec.mesh is not None:
        env["TFK8S_MESH"] = json.dumps(job.spec.mesh.axes)
    ckpt = job.metadata.annotations.get(CHECKPOINT_DIR_ANNOTATION)
    if ckpt:
        env["TFK8S_CHECKPOINT_DIR"] = ckpt
    return env


def render_pod(
    job: TPUJob, rtype: ReplicaType, index: int, assignment: GangAssignment
) -> Pod:
    rspec = job.spec.replica_specs[rtype]
    name = helpers.replica_name(job.metadata.name, rtype, index)
    pid = helpers.process_index(job, rtype, index)
    slice_id, host_index = assignment.host_of(pid)
    tmpl = rspec.template
    resources = dict(tmpl.resources)
    if job.spec.tpu.provider == "gke":
        # GKE-shaped rendering (north star: replica specs provision TPU VM
        # slices on GKE — the nvidia.com/gpu -> google.com/tpu swap). A
        # real nodepool's nodes carry only the cloud.google.com/* labels,
        # so those are the ONLY selectors (ANDed selectors naming
        # tfk8s.dev/* would leave the pod Pending forever); the gang
        # allocator's placement rides the pod labels instead. Topology
        # info comes from the assignment's SliceHandle — parsed once at
        # admission, not per rendered pod.
        sl = assignment.handle_of(pid)
        info = sl.info
        resources.setdefault("google.com/tpu", str(info.chips_per_host))
        node_selector = {
            "cloud.google.com/gke-tpu-accelerator": GKE_ACCELERATOR[info.generation],
            "cloud.google.com/gke-tpu-topology": "x".join(
                str(d) for d in info.topology
            ),
        }
    else:
        # Node labels name PHYSICAL properties: a carved sub-slice's pods
        # must select the parent slice's accelerator type, id, and the
        # box-offset host index — real nodes are labeled with what they
        # ARE, not what the job asked for (two jobs carved from one
        # v5p-32 land on disjoint physical hosts of that v5p-32).
        sl = assignment.handle_of(pid)
        if sl.physical is not None:
            phys_acc, phys_slice = sl.physical.info.accelerator, sl.physical.slice_id
        else:
            phys_acc, phys_slice = job.spec.tpu.accelerator, slice_id
        node_selector = {
            "tfk8s.dev/accelerator": phys_acc,
            "tfk8s.dev/slice": phys_slice,
            "tfk8s.dev/host": str(assignment.global_host_of(pid)),
        }
    container = ContainerSpec(
        entrypoint=tmpl.entrypoint,
        image=tmpl.image,
        command=list(tmpl.command),
        args=list(tmpl.args),
        env={**tmpl.env, **coordination_env(job, rtype, index, assignment)},
        resources=resources,
    )
    lbls = L.replica_labels(job.metadata.name, rtype, index)
    lbls[L.SLICE_ID] = slice_id
    lbls[L.HOST_INDEX] = str(host_index)
    return Pod(
        metadata=ObjectMeta(
            name=name,
            namespace=job.metadata.namespace,
            labels=lbls,
            owner_references=[owner_ref(job)],
        ),
        spec=PodSpec(
            containers=[container],
            restart_policy=rspec.restart_policy or RestartPolicy.ON_FAILURE,
            node_selector=node_selector,
        ),
    )


def render_service(job: TPUJob, rtype: ReplicaType, index: int) -> Service:
    """Per-task service providing the stable DNS name used in
    cluster_endpoints (SURVEY.md §3.3: each task addressable by name)."""
    name = helpers.replica_name(job.metadata.name, rtype, index)
    return Service(
        metadata=ObjectMeta(
            name=name,
            namespace=job.metadata.namespace,
            labels=L.replica_labels(job.metadata.name, rtype, index),
            owner_references=[owner_ref(job)],
        ),
        spec=ServiceSpec(
            selector=L.replica_labels(job.metadata.name, rtype, index),
            ports=[ServicePort(name="coord", port=helpers.DEFAULT_PORT)],
        ),
    )


def render_all(job: TPUJob, assignment: GangAssignment) -> tuple:
    """Every pod + service of the gang, in process-id order."""
    pods: List[Pod] = []
    services: List[Service] = []
    for rtype in helpers.sorted_replica_types(job):
        for i in range(job.spec.replica_specs[rtype].replicas or 0):
            pods.append(render_pod(job, rtype, i, assignment))
            services.append(render_service(job, rtype, i))
    return pods, services
